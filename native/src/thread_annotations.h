// Clang thread-safety capability macros + annotated mutex/condvar wrappers.
//
// Every mutex-guarded field in the native layer is annotated GUARDED_BY its
// mutex and every lock acquisition goes through these wrappers, so
//
//     clang++ -Wthread-safety -Werror   (make -C native tsa-check)
//
// machine-checks the lock discipline the comments used to carry alone: a
// field read without its mutex, a lock released twice, or a *_locked()
// helper called without the lock is a compile ERROR, not a review hope.
// Under g++ (which has no thread-safety analysis) the macros expand to
// nothing and the wrappers are zero-cost shims over std::mutex /
// std::condition_variable — identical codegen, no behavior change.
//
// Deliberately NOT annotatable (documented at the field instead):
// dual-protocol state whose readers hold one of TWO mutexes (e.g. the ring
// sockets in HostCollectives: identity changes hold cfg_mu_ AND op_mu_,
// readers hold either) and cross-thread handoffs synchronized by a
// condvar-generation protocol rather than a single capability (the stripe
// pool's job body). Clang's analysis models exactly one capability per
// field; forcing those under one mutex would make the annotations lie.
#pragma once

#include <chrono>
#include <condition_variable>
#include <mutex>

// Capability attributes exist only under clang; __has_attribute keeps the
// header honest if a future clang renames one.
#if defined(__clang__) && defined(__has_attribute)
#if __has_attribute(guarded_by)
#define TFT_TSA(x) __attribute__((x))
#endif
#endif
#ifndef TFT_TSA
#define TFT_TSA(x)  // no-op outside clang
#endif

#define TFT_CAPABILITY(x) TFT_TSA(capability(x))
#define TFT_SCOPED_CAPABILITY TFT_TSA(scoped_lockable)
#define TFT_GUARDED_BY(x) TFT_TSA(guarded_by(x))
#define TFT_PT_GUARDED_BY(x) TFT_TSA(pt_guarded_by(x))
#define TFT_REQUIRES(...) TFT_TSA(requires_capability(__VA_ARGS__))
#define TFT_ACQUIRE(...) TFT_TSA(acquire_capability(__VA_ARGS__))
#define TFT_RELEASE(...) TFT_TSA(release_capability(__VA_ARGS__))
#define TFT_TRY_ACQUIRE(...) TFT_TSA(try_acquire_capability(__VA_ARGS__))
#define TFT_EXCLUDES(...) TFT_TSA(locks_excluded(__VA_ARGS__))
#define TFT_NO_TSA TFT_TSA(no_thread_safety_analysis)

namespace tft {

// std::mutex with the capability attribute (std::mutex itself cannot carry
// one under libstdc++). native() exists only for the condvar wrapper.
class TFT_CAPABILITY("mutex") Mutex {
 public:
  Mutex() = default;
  Mutex(const Mutex&) = delete;
  Mutex& operator=(const Mutex&) = delete;

  void lock() TFT_ACQUIRE() { mu_.lock(); }
  void unlock() TFT_RELEASE() { mu_.unlock(); }
  bool try_lock() TFT_TRY_ACQUIRE(true) { return mu_.try_lock(); }

  std::mutex& native() { return mu_; }

 private:
  std::mutex mu_;
};

// std::lock_guard role: holds from construction to scope exit.
class TFT_SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& mu) TFT_ACQUIRE(mu) : mu_(mu) { mu_.lock(); }
  ~MutexLock() TFT_RELEASE() { mu_.unlock(); }
  MutexLock(const MutexLock&) = delete;
  MutexLock& operator=(const MutexLock&) = delete;

 private:
  Mutex& mu_;
};

// std::unique_lock role: condvar-compatible and early-releasable (the
// long-poll handlers unlock before writing their response to the socket).
// Clang models the scoped capability's held/released state, so an early
// unlock() followed by the destructor does not double-release.
class TFT_SCOPED_CAPABILITY UniqueMutexLock {
 public:
  explicit UniqueMutexLock(Mutex& mu) TFT_ACQUIRE(mu) : lk_(mu.native()) {}
  ~UniqueMutexLock() TFT_RELEASE() {}
  UniqueMutexLock(const UniqueMutexLock&) = delete;
  UniqueMutexLock& operator=(const UniqueMutexLock&) = delete;

  void unlock() TFT_RELEASE() { lk_.unlock(); }
  // Re-acquire after an explicit unlock() (e.g. releasing the state lock
  // across a slow RPC). Guarded state must be revalidated afterwards —
  // the manager's quorum-generation check is the canonical pattern.
  void lock() TFT_ACQUIRE() { lk_.lock(); }

  // For CondVar only: waiting temporarily releases and reacquires the
  // native lock, which the analysis (correctly) treats as held across the
  // wait — guarded state must be revalidated after every wake, which is
  // what the explicit while-loops around every wait below already do.
  std::unique_lock<std::mutex>& native() { return lk_; }

 private:
  std::unique_lock<std::mutex> lk_;
};

// std::condition_variable over UniqueMutexLock. No predicate overloads on
// purpose: clang's analysis cannot see capabilities inside a lambda passed
// as a wait predicate, so all call sites spell the while-loop out — which
// keeps the guarded reads in the annotated function body.
class CondVar {
 public:
  CondVar() = default;
  CondVar(const CondVar&) = delete;
  CondVar& operator=(const CondVar&) = delete;

  void notify_one() { cv_.notify_one(); }
  void notify_all() { cv_.notify_all(); }

  void wait(UniqueMutexLock& lk) { cv_.wait(lk.native()); }

  template <class Rep, class Period>
  std::cv_status wait_for(UniqueMutexLock& lk,
                          const std::chrono::duration<Rep, Period>& d) {
#if defined(__SANITIZE_THREAD__)
    // gcc's libtsan (through at least gcc 12) does not intercept
    // pthread_cond_clockwait, which libstdc++'s steady-clock wait_for
    // lowers to on glibc >= 2.30. TSan then misses the mutex
    // release/reacquire inside every timed wait and reports phantom
    // double-locks plus cascading false races for each long-poll server
    // thread. Under TSan only, route through a system_clock wait_until,
    // which lowers to the intercepted pthread_cond_timedwait. The timed
    // wait here is a wake HINT (every caller loops rechecking its
    // deadline against the steady now_ms()), so the wall-clock
    // sensitivity is harmless; production builds keep the
    // jump-immune steady-clock path.
    return cv_.wait_until(lk.native(), std::chrono::system_clock::now() + d);
#else
    return cv_.wait_for(lk.native(), d);
#endif
  }

 private:
  std::condition_variable cv_;
};

}  // namespace tft
