"""Lighthouse CLI: ``python -m torchft_tpu.lighthouse``.

The standalone global quorum service, the role of the reference's
``torchft_lighthouse`` entrypoint (reference pyproject.toml:37-38,
src/bin/lighthouse.rs:10-23). Defaults mirror the reference CLI
(src/lighthouse.rs:66-103).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
from typing import Optional, Sequence

from . import _native

logger = logging.getLogger(__name__)


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        prog="torchft_tpu.lighthouse",
        description="Global quorum service for torchft_tpu replica groups.",
    )
    parser.add_argument("--bind", default="[::]:29510")
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument("--join_timeout_ms", type=int, default=60000)
    parser.add_argument("--quorum_tick_ms", type=int, default=100)
    parser.add_argument("--heartbeat_timeout_ms", type=int, default=5000)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    lighthouse = _native.Lighthouse(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
    )
    logger.info(f"lighthouse serving on {lighthouse.address()}")

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    lighthouse.shutdown()


if __name__ == "__main__":
    main()
