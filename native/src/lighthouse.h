// Global quorum service. One per job; replica-group managers heartbeat (or
// batch-renew leases) into it and long-poll Quorum requests against it. Also
// the ROOT of the hierarchical tier: region lighthouses push membership
// digests into it and long-poll the global quorum back out. Serves an HTML
// dashboard plus a JSON status view on the same port (HTTP requests are
// sniffed apart from protocol frames). Reference: src/lighthouse.rs.
#pragma once

#include <atomic>
#include <map>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.h"
#include "net.h"
#include "quorum.h"
#include "thread_annotations.h"

namespace tft {

class Lighthouse {
 public:
  Lighthouse(const std::string& bind_addr, const LighthouseOpt& opt);
  ~Lighthouse();

  // "http://host:port" (dashboard is literally served over HTTP here).
  std::string address() const;
  uint16_t port() const;
  void shutdown();

  // Machine-readable status (the /status.json payload): members + lease
  // deadlines, last quorum, tier role, tick cost counters, region digests.
  std::string status_json();

 private:
  void accept_loop();
  void tick_loop();
  void handle_conn(Socket& sock);
  void handle_http(Socket& sock, const std::string& head);
  void handle_quorum_req(Socket& sock, const std::string& payload);
  void handle_lease_renew(Socket& sock, const std::string& payload);
  void handle_depart(Socket& sock, const std::string& payload);
  void handle_region_digest(Socket& sock, const std::string& payload);
  void handle_region_poll(Socket& sock, const std::string& payload);

  // Runs one quorum check; called with mu_ held. On success publishes the new
  // quorum (bumping quorum_id only when membership changed) and wakes waiters.
  void quorum_tick_locked() TFT_REQUIRES(mu_);

  std::string render_status_locked() TFT_REQUIRES(mu_);
  Json status_json_locked() TFT_REQUIRES(mu_);

  LighthouseOpt opt_;
  std::unique_ptr<Listener> listener_;
  std::string hostname_;

  Mutex mu_;
  CondVar quorum_cv_;
  LighthouseState state_ TFT_GUARDED_BY(mu_);
  // Broadcast channel equivalent: monotone generation + latest value.
  int64_t quorum_gen_ TFT_GUARDED_BY(mu_) = 0;
  torchft_tpu::Quorum latest_quorum_ TFT_GUARDED_BY(mu_);

  // Region tier bookkeeping (status only; liveness rides the groups' own
  // forwarded leases, so a region's death needs no root-side timeout).
  struct RegionInfo {
    int64_t last_digest_ms = 0;
    int64_t entries = 0;
  };
  std::map<std::string, RegionInfo> regions_ TFT_GUARDED_BY(mu_);

  // Tick cost counters ("root CPU per tick" in LIGHTHOUSE_BENCH). Idle
  // ticks — no registered participant, so no quorum can possibly form —
  // skip the O(groups) membership scan entirely; that is the lease-based
  // replacement for the unconditional per-tick recompute.
  int64_t ticks_total_ TFT_GUARDED_BY(mu_) = 0;
  int64_t ticks_computed_ TFT_GUARDED_BY(mu_) = 0;
  int64_t last_compute_us_ TFT_GUARDED_BY(mu_) = 0;
  int64_t total_compute_us_ TFT_GUARDED_BY(mu_) = 0;

  std::atomic<bool> shutting_down_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;
  ConnTracker conns_;
};

} // namespace tft
