"""Model: serving install ladder (nonce-pinned, CRC-guarded fetch).

Protocol core being modeled (torchft_tpu/serving.py):

- The publisher serves versioned weight payloads split into ranges.  A
  republish (new version, or the same version after a publisher restart)
  carries a *fresh nonce* and overwrites the served ranges one at a time
  -- there is a torn window where some ranges hold new bytes and some
  hold old.
- A subscriber session reads the meta (version, nonce), then fetches
  every range with the request pinned to that nonce.  The ladder of
  gates: the server answers a stale-nonce request with a hard 400; every
  range is CRC-checked on receipt; the final assembled payload's digest
  must match the manifest before the new version is swapped in.  Any
  gate failure aborts the session (a detection, never an install).

Fault actions: republish mid-fetch, publisher restart (same version,
fresh nonce), bit-flip of a served range.

Properties:

- ``no_torn_install``   -- an installed version is complete, all bytes
  from exactly one (version, nonce) publication, uncorrupted.
- ``version_monotonic`` -- the subscriber's installed version never
  moves backward.

Broken variant ``no_integrity`` turns off the ladder (no stale-nonce
400, no range CRC, no final digest): a republish racing the fetch
installs a torn mix of two publications, and a bit-flip installs
corrupted bytes.
"""

from __future__ import annotations

from .core import Model

NRANGES = 2


class ServingModel(Model):
    name = "serving"
    properties = ("no_torn_install", "version_monotonic")

    def __init__(
        self,
        max_versions: int = 3,
        republishes: int = 2,
        restarts: int = 1,
        flips: int = 1,
        no_integrity: bool = False,
    ):
        self.max_versions = max_versions
        self.faults0 = (republishes, restarts, flips)
        self.no_integrity = bool(no_integrity)
        if no_integrity:
            self.name = "serving_no_integrity"

    def budget(self) -> dict:
        return {"max_depth": 64, "max_states": 400_000}

    # State:
    #   pub     : (version, nonce, written_mask) -- the publication being
    #             written; meta flips to it once the mask is full
    #   meta    : (version, nonce) the subscriber would read
    #   store   : per-range (version, nonce, corrupt) of the served bytes
    #   sub     : (installed_version, session); session is () or
    #             (version, nonce, fetched) with per-range fetched tags
    #             ((version, nonce, corrupt) | None)
    #   flags   : (torn_install, version_regressed)
    #   faults  : (republishes, restarts, flips) remaining
    def initial(self):
        pub = (1, 1, (1 << NRANGES) - 1)
        store = tuple((1, 1, 0) for _ in range(NRANGES))
        return (pub, (1, 1), store, (0, ()), (0, 0), self.faults0)

    def check(self, state):
        flags = state[4]
        out = []
        if flags[0]:
            out.append("no_torn_install")
        if flags[1]:
            out.append("version_monotonic")
        return out

    def actions(self, state):
        pub, meta, store, sub, flags, faults = state
        republishes, restarts, flips = faults
        pv, pn, mask = pub
        installed, session = sub
        acts = []
        full = (1 << NRANGES) - 1

        # Publisher writes the pending ranges of the current publication.
        if mask != full:
            for r in range(NRANGES):
                if not (mask & (1 << r)):
                    nstore = _set(store, r, (pv, pn, 0))
                    nmask = mask | (1 << r)
                    npub = (pv, pn, nmask)
                    nmeta = (pv, pn) if nmask == full else meta
                    acts.append(
                        ("pub_range%d_v%d_n%d" % (r, pv, pn),
                         (npub, nmeta, nstore, sub, flags, faults))
                    )
        else:
            if republishes > 0 and pv < self.max_versions:
                # New version, fresh nonce; ranges rewritten one by one.
                acts.append(
                    ("republish_v%d_n%d" % (pv + 1, pn + 1),
                     ((pv + 1, pn + 1, 0), meta, store, sub, flags,
                      (republishes - 1, restarts, flips)))
                )
            if restarts > 0:
                # Publisher restart: same version republished under a
                # fresh nonce (the torn-republish guard's reason to exist).
                acts.append(
                    ("restart_v%d_n%d" % (pv, pn + 1),
                     ((pv, pn + 1, 0), meta, store, sub, flags,
                      (republishes, restarts - 1, flips)))
                )

        # Bit-flip of a served range.
        if flips > 0:
            for r in range(NRANGES):
                rv, rn, _c = store[r]
                acts.append(
                    ("flip_range%d" % r,
                     (pub, meta, _set(store, r, (rv, rn, 1)), sub, flags,
                      (republishes, restarts, flips - 1)))
                )

        # Subscriber: open a session against the current meta.
        if not session:
            mv, mn = meta
            if mv >= installed:
                acts.append(
                    ("sub_meta_v%d_n%d" % (mv, mn),
                     (pub, meta, store,
                      (installed, (mv, mn, (None,) * NRANGES)),
                      flags, faults))
                )
        else:
            sv, sn, fetched = session
            for r in range(NRANGES):
                if fetched[r] is not None:
                    continue
                if pn != sn and not self.no_integrity:
                    # Server-side stale-nonce 400: the session dies.
                    acts.append(
                        ("fetch%d_nonce400" % r,
                         (pub, meta, store, (installed, ()), flags, faults))
                    )
                    continue
                tag = store[r]
                if tag[2] and not self.no_integrity:
                    # Per-range CRC detection: the session dies.
                    acts.append(
                        ("fetch%d_crc" % r,
                         (pub, meta, store, (installed, ()), flags, faults))
                    )
                    continue
                nf = _set(fetched, r, tag)
                acts.append(
                    ("fetch%d_v%d_n%d%s" % (r, tag[0], tag[1],
                                            "_bad" if tag[2] else ""),
                     (pub, meta, store, (installed, (sv, sn, nf)), flags,
                      faults))
                )
            if all(f is not None for f in fetched):
                ok = all(f == (sv, sn, 0) for f in fetched)
                if ok or self.no_integrity:
                    torn = flags[0] or (0 if ok else 1)
                    regress = flags[1] or (1 if sv < installed else 0)
                    acts.append(
                        ("install_v%d_n%d" % (sv, sn),
                         (pub, meta, store, (sv, ()), (torn, regress),
                          faults))
                    )
                else:
                    # Final digest-vs-manifest gate: detection, no swap.
                    acts.append(
                        ("install_digest_abort",
                         (pub, meta, store, (installed, ()), flags, faults))
                    )

        return acts


def _set(t, i, v):
    return t[:i] + (v,) + t[i + 1:]


def make(broken: str = "") -> Model:
    if broken == "no_integrity":
        return ServingModel(no_integrity=True)
    if broken:
        raise ValueError("serving: unknown broken variant %r" % broken)
    return ServingModel()


BROKEN = ("no_integrity",)
