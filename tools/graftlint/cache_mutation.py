"""Plan/cache state is only mutated inside its invalidation entry points.

``HostCollectives._plans`` caches native plan ids whose layouts bake in
the ring geometry; the native side drops every plan on configure(), so the
Python cache MUST be rebuilt/invalidated only at the documented points —
a mutation anywhere else desynchronizes the two sides (a stale Python
handle would execute a freed or rebuilt native plan). The rule finds every
mutation of the attribute (assignment, subscript store/delete, mutating
method call) and requires its enclosing method to be on the allowlist.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from . import Violation

RULE = "cache_mutation"

# (file, attribute) -> methods allowed to mutate it. _plan_for and
# _sharded_plan_for are the build-and-memoize entries (fused and
# reduce-scatter/allgather plans share one cache and one invalidation
# discipline); configure is the invalidation entry.
DEFAULT_TARGETS: Dict[Tuple[str, str], Sequence[str]] = {
    ("torchft_tpu/collectives.py", "_plans"): (
        "__init__",
        "configure",
        "_plan_for",
        "_sharded_plan_for",
    ),
}

_MUTATORS = {"clear", "pop", "popitem", "update", "setdefault"}


def _is_self_attr(node: ast.AST, attr: str) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _mutations(tree: ast.Module, attr: str) -> List[Tuple[int, str]]:
    """(line, kind) of every mutation of self.<attr> in the module."""
    out: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in targets:
                if _is_self_attr(tgt, attr):
                    out.append((node.lineno, "rebound"))
                elif isinstance(tgt, ast.Subscript) and _is_self_attr(
                    tgt.value, attr
                ):
                    out.append((node.lineno, "item-assigned"))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and _is_self_attr(
                    tgt.value, attr
                ):
                    out.append((node.lineno, "item-deleted"))
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATORS
                and _is_self_attr(f.value, attr)
            ):
                out.append((node.lineno, f".{f.attr}()"))
    return out


def _method_spans(tree: ast.Module) -> List[Tuple[int, int, str]]:
    """(start, end, qualified method name) for every top-level method of
    every class; nested defs inherit the enclosing method's name."""
    spans = []
    for cls in tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                end = max(
                    getattr(n, "end_lineno", fn.lineno)
                    for n in ast.walk(fn)
                )
                spans.append((fn.lineno, end, fn.name))
    return spans


def check(
    root: Path,
    targets: Optional[Dict[Tuple[str, str], Sequence[str]]] = None,
) -> List[Violation]:
    out: List[Violation] = []
    for (rel, attr), allowed in (targets or DEFAULT_TARGETS).items():
        path = root / rel
        tree = ast.parse(path.read_text())
        spans = _method_spans(tree)
        for line, kind in _mutations(tree, attr):
            method = next(
                (
                    name
                    for start, end, name in spans
                    if start <= line <= end
                ),
                "<module>",
            )
            if method not in allowed:
                out.append(
                    Violation(
                        RULE,
                        rel,
                        line,
                        f"self.{attr} {kind} in {method}(); plan/cache "
                        "state may only change in "
                        f"{'/'.join(allowed)} (native plans drop on "
                        "configure — anything else desyncs the bridge)",
                    )
                )
    return out
