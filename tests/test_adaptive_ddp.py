"""AdaptiveDDP tests: the probe/decision discipline.

The contract VERDICT item 8 demanded: pipelined DDP can never again lose
to blocking, because blocking is always a probed candidate and the
cohort-agreed decision is the argmin with ties resolving to blocking.
"""

from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.collectives import DummyCollectives
from torchft_tpu.ddp import AdaptiveDDP, PipelinedDDP
from torchft_tpu.train_state import FTTrainState


def _grad_fn(params, x):
    import jax
    import jax.numpy as jnp

    def loss(p):
        return jnp.mean((x @ p["w"]) ** 2)

    value, grads = jax.value_and_grad(loss)(params)
    return value, grads


def _state():
    import jax.numpy as jnp
    import optax

    params = {"w": jnp.ones((8, 8), jnp.float32)}
    return FTTrainState(params, optax.sgd(0.1))


class _FakeManager:
    """Just enough Manager surface for the decision-rule unit tests."""

    def __init__(self, cohort_timings):
        # cohort_timings: list (one per member) of per-candidate medians
        self._cohort = cohort_timings
        self._metrics_records = {}
        self._qid = 7

    def allgather(self, tree):
        from torchft_tpu.collectives import _completed

        return _completed(
            [{"probe_t": np.asarray(t, np.float64)} for t in self._cohort]
        )

    def quorum_id(self):
        return self._qid

    def errored(self):
        return None

    def metrics(self):
        class M:
            def __init__(self, store):
                self._s = store

            def record(self, name, s):
                self._s[name] = s

            def incr(self, name, by=1):
                self._s[name] = self._s.get(name, 0) + by

        return M(self._metrics_records)


def _adaptive_with_fake(cohort):
    ddp = AdaptiveDDP.__new__(AdaptiveDDP)
    ddp._manager = _FakeManager(cohort)
    ddp._candidates = list(AdaptiveDDP._CANDIDATES)
    ddp._probe_t = [[t] for t in cohort[0]]
    ddp._auto = True
    ddp._mode = None
    ddp._probe_qid = 7
    ddp._probe_idx = 6
    ddp._decision_qid = None
    ddp.decision = None
    return ddp


class TestDecisionRule:
    def test_picks_cohort_fastest(self):
        # member 0 prefers plan, member 1 prefers plan more strongly
        ddp = _adaptive_with_fake(
            [[0.10, 0.08, 0.09], [0.10, 0.05, 0.09]]
        )
        ddp._decide()
        assert ddp.mode == "plan"
        assert ddp.decision["mode"] == "plan"

    def test_never_slower_than_blocking(self):
        # every alternative measures worse somewhere: blocking wins
        ddp = _adaptive_with_fake(
            [[0.10, 0.12, 0.11], [0.10, 0.09, 0.15]]
        )
        ddp._decide()
        assert ddp.mode == "blocking"

    def test_tie_resolves_to_blocking(self):
        ddp = _adaptive_with_fake([[0.10, 0.10, 0.10]])
        ddp._decide()
        assert ddp.mode == "blocking"

    def test_decision_is_deterministic_across_members(self):
        # identical gathered data -> identical argmin on every member
        cohort = [[0.3, 0.2, 0.25], [0.31, 0.22, 0.24]]
        modes = set()
        for _ in range(2):
            ddp = _adaptive_with_fake(cohort)
            ddp._decide()
            modes.add(ddp.mode)
        assert modes == {"plan"}

    def test_failed_candidate_cannot_win(self):
        # A candidate that errored on ANY member carries the failure
        # sentinel through the gather: even if it measured fastest
        # elsewhere, it can never rank above a working candidate.
        s = AdaptiveDDP._PROBE_FAILED_S
        ddp = _adaptive_with_fake([[0.5, s, 0.4], [0.5, 0.001, 0.4]])
        ddp._decide()
        assert ddp.mode == "pipelined"

    def test_all_failed_falls_back_to_blocking(self):
        s = AdaptiveDDP._PROBE_FAILED_S
        ddp = _adaptive_with_fake([[s, s, s]])
        ddp._decide()
        assert ddp.mode == "blocking"

    def test_errored_gather_locks_blocking(self):
        # When the decision allgather itself failed, this member's data
        # is local-only and any argmin could disagree with the cohort:
        # lock the safe default (a mismatch self-heals via the
        # quorum-change re-probe).
        ddp = _adaptive_with_fake([[0.5, 0.1, 0.2]])
        ddp._manager.errored = lambda: RuntimeError("gather failed")
        ddp._decide()
        assert ddp.mode == "blocking"


class TestConstruction:
    def test_env_mode_pins_without_probe(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_DDP_MODE", "blocking")
        ddp = AdaptiveDDP(
            _ManagerStub(), _state(), _grad_fn
        )
        assert ddp.mode == "blocking"  # locked, no probe phase

    def test_bad_env_mode_rejected(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_DDP_MODE", "warp")
        with pytest.raises(ValueError, match="TORCHFT_DDP_MODE"):
            AdaptiveDDP(_ManagerStub(), _state(), _grad_fn)

    def test_int8_drops_plan_candidate(self):
        ddp = AdaptiveDDP(
            _ManagerStub(), _state(), _grad_fn, compress="int8", mode="auto"
        )
        assert "plan" not in ddp._candidates
        with pytest.raises(ValueError, match="plan"):
            AdaptiveDDP(
                _ManagerStub(), _state(), _grad_fn, compress="int8",
                mode="plan",
            )

    def test_pipelined_rejects_plan_with_int8(self):
        with pytest.raises(ValueError, match="allgather"):
            PipelinedDDP(
                _ManagerStub(), _state(), _grad_fn, compress="int8",
                transport="plan",
            )


class _ManagerStub:
    """Constructor-only stand-in (never stepped)."""


class TestEndToEnd:
    def _manager(self):
        from torchft_tpu import Lighthouse
        from torchft_tpu._native import Store
        from torchft_tpu.collectives import HostCollectives
        from torchft_tpu.manager import Manager

        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = Store()
        manager = Manager(
            collectives=HostCollectives(timeout=timedelta(seconds=10)),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            timeout=timedelta(seconds=10),
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="adaptive_e2e",
        )
        return manager, store, lighthouse

    def test_probe_locks_and_training_progresses(self):
        import jax.numpy as jnp

        manager, store, lighthouse = self._manager()
        state = _state()
        # device_pack="off" pins the classic 3-candidate probe (the
        # devpack candidate has its own suite, test_device_pack.py)
        ddp = AdaptiveDDP(
            manager, state, _grad_fn, probe_steps=2, device_pack="off"
        )
        x = jnp.ones((4, 8), jnp.float32)
        try:
            assert ddp.mode is None  # probing
            for _ in range(8):
                loss = ddp.step(x)
            ddp.flush()
            assert ddp.mode in ("blocking", "plan", "pipelined")
            assert ddp.decision["mode"] == ddp.mode
            assert set(ddp.decision["probe_s"]) == {
                "blocking", "plan", "pipelined"
            }
            assert np.isfinite(float(loss))
            assert manager.current_step() == 8
            counters = manager.metrics().snapshot()["counters"]
            assert counters.get(f"ddp_mode_{ddp.mode}") == 1
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_backend_without_plans_never_locks_plan(self):
        # A backend whose plan_allreduce raises (base Collectives
        # default) makes every plan probe step error: the managed latch
        # resolves instantly, the step is discarded, and its
        # meaninglessly-fast wall time must NOT let "plan" win — the
        # failure sentinel keeps it out, and the probe must still
        # terminate (an attempted-step clock; a committed-step clock
        # would stall forever on the never-committing candidate).
        import jax.numpy as jnp

        from torchft_tpu.collectives import Collectives

        class NoPlans(DummyCollectives):
            plan_allreduce = Collectives.plan_allreduce  # raises

        from torchft_tpu import Lighthouse
        from torchft_tpu._native import Store
        from torchft_tpu.manager import Manager

        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = Store()
        manager = Manager(
            collectives=NoPlans(world_size=1),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            timeout=timedelta(seconds=10),
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="noplan_e2e",
        )
        try:
            state = _state()
            ddp = AdaptiveDDP(manager, state, _grad_fn, probe_steps=2)
            x = jnp.ones((4, 8), jnp.float32)
            for _ in range(10):
                ddp.step(x)
            ddp.flush()
            assert ddp.mode is not None, "probe must terminate"
            assert ddp.mode != "plan", (
                "a candidate whose every step errors must never win"
            )
            assert ddp.decision["probe_s"]["plan"] >= 1e8
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_plan_transport_trains_equivalently(self):
        # PipelinedDDP(transport="plan") on a solo manager must produce
        # the same committed parameters as the legacy transport: solo
        # AVG is identity, so both settle to identical SGD trajectories.
        import jax
        import jax.numpy as jnp

        manager, store, lighthouse = self._manager()
        try:
            x = jnp.ones((4, 8), jnp.float32)
            results = {}
            for transport in ("legacy", "plan"):
                state = _state()
                ddp = PipelinedDDP(
                    manager, state, _grad_fn, transport=transport
                )
                for _ in range(3):
                    ddp.step(x)
                ddp.flush()
                results[transport] = np.asarray(
                    jax.tree_util.tree_leaves(state.params)[0]
                )
            np.testing.assert_array_equal(
                results["legacy"], results["plan"]
            )
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()


class TestReprobeOnQuorumChange:
    def test_quorum_move_restarts_probe(self):
        # Drive AdaptiveDDP against a scripted manager: after lock-in, a
        # quorum_id change at step N must unlock and restart the probe
        # schedule, with the probe clock re-anchored at the cohort's
        # committed-step count (the quorum synchronizes it, so every
        # member restarts at the same origin).
        import jax.numpy as jnp

        class ScriptedManager:
            def __init__(self):
                self.collectives = DummyCollectives()
                self.qid = 1
                self.committed = 0
                self._m = _FakeManager([[0.0, 0.0, 0.0]])

            def start_quorum(self, **kw):
                pass

            def quorum_id(self):
                return self.qid

            def current_step(self):
                return self.committed

            def errored(self):
                return None

            def plan_allreduce(self, tree, op=None, wire=None,
                               device_pack=None):
                from torchft_tpu.collectives import _completed

                return _completed(tree)

            def allreduce(self, tree, op=None, wire=None):
                from torchft_tpu.collectives import _completed

                return _completed(tree)

            def allgather(self, tree):
                from torchft_tpu.collectives import _completed

                return _completed([tree])

            def should_commit(self, **kw):
                self.committed += 1
                return True

            def is_healing(self):
                return False

            def metrics(self):
                return self._m.metrics()

            def reset_plan_feedback(self):
                pass

        mgr = ScriptedManager()
        state = _state()
        ddp = AdaptiveDDP(
            mgr, state, _grad_fn, probe_steps=2, device_pack="off"
        )
        x = jnp.ones((4, 8), jnp.float32)
        # step 1 anchors the probe clock (first quorum-id observation,
        # untimed); 3 candidates x 2 steps follow
        for _ in range(7):
            ddp.step(x)
        assert ddp.mode is not None
        locked = ddp.mode
        ddp.step(x)  # steady state
        assert ddp.mode == locked
        mgr.qid = 2  # membership moves
        ddp.step(x)  # observes the new id at this step's end
        assert ddp.mode is None  # probing again, in lockstep
        for _ in range(6):  # clock already anchored by the restart
            ddp.step(x)
        assert ddp.mode is not None
        ddp.flush()


class TestProbeRefresh:
    """TORCHFT_DDP_REPROBE_STEPS: a locked schedule revalidates on a fixed
    attempted-step cadence, not only on membership changes — closing the
    stale-lock gap where a cohort's bandwidth moves but its quorum doesn't."""

    def test_locked_mode_reprobes_on_cadence(self):
        import jax.numpy as jnp

        from torchft_tpu.collectives import _completed

        class ScriptedManager:
            def __init__(self):
                self.qid = 1
                self.committed = 0
                self._m = _FakeManager([[0.0, 0.0, 0.0]])

            def start_quorum(self, **kw):
                pass

            def quorum_id(self):
                return self.qid

            def current_step(self):
                return self.committed

            def errored(self):
                return None

            def plan_allreduce(self, tree, op=None, wire=None,
                               device_pack=None):
                return _completed(tree)

            def allreduce(self, tree, op=None, wire=None):
                return _completed(tree)

            def allgather(self, tree):
                return _completed([tree])

            def should_commit(self, **kw):
                self.committed += 1
                return True

            def is_healing(self):
                return False

            def metrics(self):
                return self._m.metrics()

            def reset_plan_feedback(self):
                pass

        mgr = ScriptedManager()
        state = _state()
        ddp = AdaptiveDDP(
            mgr, state, _grad_fn, probe_steps=2, device_pack="off",
            reprobe_steps=4,
        )
        x = jnp.ones((4, 8), jnp.float32)
        # anchor + 3 candidates x 2 steps -> locks
        for _ in range(7):
            ddp.step(x)
        assert ddp.mode is not None
        first_decision_metrics = dict(ddp._manager._m._metrics_records)
        # 3 locked steps: still locked (cadence is 4)
        for _ in range(3):
            ddp.step(x)
        assert ddp.mode is not None
        # 4th locked step trips the refresh: probing again, same quorum
        ddp.step(x)
        assert ddp.mode is None
        assert ddp._manager._m._metrics_records.get("ddp_reprobe") == 1
        # and the refreshed probe terminates in a new lock
        for _ in range(6):
            ddp.step(x)
        assert ddp.mode is not None
        ddp.flush()
        assert first_decision_metrics  # decision metrics were recorded

    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_DDP_REPROBE_STEPS", raising=False)
        ddp = AdaptiveDDP(_ManagerStub(), _state(), _grad_fn, mode="blocking")
        assert ddp._reprobe_steps == 0

    def test_env_knob_sets_cadence(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_DDP_REPROBE_STEPS", "128")
        ddp = AdaptiveDDP(_ManagerStub(), _state(), _grad_fn, mode="blocking")
        assert ddp._reprobe_steps == 128


class TestPlanHierCandidate:
    """The topology-aware candidate: joins the race only on region-labeled
    members (construction-time, cohort-uniform like every schedule knob),
    and on a cohort that cannot run the two-tier schedule every one of its
    probe steps records the failure sentinel — it can never win, and
    nothing crashes."""

    def _scripted(self, region="", hier_works=True):
        from torchft_tpu.collectives import _completed

        class ScriptedManager:
            _region = region

            def __init__(self):
                self.qid = 1
                self.committed = 0
                self.hier_dispatches = 0
                self._fail_commit = False
                self._m = _FakeManager([[0.0] * 6])

            def start_quorum(self, **kw):
                pass

            def quorum_id(self):
                return self.qid

            def current_step(self):
                return self.committed

            def errored(self):
                return None

            def plan_allreduce(self, tree, op=None, wire=None,
                               device_pack=None, hier=False):
                if hier:
                    self.hier_dispatches += 1
                    if not hier_works:
                        # The managed discipline: the dispatch error
                        # latches, the Work resolves to the failure
                        # default, and the commit vote discards the step.
                        self._fail_commit = True
                        return _completed(None)
                return _completed(tree)

            def allreduce(self, tree, op=None, wire=None):
                return _completed(tree)

            def allgather(self, tree):
                return _completed([tree])

            def should_commit(self, **kw):
                failed, self._fail_commit = self._fail_commit, False
                if not failed:
                    self.committed += 1
                return not failed

            def is_healing(self):
                return False

            def metrics(self):
                return self._m.metrics()

            def reset_plan_feedback(self):
                pass

        return ScriptedManager()

    def test_candidate_only_on_region_labeled_members(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_REGION", raising=False)
        state = _state()
        ddp = AdaptiveDDP(
            self._scripted(region=""), state, _grad_fn, device_pack="off"
        )
        assert "plan_hier" not in ddp._candidates
        ddp2 = AdaptiveDDP(
            self._scripted(region="east"), state, _grad_fn,
            device_pack="off",
        )
        assert ddp2._candidates.index("plan_hier") == \
            ddp2._candidates.index("plan") + 1

    def test_env_label_enables_candidate(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_REGION", "west")
        ddp = AdaptiveDDP(
            self._scripted(region=""), _state(), _grad_fn,
            device_pack="off",
        )
        assert "plan_hier" in ddp._candidates

    def test_unusable_cohort_records_sentinel_never_wins(self, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.delenv("TORCHFT_REGION", raising=False)
        mgr = self._scripted(region="east", hier_works=False)
        state = _state()
        ddp = AdaptiveDDP(mgr, state, _grad_fn, probe_steps=2,
                          device_pack="off")
        assert "plan_hier" in ddp._candidates
        x = jnp.ones((4, 8), jnp.float32)
        # anchor + 4 candidates x 2 probe steps (+ slack for the
        # error-echo step the reconfigure-free script never emits)
        for _ in range(1 + 2 * len(ddp._candidates) + 2):
            ddp.step(x)
        ddp.flush()
        assert ddp.mode is not None
        assert ddp.mode != "plan_hier", (
            "a candidate whose every probe step failed won the argmin"
        )
        assert mgr.hier_dispatches >= 1  # it really was probed
        hier_idx = ddp._candidates.index("plan_hier")
        assert ddp.decision["probe_s"][ddp._candidates[hier_idx]] >= \
            AdaptiveDDP._PROBE_FAILED_S

    def test_pinned_mode_accepts_plan_hier(self):
        mgr = self._scripted(region="east", hier_works=True)
        ddp = AdaptiveDDP(mgr, _state(), _grad_fn, mode="plan_hier",
                          device_pack="off")
        import jax.numpy as jnp

        x = jnp.ones((4, 8), jnp.float32)
        for _ in range(3):
            ddp.step(x)
        ddp.flush()
        assert ddp.mode == "plan_hier"
        assert mgr.hier_dispatches == 3


class TestShardedCandidate:
    """The per-step ZeRO candidate: env opt-in (TORCHFT_DDP_SHARDED),
    structural gates (f32 masters, no int8), the pinned mode's
    equivalence with the fused per-step path, and the sentinel
    discipline on a backend that can't serve sharded plans."""

    def test_absent_by_default_present_on_opt_in(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_DDP_SHARDED", raising=False)
        ddp = AdaptiveDDP(_ManagerStub(), _state(), _grad_fn, mode="auto")
        assert "ddp_sharded" not in ddp._candidates
        monkeypatch.setenv("TORCHFT_DDP_SHARDED", "1")
        ddp = AdaptiveDDP(_ManagerStub(), _state(), _grad_fn, mode="auto")
        assert "ddp_sharded" in ddp._candidates

    def test_structural_gates_drop_candidate(self, monkeypatch):
        import jax.numpy as jnp
        import optax

        monkeypatch.setenv("TORCHFT_DDP_SHARDED", "1")
        ddp = AdaptiveDDP(
            _ManagerStub(), _state(), _grad_fn, compress="int8",
            mode="auto",
        )
        assert "ddp_sharded" not in ddp._candidates
        bf16 = FTTrainState(
            {"w": jnp.ones((8, 8), jnp.bfloat16)}, optax.sgd(0.1)
        )
        ddp = AdaptiveDDP(_ManagerStub(), bf16, _grad_fn, mode="auto")
        assert "ddp_sharded" not in ddp._candidates

    def test_pinned_mode_validates_eagerly(self):
        import jax.numpy as jnp
        import optax

        with pytest.raises(ValueError, match="int8"):
            AdaptiveDDP(
                _ManagerStub(), _state(), _grad_fn, compress="int8",
                mode="ddp_sharded",
            )
        bf16 = FTTrainState(
            {"w": jnp.ones((8, 8), jnp.bfloat16)}, optax.sgd(0.1)
        )
        with pytest.raises(ValueError, match="f32 master"):
            AdaptiveDDP(_ManagerStub(), bf16, _grad_fn, mode="ddp_sharded")

    def test_pinned_sharded_matches_fused_per_step(self):
        # Solo manager: the pinned ddp_sharded trajectory must be
        # bit-identical to the fused plan transport's (rs + ag of a solo
        # cohort is identity movement; the shard-local update IS the
        # full update at W=1).
        import jax
        import jax.numpy as jnp

        e2e = TestEndToEnd()
        manager, store, lighthouse = e2e._manager()
        try:
            x = jnp.ones((4, 8), jnp.float32)
            results = {}
            for mode in ("plan", "ddp_sharded"):
                state = _state()
                ddp = AdaptiveDDP(
                    manager, state, _grad_fn, mode=mode,
                    device_pack="off",
                )
                for _ in range(3):
                    ddp.step(x)
                ddp.flush()
                assert ddp._ddp.last_commit is True
                results[mode] = np.asarray(
                    jax.tree_util.tree_leaves(state.params)[0]
                )
            assert results["plan"].tobytes() == results[
                "ddp_sharded"
            ].tobytes()
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_backend_without_sharded_plans_records_sentinel(
        self, monkeypatch
    ):
        # DummyCollectives has no sharded plan: every ddp_sharded probe
        # step latches the base-class NotImplementedError, records the
        # failure sentinel, and the candidate can never win — the
        # never-a-crash discipline plan_hier proves for topology.
        import jax.numpy as jnp

        from torchft_tpu import Lighthouse
        from torchft_tpu._native import Store
        from torchft_tpu.manager import Manager

        monkeypatch.setenv("TORCHFT_DDP_SHARDED", "1")
        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = Store()
        manager = Manager(
            collectives=DummyCollectives(world_size=1),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            timeout=timedelta(seconds=10),
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="nosharded_e2e",
        )
        try:
            state = _state()
            ddp = AdaptiveDDP(
                manager, state, _grad_fn, probe_steps=2,
                device_pack="off",
            )
            assert "ddp_sharded" in ddp._candidates
            x = jnp.ones((4, 8), jnp.float32)
            for _ in range(12):
                ddp.step(x)
            ddp.flush()
            assert ddp.mode is not None, "probe must terminate"
            assert ddp.mode != "ddp_sharded"
            assert ddp.decision["probe_s"]["ddp_sharded"] >= 1e8
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_tenure_boundary_resets_optimizer_state(self):
        # Crossing into ddp_sharded drops the stale shard; crossing out
        # re-inits the full-size state the unsharded engines update —
        # both deterministic from cohort-identical params.
        import jax
        import jax.numpy as jnp
        import optax

        e2e = TestEndToEnd()
        manager, store, lighthouse = e2e._manager()
        try:
            params = {"w": jnp.ones((8, 8), jnp.float32)}
            state = FTTrainState(params, optax.adam(1e-2))
            ddp = AdaptiveDDP(
                manager, state, _grad_fn, mode="ddp_sharded",
                device_pack="off",
            )
            x = jnp.ones((4, 8), jnp.float32)
            ddp.step(x)
            assert ddp._sharded()._opt_shard is not None
            # leave the sharded tenure: full state re-initialized
            ddp._run_step("blocking", x)
            counts = [
                l for l in jax.tree_util.tree_leaves(state.opt_state)
                if getattr(l, "size", 0) == 64
            ]
            assert counts, "full-size optimizer state was not rebuilt"
            # re-enter: the stale shard is dropped before the step
            ddp._run_step("ddp_sharded", x)
            assert ddp._sharded()._shard_meta is not None
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()
