"""Measures the data-plane overlap pipeline and the striped-connection ring.

Two CPU-loopback-measurable modes (no TPU required), both over a real
2-member host ring with a gradient-sized payload (~10x the flagship bench
model's gradients — where transfer+ring cost is the dominant
fault-tolerance overhead):

  default          chunked-pipeline ON vs OFF at a single connection
                   (d2h DMA / TCP ring / h2d upload overlap) ->
                   OVERLAP_BENCH.json
  --sharded-sweep  full-allreduce outer sync (fused allreduce + redundant
                   full-model outer update on every member) vs the SHARDED
                   outer sync (reduce-scatter -> outer update on the owned
                   1/W shard -> bf16 parameter allgather), per delta wire
                   (f32 and q8) and per stripe count, under the
                   BDP-emulated per-connection cap -> SHARD_BENCH.json.
                   Headline: the f32-delta row, where the sharded schedule
                   strictly cuts wire bytes (RS 4B/elem + AG 2B/elem vs
                   the fused 8B/elem) on top of the ~W× outer-update and
                   h2d-return savings. The q8 rows are reported for
                   completeness: a quantized fused ring already ships ~2
                   wire bytes/elem, so adding a bf16 param allgather can
                   COST wire there — the sharded win in that regime is
                   outer FLOPs/memory, not bytes, and the artifact says
                   which side won honestly. --dryrun shrinks the payload
                   and iterations to a smoke test (no artifact written).
  --sharded-step-sweep
                   PER-STEP ZeRO vs the fused plan-f32 per-step schedule:
                   plan reduce-scatter (q8 grad wire, owner shard full
                   f32) -> optimizer update on the owned ~1/W shard ->
                   bf16 param allgather, vs plan-f32 allreduce + the
                   redundant full-model update — at W=2 and W=3 under the
                   starved-link cap, with both legs' MEASURED wire bytes
                   and each member's resident optimizer bytes (∝ 1/W) in
                   the rows -> merged into SHARD_BENCH.json under
                   "per_step". --dryrun shrinks payload/iters to a smoke
                   test asserting the 1/W scaling (no artifact written).
  --plan-sweep     legacy managed gradient sync vs the persistent native
                   COMM PLAN on a ddp_small-shaped gradient tree (the
                   real model's param signature: ~0.72M params over its
                   actual leaf structure), per wire (f32 / bf16 / q8),
                   under the BDP-emulated per-connection cap ->
                   PLAN_BENCH.json. Legacy per wire = what PipelinedDDP
                   ships today (device-packed allreduce; jitted bf16
                   downcast; jitted int8 quantize+EF feeding the q8
                   ring); planned = ONE native call per step (casts,
                   EF, staging, ring, unpack all below Python). The
                   artifact reports steps/s both ways, the ratio, and
                   the plan path's per-step Python staging-allocation
                   count (zero after warmup is the contract). --dryrun
                   shrinks iterations to a smoke test (no artifact).
  --device-pack-sweep
                   host-pack vs DEVICE-pack comm plans on the ddp_small
                   gradient signature, per wire (f32 / bf16 / q8), under
                   the 12 MB/s BDP cap -> DEVPACK_BENCH.json. Host pack
                   reads every leaf at full f32 width before encoding;
                   device pack runs the Pallas quantize/cast kernels on
                   the accelerator and ships only WIRE bytes across the
                   device link (int8 codes + scale sidecar, or bf16),
                   feeding the prepacked native plan. The artifact
                   reports steps/s both ways and the measured per-step
                   `d2h_bytes` (from pop_op_stats), whose q8:f32 ratio
                   is the tentpole number (~0.25x). On this CPU host the
                   kernels run in interpret mode and there is no real
                   device link — the d2h accounting is exact anyway, and
                   the steps/s comparison is the honest worst case for
                   device pack (it pays the interpret-mode kernels and
                   saves nothing). --dryrun shrinks iterations to a
                   smoke test (no artifact written).
  --hier-sweep     FLAT ring vs the TWO-TIER topology-aware schedule on a
                   W=8 / R=2-regions fleet of real processes, per wire
                   (f32 / bf16 / q8+EF) and per stripe count, with the
                   fast-intra/slow-inter fabric emulated via the existing
                   per-connection pacing (TORCHFT_HC_WIRE_CAP_MBPS caps
                   every flat edge AND the inter tier at 12 MB/s — the
                   topology-oblivious placement where any flat hop may be
                   a DCN hop — while the intra tier rides unpaced
                   loopback) -> HIER_BENCH.json. Both sides ride the comm
                   PLAN path (the AdaptiveDDP plan / plan_hier
                   candidates). The artifact also carries: MEASURED
                   per-leader inter-tier bytes (from the duplex tx
                   accounting, checked against the (R-1)/R * N per-phase
                   prediction), cross-member + cross-iteration
                   bit-identity digests (incl. an uneven 5/3 region
                   split), and a LEADER-KILL probe (SIGKILL a region
                   leader mid-collective: the survivors must error within
                   one op deadline and commit again after reconfiguring
                   to W=7). --dryrun shrinks to W=4 / tiny payload as a
                   CI smoke (no artifact written).
  --stripe-sweep   ring striped over N parallel TCP connections per
                   neighbor, N swept over STRIPE_COUNTS at the pipelined
                   chunk config -> STRIPE_BENCH.json. Two passes:
                   (a) raw loopback — a CONTROL: loopback under this
                   sandbox is CPU-bound (a raw-socket probe here tops out
                   ~700 MB/s at 1 connection and gets SLOWER with more),
                   so stripes can only show parity; (b) per-connection
                   send cap (TORCHFT_HC_WIRE_CAP_MBPS) — emulates the
                   window/BDP-limited paths the striping exists for (the
                   TPU-tunnel link behind OVERLAP_BENCH.json delivered
                   4.5-13.4 MB/s on one connection), where aggregate
                   throughput scaling with N is a real end-to-end property
                   of the transport: serialized stripes, lock contention,
                   or a desynced schedule would all fail it.

Writes the JSON artifact and prints one summary line per config.

Usage: python bench_overlap.py [--stripe-sweep] [--peer <store_addr> <mode>]
"""

import json
import os
import subprocess
import sys
import threading
import time
from datetime import timedelta

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_LEAVES = 64
TOTAL_MB = 256  # ~64M f32 elements ~= 10x the bench model's ~25M params
ITERS = 3


def _tree(fill: float):
    import jax.numpy as jnp

    n = TOTAL_MB * (1 << 20) // 4 // N_LEAVES
    return {f"g{i}": jnp.full((n,), fill, jnp.float32) for i in range(N_LEAVES)}


# (name, pipeline_chunks) at a single ring connection — isolates the
# intra-buffer overlap pipeline from connection striping.
PHASES = (("single_shot", 1), ("pipelined", 8))

# Ring connections per neighbor edge for the stripe sweep; chunk config held
# at the pipelined setting so the sweep isolates the transport.
STRIPE_COUNTS = (1, 2, 4, 8)
STRIPE_CHUNKS = 8
# Per-connection send cap (MB/s) for the BDP-emulated pass — the order of
# the per-connection rates measured through real tunneled links here
# (OVERLAP_BENCH.json), generous by ~4x.
WIRE_CAP_MBPS = 50


# Sharded-sweep knobs: payload sized so the capped wire leg dominates but a
# full config sweep stays under a couple of minutes end-to-end. The cap is
# the TOP of the per-connection rates actually measured through tunneled
# links here (4.5-13.4 MB/s, OVERLAP_BENCH.json) — the stripe sweep's
# 50 MB/s is generous-by-4x on purpose (it probes aggregation headroom);
# this sweep compares two schedules' WIRE BYTES, so the cap models the
# starved path where bytes are the bill.
SHARD_PAYLOAD_MB = 32
SHARD_WIRE_CAP_MBPS = 12
SHARD_STRIPES = (1, 8)
SHARD_WIRES = ("f32", "q8")
SHARD_ITERS = 3
# Nesterov outer step, the standard DiLoCo outer optimizer.
SHARD_OUTER_LR, SHARD_OUTER_MOM = 0.7, 0.9

# Sharded-step-sweep knobs: PER-STEP ZeRO (plan reduce-scatter on the q8
# wire -> optimizer update on the owned 1/W shard -> bf16 param
# allgather) vs the fused plan-f32 per-step schedule (full allreduce +
# redundant full-model update), at W=2 and W=3 under the same
# starved-link cap the plan sweep models. Two stories, both honest: the
# sharded schedule cuts WIRE BYTES only vs plan-f32 (vs a fused q8 ring
# it trades bytes for exactness — SHARD_BENCH's q8 rows); it always
# cuts optimizer update FLOPs and resident state by ~W.
SHSTEP_PAYLOAD_MB = 8
SHSTEP_WIRE_CAP_MBPS = 12
SHSTEP_STRIPES = 4
SHSTEP_CHUNKS = 8
SHSTEP_ITERS = 3
SHSTEP_WORLDS = (2, 3)

# Plan-sweep knobs: the ddp_small gradient signature under the same
# measured-tunnel-rate cap the sharded sweep uses (the regime where
# per-step DDP actually runs), plus enough iterations that the median
# shakes off scheduler noise.
PLAN_WIRES = ("f32", "bf16", "q8")
PLAN_WIRE_CAP_MBPS = 12
PLAN_STRIPES = 4
PLAN_ITERS = 8

# Hier-sweep knobs: a W=8 fleet split into R=2 regions of 4, every member
# its own PROCESS (the leader-kill probe needs real SIGKILL). The
# per-connection cap models the slow wide-area path at the top of the
# measured tunnel rates (like the plan sweep); in FLAT mode it paces
# every edge — the topology-oblivious placement where any hop may cross
# the DCN — while the hier schedule's intra tier rides unpaced loopback
# (TORCHFT_HC_WIRE_CAP_INTRA_MBPS unset), which is exactly the
# fast-intra/slow-inter fabric the two-tier schedule exists for.
HIER_WORLD = 8
HIER_REGIONS = 2
HIER_PAYLOAD_MB = 16
HIER_WIRE_CAP_MBPS = 12
HIER_STRIPES = (1, 4)
HIER_ITERS = 3
HIER_WIRES = {"f32": None, "bf16": "bf16", "q8": "q8ef"}
# Leader-kill probe payload: sized so the inter phase runs for seconds
# under the cap — the SIGKILL must land mid-collective, and the op
# timeout bounds how fast the survivors must surface the death.
HIER_KILL_MB = 24
HIER_KILL_TIMEOUT_S = 30


def _hier_world() -> int:
    return 4 if "--dryrun" in sys.argv else HIER_WORLD


def _hier_payload_mb() -> float:
    return 1 if "--dryrun" in sys.argv else HIER_PAYLOAD_MB


def _hier_kill_mb() -> float:
    return 4 if "--dryrun" in sys.argv else HIER_KILL_MB


def _hier_iters() -> int:
    return 1 if "--dryrun" in sys.argv else HIER_ITERS


def _hier_stripes():
    return (1,) if "--dryrun" in sys.argv else HIER_STRIPES


def _hier_regions(world: int):
    half = world // 2
    return ["east"] * half + ["west"] * (world - half)


def _hier_digest(tree) -> str:
    import hashlib

    return hashlib.sha256(
        np.ascontiguousarray(np.asarray(tree)).tobytes()
    ).hexdigest()


def _hier_member(store_addr: str, rank: int, rec=None) -> None:
    """The full hier-sweep protocol for ONE member; rank 0 (the measurer)
    passes `rec` and records timings/accounting. Every rank runs the
    identical op sequence — the ring has no slack for divergence."""
    import signal

    from torchft_tpu._native import StoreClient
    from torchft_tpu.collectives import HostCollectives, ReduceOp

    W = _hier_world()
    regions = _hier_regions(W)
    count = int(_hier_payload_mb() * (1 << 20)) // 4
    data = (np.arange(count, dtype=np.float32) % 1001) * 0.01 + (rank + 1)
    iters = _hier_iters()
    client = StoreClient(store_addr, connect_timeout=timedelta(seconds=60))

    for stripes in _hier_stripes():
        for wname, wire in HIER_WIRES.items():
            cfg = f"{wname}_s{stripes}"
            hc = HostCollectives(
                timeout=timedelta(seconds=600),
                connect_timeout=timedelta(seconds=600),
                stripes=stripes,
            )
            hc.configure(f"{store_addr}/{cfg}", rank, W, regions)

            def flat():
                return hc.plan_allreduce(
                    data.copy(), ReduceOp.SUM, divisor=float(W), wire=wire
                ).wait()

            def hier():
                return hc.plan_allreduce(
                    data.copy(), ReduceOp.SUM, divisor=float(W), wire=wire,
                    hier=True,
                ).wait()

            flat()  # warm: plan builds
            hier()
            hc.pop_op_stats()
            t0 = time.perf_counter()
            for _ in range(iters):
                flat()
            flat_s = (time.perf_counter() - t0) / iters
            hc.pop_op_stats()
            digests = []
            t0 = time.perf_counter()
            for _ in range(iters):
                digests.append(_hier_digest(hier()))
            hier_s = (time.perf_counter() - t0) / iters
            stats = [
                s for s in hc.pop_op_stats()
                if s["op"] == "plan_allreduce" and s.get("hier")
            ]
            client.set(f"hier_digest/{cfg}/{rank}", digests[-1].encode())
            if rec is not None:
                rec[cfg] = {
                    "wire": wname,
                    "stripes": stripes,
                    "flat_s": round(flat_s, 4),
                    "hier_s": round(hier_s, 4),
                    "flat_steps_per_s": round(1.0 / flat_s, 3),
                    "hier_steps_per_s": round(1.0 / hier_s, 3),
                    "hier_speedup": round(flat_s / hier_s, 3),
                    # identical inputs every iteration: equal digests =
                    # deterministic across runs of the reduction tree.
                    # NOT asserted on the q8+EF wire — the leader's
                    # error-feedback carry advances between syncs BY
                    # DESIGN, so consecutive results differ while
                    # cross-member identity (the real contract) holds.
                    "deterministic_across_iters": (
                        len(set(digests)) == 1 if wire != "q8ef" else None
                    ),
                    "tiers": stats[-1]["tiers"],
                    "phase_s": {
                        k: stats[-1][k]
                        for k in ("intra_rs_s", "intra_ag_s",
                                  "inter_ring_s", "intra_bcast_s")
                    },
                }
            hc.shutdown()

    # Uneven region split (5/3): the bit-identity contract must hold off
    # the symmetric case too (the bulk op this time, q8 inter wire).
    half = W // 2 + 1
    uneven = ["east"] * half + ["west"] * (W - half)
    hc = HostCollectives(
        timeout=timedelta(seconds=600),
        connect_timeout=timedelta(seconds=600),
        stripes=_hier_stripes()[-1],
    )
    hc.configure(f"{store_addr}/uneven", rank, W, uneven)
    out = hc.allreduce_hier(data.copy(), ReduceOp.SUM, wire="q8").wait()
    client.set(f"hier_digest/uneven/{rank}", _hier_digest(out).encode())
    hc.shutdown()

    # ---- three-tier (host -> region -> fleet) shm sweep ----
    # 2 hosts x W/2 members, each host one region's whole membership: the
    # schedule is host rings + the capped inter (leader) ring. The SAME
    # layout runs twice — TORCHFT_HC_SHM on (shared-memory rings) vs off
    # (loopback TCP, the honest control) — and the host-tier PHASE walls
    # are the comparison: the shm rings must move the same payload >= 2x
    # faster than loopback TCP pays for its kernel copies + syscalls.
    hosts3 = ["hostA"] * (W // 2) + ["hostB"] * (W - W // 2)
    # Gradient-scale frames: ring buffers sized so a stripe's ring chunk
    # lands without producer/consumer ping-pong (the 1 MiB default is
    # tuned for pipelined chunks; the knob row documents the tradeoff).
    os.environ["TORCHFT_HC_SHM_RING_BYTES"] = str(8 << 20)
    for transport in ("shm", "tcp"):
        os.environ["TORCHFT_HC_SHM"] = "1" if transport == "shm" else "0"
        hc = HostCollectives(
            timeout=timedelta(seconds=600),
            connect_timeout=timedelta(seconds=600),
            stripes=_hier_stripes()[-1],
        )
        hc.configure(f"{store_addr}/shm3_{transport}", rank, W, regions,
                     hosts3)

        def tier3():
            return hc.plan_allreduce(
                data.copy(), ReduceOp.SUM, divisor=float(W), hier=True,
            ).wait()

        tier3()  # warm: plan build + shm rings touched
        hc.pop_op_stats()
        digests = []
        t0 = time.perf_counter()
        for _ in range(max(iters, 5)):
            digests.append(_hier_digest(tier3()))
        wall_s = (time.perf_counter() - t0) / max(iters, 5)
        stats = [
            s for s in hc.pop_op_stats()
            if s["op"] == "plan_allreduce" and s.get("hier")
        ]
        client.set(
            f"hier_digest/shm3_{transport}/{rank}", digests[-1].encode()
        )
        # Every member publishes its least-diluted host-phase sample:
        # min across iterations AND members. A single bench box runs the
        # whole W-process fleet, so any one member's phase wall folds in
        # scheduler preemption of its co-hosted peers — identical for
        # both transports, pure dilution of the ratio. The fleet-wide
        # minimum is the cleanest measurement of the transport itself.
        my_phase = min(
            s["shm_rs_s"] + s["shm_ag_s"] + s["shm_bcast_s"]
            for s in stats
        )
        client.set(
            f"shm3_phase/{transport}/{rank}",
            repr(my_phase).encode(),
        )
        if rec is not None:
            st = stats[-1]
            host_tier = st["tiers"]["host"]
            host_phase_s = min(
                float(
                    client.get(
                        f"shm3_phase/{transport}/{r}",
                        timeout=timedelta(seconds=120),
                    ).decode()
                )
                for r in range(W)
            )
            rec[f"shm3_{transport}"] = {
                "transport": hc.host_tier_transport(),
                "stripes": _hier_stripes()[-1],
                "step_s": round(wall_s, 4),
                "steps_per_s": round(1.0 / wall_s, 3),
                "host_phase_s": round(host_phase_s, 5),
                "host_moved_bytes": host_tier.get("shm_bytes", 0)
                or host_tier.get("tx_bytes", 0),
                "tiers": st["tiers"],
                "deterministic_across_iters": len(set(digests)) == 1,
            }
        hc.shutdown()
    os.environ.pop("TORCHFT_HC_SHM", None)
    os.environ.pop("TORCHFT_HC_SHM_RING_BYTES", None)

    # Uneven HOST layout (a 3-member group, a singleton, a pair inside
    # uneven regions), q8 inter wire: the three-tier bit-identity
    # contract must hold off the symmetric case too.
    half = W // 2 + 1
    uneven_r = ["east"] * half + ["west"] * (W - half)
    uneven_h = []
    for i in range(W):
        grp = "hU0" if i < min(3, half) else (
            "hU1" if i < half else f"hU{2 + (i - half) // 2}"
        )
        uneven_h.append(grp)
    hc = HostCollectives(
        timeout=timedelta(seconds=600),
        connect_timeout=timedelta(seconds=600),
        stripes=_hier_stripes()[-1],
    )
    hc.configure(f"{store_addr}/shm3_uneven", rank, W, uneven_r, uneven_h)
    out = hc.allreduce_hier(data.copy(), ReduceOp.SUM, wire="q8").wait()
    client.set(f"hier_digest/shm3_uneven/{rank}", _hier_digest(out).encode())
    hc.shutdown()

    # Oracle pinning payload: one small seeded op per wire on the 3-tier
    # layout; rank 0 checks every digest against the numpy three-tier
    # oracle (tests/test_hier_collectives.hier_oracle) after the sweep.
    oracle_count = 50_000
    odata = (
        np.arange(oracle_count, dtype=np.float32) % 997
    ) * 0.01 + (rank + 1)
    for wname, wire in (("f32", None), ("bf16", "bf16"), ("q8", "q8")):
        hc = HostCollectives(
            timeout=timedelta(seconds=600),
            connect_timeout=timedelta(seconds=600),
            stripes=1,
        )
        hc.configure(f"{store_addr}/shm3_oracle_{wname}", rank, W, regions,
                     hosts3)
        out = hc.allreduce_hier(odata.copy(), ReduceOp.SUM, wire=wire).wait()
        client.set(
            f"hier_digest/shm3_oracle_{wname}/{rank}",
            _hier_digest(out).encode(),
        )
        hc.shutdown()

    # Leader-kill probe: the WEST leader SIGKILLs itself mid-collective;
    # every survivor must error within ONE op deadline (the configured
    # timeout), not the 600 s rendezvous budget, and the reconfigured
    # W-1 cohort must commit the next op.
    victim = W // 2
    hc = HostCollectives(
        timeout=timedelta(seconds=HIER_KILL_TIMEOUT_S),
        connect_timeout=timedelta(seconds=600),
        stripes=1,
    )
    hc.configure(f"{store_addr}/kill", rank, W, regions)
    big = np.ones(int(_hier_kill_mb() * (1 << 20)) // 4, np.float32)
    if rank == victim:
        # Early enough that the kill lands inside the op's inter phase
        # even at the dryrun payload (the self-kill after the op is the
        # backstop if the op still wins the race).
        threading.Timer(
            0.05, lambda: os.kill(os.getpid(), signal.SIGKILL)
        ).start()
    t0 = time.perf_counter()
    died = None
    try:
        hc.allreduce_hier(big).wait()
    except Exception as e:  # noqa: BLE001
        died = e
    err_s = time.perf_counter() - t0
    if rank == victim:
        # The op can race the timer and complete first; the victim must
        # NEVER reach the recovery rendezvous (it would rejoin under a
        # surviving rank and corrupt the handshake) — die here if the
        # timer hasn't landed yet.
        os.kill(os.getpid(), signal.SIGKILL)
    hc.shutdown()
    if rec is not None:
        rec["leader_kill"] = {
            "victim_rank": victim,
            "payload_MB": _hier_kill_mb(),
            "op_timeout_s": HIER_KILL_TIMEOUT_S,
            "errored": died is not None,
            "error_s": round(err_s, 3),
            "error": str(died)[:120] if died else None,
        }

    new_rank = rank if rank < victim else rank - 1
    new_regions = [g for i, g in enumerate(regions) if i != victim]
    hc = HostCollectives(
        timeout=timedelta(seconds=600),
        connect_timeout=timedelta(seconds=600),
        stripes=1,
    )
    hc.configure(f"{store_addr}/recover", new_rank, W - 1, new_regions)
    out = hc.allreduce_hier(
        np.arange(4096, dtype=np.float32) + new_rank
    ).wait()
    client.set(f"hier_digest/recover/{new_rank}", _hier_digest(out).encode())
    hc.shutdown()
    if rec is not None:
        rec["leader_kill"]["recovered_commit"] = True
        rec["leader_kill"]["surviving_world"] = W - 1

    # Co-hosted kill probe (three-tier): SIGKILL a member that shares a
    # SHARED-MEMORY ring with the measurer mid-collective. The shm tier
    # has no socket FIN — the poisoned-magic / deadline discipline must
    # surface the death within ONE op deadline on every survivor, and
    # the reconfigured cohort must commit the next op.
    Ws = W - 1  # the surviving cohort from the leader-kill probe
    hostsK = ["hK0"] * ((Ws + 1) // 2) + ["hK1"] * (Ws // 2)
    victim2 = 1  # co-hosted with the measurer (rank 0) on hK0
    hc = HostCollectives(
        timeout=timedelta(seconds=HIER_KILL_TIMEOUT_S),
        connect_timeout=timedelta(seconds=600),
        stripes=1,
    )
    hc.configure(f"{store_addr}/cohost_kill", new_rank, Ws, None, hostsK)
    assert hc.hier_capable()
    big = np.ones(int(_hier_kill_mb() * (1 << 20)) // 4, np.float32)
    if new_rank == victim2:
        # Die INSIDE the collective window without ever feeding the shm
        # ring: a SIGKILL closes no socket and poisons no magic, so the
        # co-hosted survivors' only signal is the pid-liveness probe the
        # blocked ring waiter runs each futex slice — the exact path this
        # probe exists to verify. (The shm ring is so fast that a timer
        # racing a live op loses at any payload; a never-arriving peer is
        # the honest mid-collective shape.)
        time.sleep(0.25)
        os.kill(os.getpid(), signal.SIGKILL)
    t0 = time.perf_counter()
    died = None
    try:
        hc.allreduce_hier(big).wait()
    except Exception as e:  # noqa: BLE001
        died = e
    err_s = time.perf_counter() - t0
    hc.shutdown()
    if rec is not None:
        rec["cohost_kill"] = {
            "victim_new_rank": victim2,
            "victim_cohosted_with_measurer": True,
            "host_transport": "shm",
            "payload_MB": _hier_kill_mb(),
            "op_timeout_s": HIER_KILL_TIMEOUT_S,
            "errored": died is not None,
            "error_s": round(err_s, 3),
            "error": str(died)[:120] if died else None,
        }

    rank2 = new_rank if new_rank < victim2 else new_rank - 1
    # The survivor cohort commits its next op THROUGH the shm tier: one
    # shared host label (they really are co-hosted) keeps the
    # hierarchical schedule alive at any surviving world size.
    hostsK2 = ["hR0"] * (Ws - 1)
    hc = HostCollectives(
        timeout=timedelta(seconds=600),
        connect_timeout=timedelta(seconds=600),
        stripes=1,
    )
    hc.configure(f"{store_addr}/cohost_recover", rank2, Ws - 1, None,
                 hostsK2)
    out = hc.allreduce_hier(
        np.arange(4096, dtype=np.float32) + rank2
    ).wait()
    client.set(
        f"hier_digest/cohost_recover/{rank2}", _hier_digest(out).encode()
    )
    hc.shutdown()
    if rec is not None:
        rec["cohost_kill"]["recovered_commit"] = True
        rec["cohost_kill"]["surviving_world"] = Ws - 1


def _plan_iters() -> int:
    return 2 if "--dryrun" in sys.argv else PLAN_ITERS


def _ddp_small_grad_tree(scale: float):
    """A gradient pytree with the ddp_small model's EXACT parameter
    signature (bench.py's link-sized per-step DDP config): the plan's
    win is per-leaf Python overhead, so the leaf structure must be the
    real model's, not a synthetic blob."""
    import jax
    import jax.numpy as jnp

    from bench import DDP_SMALL_CONFIG
    from torchft_tpu.models import TransformerConfig, init_params

    cfg = TransformerConfig(**DDP_SMALL_CONFIG, use_flash=False)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return jax.tree_util.tree_map(
        lambda l: (jnp.ones(l.shape, jnp.float32) * scale), params
    )


def _plan_sync_legacy(hc, tree, wire, box):
    """What PipelinedDDP ships per step today, per wire: the jitted
    compress (bf16 downcast / int8 quantize with error feedback) plus the
    managed device-packed allreduce."""
    import jax

    from torchft_tpu.collectives import ReduceOp

    if wire == "f32":
        res = hc.allreduce(tree, ReduceOp.SUM, divisor=2.0).wait()
    elif wire == "bf16":
        import jax.numpy as jnp

        if box.get("down") is None:
            box["down"] = jax.jit(lambda t: jax.tree_util.tree_map(
                lambda l: l.astype(jnp.bfloat16), t))
            box["up"] = jax.jit(lambda t: jax.tree_util.tree_map(
                lambda l: l.astype(jnp.float32), t))
        res = box["up"](
            hc.allreduce(box["down"](tree), ReduceOp.SUM, divisor=2.0).wait()
        )
    else:  # q8: jitted EF quantize -> quantized ring
        import jax.numpy as jnp

        from torchft_tpu.quantize import quantize_with_feedback

        if box.get("quant") is None:
            box["quant"] = jax.jit(quantize_with_feedback)
            box["res"] = jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, jnp.float32), tree
            )
        out = box["quant"](tree, box["res"])
        box["res"] = out["res"]
        res = hc.allreduce(
            out["dq"], ReduceOp.SUM, divisor=2.0, wire="q8"
        ).wait()
    jax.block_until_ready(res)
    return res


def _plan_sync_planned(hc, tree, wire, device_pack=False):
    """The same logical sync through the persistent comm plan: one
    native call (pack/cast/EF + striped ring + unpack), no jitted
    compress program, no per-step staging allocation. ``device_pack``
    moves the wire encoding onto the accelerator (Pallas kernels +
    prepacked plan leaves) so only wire-sized bytes cross d2h."""
    from torchft_tpu.collectives import ReduceOp

    plan_wire = {"f32": None, "bf16": "bf16", "q8": "q8ef"}[wire]
    return hc.plan_allreduce(
        tree, ReduceOp.SUM, divisor=2.0, wire=plan_wire,
        device_pack=device_pack,
    ).wait()


def _configs(mode):
    """(prefix, pipeline_chunks, stripes) per phase — IDENTICAL on both ring
    members (the chunk/stripe schedule is part of the wire contract;
    configure() validates it through the store)."""
    if mode in ("stripes", "stripes_capped"):
        pre = "cap_" if mode == "stripes_capped" else ""
        return [(f"{pre}stripe{s}", STRIPE_CHUNKS, s) for s in STRIPE_COUNTS]
    if mode.startswith("sharded"):
        return [(f"{w}_s{s}", STRIPE_CHUNKS, s)
                for w in SHARD_WIRES for s in SHARD_STRIPES]
    if mode.startswith("plan") or mode.startswith("devpack"):
        return [(w, STRIPE_CHUNKS, PLAN_STRIPES) for w in PLAN_WIRES]
    return [(name, chunks, 1) for name, chunks in PHASES]


def _apply_cap(mode) -> None:
    # The cap is pure send pacing (no wire-format effect), read by the
    # native layer at configure(); set it identically in both processes so
    # each DIRECTION of the ring is capped.
    if mode == "stripes_capped":
        os.environ["TORCHFT_HC_WIRE_CAP_MBPS"] = str(WIRE_CAP_MBPS)
    elif mode == "sharded_capped":
        os.environ["TORCHFT_HC_WIRE_CAP_MBPS"] = str(SHARD_WIRE_CAP_MBPS)
    elif mode.startswith("shstep"):
        os.environ["TORCHFT_HC_WIRE_CAP_MBPS"] = str(SHSTEP_WIRE_CAP_MBPS)
    elif mode in ("plan_capped", "devpack_capped"):
        os.environ["TORCHFT_HC_WIRE_CAP_MBPS"] = str(PLAN_WIRE_CAP_MBPS)
    else:
        os.environ.pop("TORCHFT_HC_WIRE_CAP_MBPS", None)


def _shard_payload_mb() -> int:
    return 4 if "--dryrun" in sys.argv else SHARD_PAYLOAD_MB


def _shard_iters() -> int:
    return 1 if "--dryrun" in sys.argv else SHARD_ITERS


def _shard_tree(fill: float):
    import jax.numpy as jnp

    n = _shard_payload_mb() * (1 << 20) // 4 // N_LEAVES
    return {f"g{i}": jnp.full((n,), fill, jnp.float32)
            for i in range(N_LEAVES)}


def _nesterov(avg, mom, params):
    # One elementwise Nesterov outer step in numpy — identical arithmetic
    # on both sides of the comparison, sized by what each side holds (the
    # full model for the fused path, the owned shard for the sharded one).
    mom *= SHARD_OUTER_MOM
    mom += avg
    params -= SHARD_OUTER_LR * (avg + SHARD_OUTER_MOM * mom)


def _sync_full(hc, tree, wire, box):
    """The fused outer sync: full allreduce + a full-model outer update
    (every member runs it redundantly — that redundancy is the point of
    comparison)."""
    import jax

    from torchft_tpu.collectives import ReduceOp

    res = hc.allreduce(
        tree, ReduceOp.SUM, divisor=2.0,
        wire=("q8" if wire == "q8" else None),
    ).wait()
    leaves = jax.tree_util.tree_leaves(res)
    if box.get("m") is None:
        box["m"] = [np.zeros(l.size, np.float32) for l in leaves]
        box["p"] = [np.zeros(l.size, np.float32) for l in leaves]
    for i, leaf in enumerate(leaves):
        _nesterov(np.asarray(leaf).ravel(), box["m"][i], box["p"][i])
    return res


def _sync_sharded(hc, tree, wire, box):
    """The sharded outer sync: reduce-scatter -> outer update on the
    owned 1/W shard -> bf16 parameter allgather."""
    import jax

    from torchft_tpu.collectives import ReduceOp

    sh = hc.reduce_scatter(
        tree, ReduceOp.SUM, divisor=2.0,
        wire=("q8" if wire == "q8" else None),
    ).wait()
    (name,) = list(sh.values)
    avg = np.asarray(sh.values[name])
    if box.get("m") is None or box["m"].size != avg.size:
        box["m"] = np.zeros(avg.size, np.float32)
        box["p"] = np.zeros(avg.size, np.float32)
    _nesterov(avg, box["m"], box["p"])
    out = hc.allgather_into(
        sh.replace_values({name: box["p"].copy()}), wire="bf16"
    ).wait()
    jax.block_until_ready(out)
    return out


def _shstep_payload_mb() -> int:
    return 2 if "--dryrun" in sys.argv else SHSTEP_PAYLOAD_MB


def _shstep_iters() -> int:
    return 1 if "--dryrun" in sys.argv else SHSTEP_ITERS


def _shstep_tree(fill: float):
    import jax.numpy as jnp

    n = _shstep_payload_mb() * (1 << 20) // 4 // N_LEAVES
    return {f"g{i}": jnp.full((n,), fill, jnp.float32)
            for i in range(N_LEAVES)}


def _shstep_fused(hc, tree, world, box):
    """The plan-f32 per-step baseline: fused plan allreduce + redundant
    full-model optimizer update on every member."""
    import jax

    from torchft_tpu.collectives import ReduceOp

    res = hc.plan_allreduce(
        tree, ReduceOp.SUM, divisor=float(world)
    ).wait()
    leaves = jax.tree_util.tree_leaves(res)
    if box.get("m") is None:
        box["m"] = [np.zeros(l.size, np.float32) for l in leaves]
        box["p"] = [np.zeros(l.size, np.float32) for l in leaves]
    for i, leaf in enumerate(leaves):
        _nesterov(np.asarray(leaf).ravel(), box["m"][i], box["p"][i])
    return res


def _shstep_sharded(hc, tree, world, box):
    """The per-step ZeRO schedule: plan reduce-scatter (q8 grad wire,
    owner shard full f32) -> optimizer update on the owned ~1/W shard ->
    bf16 param allgather through the same plan."""
    import jax

    from torchft_tpu.collectives import ReduceOp

    sh = hc.plan_reduce_scatter(
        tree, ReduceOp.SUM, divisor=float(world),
        wire="q8", ag_wire="bf16",
    ).wait()
    avg = np.asarray(sh.values["float32"])
    if box.get("m") is None or box["m"].size != avg.size:
        box["m"] = np.zeros(avg.size, np.float32)
        box["p"] = np.zeros(avg.size, np.float32)
    _nesterov(avg, box["m"], box["p"])
    out = hc.plan_allgather_into(
        sh.replace_values({"float32": box["p"].copy()}), wire="bf16"
    ).wait()
    jax.block_until_ready(out)
    return out


def _shstep_member(hc, tree, world) -> dict:
    """The full sharded-step protocol for one member (measurer and peers
    run the same sequence — the ring has no slack for divergence): warm
    both schedules, then ITERS of each. Returns the member's boxes."""
    fbox, sbox = {}, {}
    _shstep_fused(hc, tree, world, fbox)
    _shstep_sharded(hc, tree, world, sbox)
    hc.pop_op_stats()  # drop warmup timings
    iters = _shstep_iters()
    t0 = time.perf_counter()
    for _ in range(iters):
        _shstep_fused(hc, tree, world, fbox)
    fused_s = (time.perf_counter() - t0) / iters
    fused_stats = hc.pop_op_stats()
    t0 = time.perf_counter()
    for _ in range(iters):
        _shstep_sharded(hc, tree, world, sbox)
    sharded_s = (time.perf_counter() - t0) / iters
    sharded_stats = hc.pop_op_stats()
    return {"fbox": fbox, "sbox": sbox, "fused_s": fused_s,
            "sharded_s": sharded_s, "fused_stats": fused_stats,
            "sharded_stats": sharded_stats}


def peer(store_addr: str, mode: str) -> None:
    from torchft_tpu.platform import apply_jax_platform_env

    if mode.startswith("hier:"):
        # Hier-sweep member: the cap env was inherited from the parent
        # (flat edges + inter tier paced, intra unpaced).
        apply_jax_platform_env()
        _hier_member(store_addr, int(mode.split(":", 1)[1]))
        return

    _apply_cap(mode)
    apply_jax_platform_env()
    from torchft_tpu.collectives import HostCollectives, ReduceOp

    if mode.startswith("shstep:"):
        # Sharded-step member: rank r of a W-member ring, mirroring the
        # measurer's op sequence exactly.
        _, r, world = mode.split(":")
        r, world = int(r), int(world)
        zeros = _shstep_tree(0.0)
        hc = HostCollectives(timeout=timedelta(seconds=600),
                             connect_timeout=timedelta(seconds=600),
                             pipeline_chunks=SHSTEP_CHUNKS,
                             stripes=SHSTEP_STRIPES)
        hc.configure(f"{store_addr}/shstep{world}", r, world)
        _shstep_member(hc, zeros, world)
        hc.shutdown()
        return

    if mode.startswith("sharded"):
        # Mirror the measuring side's op sequence exactly (the ring has no
        # slack for schedule divergence): warm full+sharded, then ITERS of
        # each, per (wire, stripes) config.
        zeros = _shard_tree(0.0)
        for prefix, chunks, stripes in _configs(mode):
            wire = prefix.split("_")[0]
            hc = HostCollectives(timeout=timedelta(seconds=600),
                                 connect_timeout=timedelta(seconds=600),
                                 pipeline_chunks=chunks,
                                 stripes=stripes)
            hc.configure(f"{store_addr}/{prefix}", 1, 2)
            fbox, sbox = {}, {}
            _sync_full(hc, zeros, wire, fbox)
            _sync_sharded(hc, zeros, wire, sbox)
            for _ in range(_shard_iters()):
                _sync_full(hc, zeros, wire, fbox)
            for _ in range(_shard_iters()):
                _sync_sharded(hc, zeros, wire, sbox)
            hc.shutdown()
        return

    if mode.startswith("devpack"):
        # Mirror the measuring side exactly: warm host-pack + device-pack
        # plans, then iters of each, per wire config. Pack placement is
        # ring-schedule-neutral (prepacked is not in the plan hash), but
        # mirroring keeps the two sides' per-step wall comparable.
        zeros = _ddp_small_grad_tree(0.0)
        for prefix, chunks, stripes in _configs(mode):
            hc = HostCollectives(timeout=timedelta(seconds=600),
                                 connect_timeout=timedelta(seconds=600),
                                 pipeline_chunks=chunks,
                                 stripes=stripes)
            hc.configure(f"{store_addr}/{prefix}", 1, 2)
            _plan_sync_planned(hc, zeros, prefix, device_pack=False)
            _plan_sync_planned(hc, zeros, prefix, device_pack=True)
            for _ in range(_plan_iters()):
                _plan_sync_planned(hc, zeros, prefix, device_pack=False)
            for _ in range(_plan_iters()):
                _plan_sync_planned(hc, zeros, prefix, device_pack=True)
            hc.shutdown()
        return

    if mode.startswith("plan"):
        # Mirror the measuring side's op sequence exactly: warm legacy +
        # warm planned, then iters of each, per wire config.
        zeros = _ddp_small_grad_tree(0.0)
        for prefix, chunks, stripes in _configs(mode):
            hc = HostCollectives(timeout=timedelta(seconds=600),
                                 connect_timeout=timedelta(seconds=600),
                                 pipeline_chunks=chunks,
                                 stripes=stripes)
            hc.configure(f"{store_addr}/{prefix}", 1, 2)
            box = {}
            _plan_sync_legacy(hc, zeros, prefix, box)
            _plan_sync_planned(hc, zeros, prefix)
            for _ in range(_plan_iters()):
                _plan_sync_legacy(hc, zeros, prefix, box)
            for _ in range(_plan_iters()):
                _plan_sync_planned(hc, zeros, prefix)
            hc.shutdown()
        return

    zeros = _tree(0.0)
    for prefix, chunks, stripes in _configs(mode):
        hc = HostCollectives(timeout=timedelta(seconds=600),
                             connect_timeout=timedelta(seconds=600),
                             pipeline_chunks=chunks,
                             stripes=stripes)
        hc.configure(f"{store_addr}/{prefix}", 1, 2)
        for _ in range(1 + ITERS):  # warm + timed
            hc.allreduce(zeros, ReduceOp.SUM).wait()
        hc.shutdown()


def _measure(store, tree, mode):
    """Times every config of `mode` against the already-running peer;
    returns {config_name: {"s", "MBps"}}."""
    import jax

    from torchft_tpu.collectives import HostCollectives, ReduceOp

    _apply_cap(mode)
    out = {}
    for prefix, chunks, stripes in _configs(mode):
        hc = HostCollectives(
            timeout=timedelta(seconds=600),
            connect_timeout=timedelta(seconds=600),
            pipeline_chunks=chunks,
            stripes=stripes,
        )
        hc.configure(f"{store.address()}/{prefix}", 0, 2)
        res = hc.allreduce(tree, ReduceOp.SUM).wait()  # warm (jit pack)
        jax.block_until_ready(res)
        hc.pop_op_stats()  # drop the warm iter's timings
        t0 = time.perf_counter()
        for _ in range(ITERS):
            res = hc.allreduce(tree, ReduceOp.SUM).wait()
            jax.block_until_ready(res)
        dt = (time.perf_counter() - t0) / ITERS
        # Ring-leg transport wall from the op stats: per-chunk slowest-
        # stripe maxima, excluding the d2h/h2d memcpy legs and the
        # peer-skew wait at the op-header sync — the number the stripe
        # count actually moves.  End-to-end `s` stays the headline for
        # the overlap mode, where the pipeline overlap is the story.
        ring_wall = 0.0
        for st in hc.pop_op_stats():
            for b in st.get("buckets", {}).values():
                ring_wall += b.get("stripe_wall") or b["ring"]
        ring_s = ring_wall / ITERS
        out[prefix] = {"s": round(dt, 3), "MBps": round(TOTAL_MB / dt, 1),
                       "ring_s": round(ring_s, 3),
                       "ring_MBps": round(TOTAL_MB / ring_s, 1)}
        label = (f"stripes={stripes}" if mode.startswith("stripes")
                 else f"chunks={chunks}")
        print(f"{prefix} ({label}): {dt:.3f}s {TOTAL_MB / dt:.1f} MB/s "
              f"end-to-end, ring {ring_s:.3f}s {TOTAL_MB / ring_s:.1f} MB/s",
              flush=True)
        hc.shutdown()
    return out


def _measure_sharded(store, tree, mode):
    """Times full-allreduce vs sharded outer sync per (wire, stripes)
    config against the already-running peer; returns
    {config: {"full_s", "sharded_s", "speedup"}}."""
    from torchft_tpu.collectives import HostCollectives

    _apply_cap(mode)
    out = {}
    iters = _shard_iters()
    for prefix, chunks, stripes in _configs(mode):
        wire = prefix.split("_")[0]
        hc = HostCollectives(
            timeout=timedelta(seconds=600),
            connect_timeout=timedelta(seconds=600),
            pipeline_chunks=chunks,
            stripes=stripes,
        )
        hc.configure(f"{store.address()}/{prefix}", 0, 2)
        fbox, sbox = {}, {}
        _sync_full(hc, tree, wire, fbox)      # warm (jit pack + scratch)
        _sync_sharded(hc, tree, wire, sbox)
        t0 = time.perf_counter()
        for _ in range(iters):
            _sync_full(hc, tree, wire, fbox)
        full_s = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            _sync_sharded(hc, tree, wire, sbox)
        sharded_s = (time.perf_counter() - t0) / iters
        out[prefix] = {
            "wire": wire,
            "stripes": stripes,
            "full_s": round(full_s, 3),
            "sharded_s": round(sharded_s, 3),
            "speedup": round(full_s / sharded_s, 3),
        }
        print(
            f"{prefix}: full {full_s:.3f}s, sharded {sharded_s:.3f}s "
            f"-> {full_s / sharded_s:.2f}x",
            flush=True,
        )
        hc.shutdown()
    return out


def _measure_plan(store, tree, mode):
    """Times legacy vs planned gradient sync per wire against the
    already-running peer; returns {wire: row}."""
    from torchft_tpu.collectives import HostCollectives

    _apply_cap(mode)
    out = {}
    iters = _plan_iters()
    for prefix, chunks, stripes in _configs(mode):
        hc = HostCollectives(
            timeout=timedelta(seconds=600),
            connect_timeout=timedelta(seconds=600),
            pipeline_chunks=chunks,
            stripes=stripes,
        )
        hc.configure(f"{store.address()}/{prefix}", 0, 2)
        box = {}
        _plan_sync_legacy(hc, tree, prefix, box)   # warm: jit programs
        _plan_sync_planned(hc, tree, prefix)       # warm: plan build
        hc.pop_op_stats()
        t0 = time.perf_counter()
        for _ in range(iters):
            _plan_sync_legacy(hc, tree, prefix, box)
        legacy_s = (time.perf_counter() - t0) / iters
        hc.pop_op_stats()
        t0 = time.perf_counter()
        for _ in range(iters):
            _plan_sync_planned(hc, tree, prefix)
        planned_s = (time.perf_counter() - t0) / iters
        plan_stats = [
            s for s in hc.pop_op_stats() if s["op"] == "plan_allreduce"
        ]
        staging_allocs = max(
            (s["py_staging_allocs"] for s in plan_stats), default=None
        )
        out[prefix] = {
            "wire": prefix,
            "stripes": stripes,
            "legacy_s": round(legacy_s, 4),
            "planned_s": round(planned_s, 4),
            "legacy_steps_per_s": round(1.0 / legacy_s, 2),
            "planned_steps_per_s": round(1.0 / planned_s, 2),
            "speedup": round(legacy_s / planned_s, 3),
            # The zero-allocation contract, measured not asserted: the
            # max over every timed step's Python staging allocations.
            "py_staging_allocs_after_warmup": staging_allocs,
            "buckets": len(plan_stats[-1]["buckets"]) if plan_stats else 0,
        }
        print(
            f"{prefix}: legacy {legacy_s:.4f}s, planned {planned_s:.4f}s "
            f"-> {legacy_s / planned_s:.2f}x "
            f"(py staging allocs {staging_allocs})",
            flush=True,
        )
        hc.shutdown()
    return out


def _measure_devpack(store, tree, mode):
    """Times host-pack vs device-pack comm plans per wire against the
    already-running peer, and drains pop_op_stats for the measured
    per-step d2h_bytes of each; returns {wire: row}."""
    from torchft_tpu.collectives import HostCollectives

    _apply_cap(mode)
    out = {}
    iters = _plan_iters()
    for prefix, chunks, stripes in _configs(mode):
        hc = HostCollectives(
            timeout=timedelta(seconds=600),
            connect_timeout=timedelta(seconds=600),
            pipeline_chunks=chunks,
            stripes=stripes,
        )
        hc.configure(f"{store.address()}/{prefix}", 0, 2)
        # warm: plan builds + (device side) Pallas kernel jits
        _plan_sync_planned(hc, tree, prefix, device_pack=False)
        _plan_sync_planned(hc, tree, prefix, device_pack=True)
        hc.pop_op_stats()
        t0 = time.perf_counter()
        for _ in range(iters):
            _plan_sync_planned(hc, tree, prefix, device_pack=False)
        host_s = (time.perf_counter() - t0) / iters
        host_stats = [
            s for s in hc.pop_op_stats() if s["op"] == "plan_allreduce"
        ]
        t0 = time.perf_counter()
        for _ in range(iters):
            _plan_sync_planned(hc, tree, prefix, device_pack=True)
        dev_s = (time.perf_counter() - t0) / iters
        dev_stats = [
            s for s in hc.pop_op_stats() if s["op"] == "plan_allreduce"
        ]
        assert all(not s["device_pack"] for s in host_stats)
        assert all(s["device_pack"] for s in dev_stats), (
            "device pack silently fell back to host pack — the Pallas "
            "kernels are unavailable on this host"
        )
        d2h_host = host_stats[-1]["d2h_bytes"]
        d2h_dev = dev_stats[-1]["d2h_bytes"]
        # Tunneled-device model: on the runtimes this feature targets the
        # d2h leg rides the SAME throttled tunnel the BDP cap emulates
        # for the ring (pop_op_stats measured it at 4.5-13.4 MB/s,
        # OVERLAP_BENCH.json), so a step there costs the measured wall
        # PLUS d2h_bytes at the capped rate. Pure arithmetic on measured
        # numbers — the formula is in the artifact, not a hidden sleep.
        link_s = PLAN_WIRE_CAP_MBPS * 1e6
        host_tun = host_s + d2h_host / link_s
        dev_tun = dev_s + d2h_dev / link_s
        out[prefix] = {
            "wire": prefix,
            "stripes": stripes,
            "host_pack_s": round(host_s, 4),
            "device_pack_s": round(dev_s, 4),
            "host_pack_steps_per_s": round(1.0 / host_s, 2),
            "device_pack_steps_per_s": round(1.0 / dev_s, 2),
            # raw loopback: d2h is a memcpy here, so device pack pays
            # its kernels and banks nothing — the honest control
            "devpack_speedup_raw": round(host_s / dev_s, 3),
            # the tentpole accounting: bytes that crossed the DEVICE link
            "d2h_bytes_host_pack": d2h_host,
            "d2h_bytes_device_pack": d2h_dev,
            "wire_bytes": dev_stats[-1]["wire_bytes"],
            "tunnel_host_pack_s": round(host_tun, 4),
            "tunnel_device_pack_s": round(dev_tun, 4),
            "tunnel_device_pack_steps_per_s": round(1.0 / dev_tun, 2),
            "devpack_speedup_tunnel": round(host_tun / dev_tun, 3),
        }
        print(
            f"{prefix}: host-pack {host_s:.4f}s, device-pack {dev_s:.4f}s "
            f"(raw {host_s / dev_s:.2f}x, tunneled-link model "
            f"{host_tun / dev_tun:.2f}x); d2h {d2h_host} -> "
            f"{d2h_dev} B/step",
            flush=True,
        )
        hc.shutdown()
    return out


def _run_mode(mode):
    import jax

    from torchft_tpu import Store

    store = Store()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    peer_args = [sys.executable, os.path.abspath(__file__), "--peer",
                 store.address(), mode]
    if "--dryrun" in sys.argv:
        peer_args.append("--dryrun")
    peer_proc = subprocess.Popen(peer_args, env=env)
    if mode.startswith("sharded"):
        tree = _shard_tree(1.0)
    elif mode.startswith("plan") or mode.startswith("devpack"):
        tree = _ddp_small_grad_tree(1.0)
    else:
        tree = _tree(1.0)
    jax.block_until_ready(tree)
    try:
        if mode.startswith("sharded"):
            results = _measure_sharded(store, tree, mode)
        elif mode.startswith("devpack"):
            results = _measure_devpack(store, tree, mode)
        elif mode.startswith("plan"):
            results = _measure_plan(store, tree, mode)
        else:
            results = _measure(store, tree, mode)
        assert peer_proc.wait(timeout=600) == 0
    finally:
        if peer_proc.poll() is None:
            peer_proc.kill()
        store.shutdown()
    return results


def _run_shstep(world: int) -> dict:
    """One W-member sharded-step row: spawns W-1 peer processes, runs the
    measurer in-process, returns the row with measured per-leg bytes."""
    import jax

    from torchft_tpu import Store
    from torchft_tpu.collectives import HostCollectives

    store = Store()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    peers = []
    for r in range(1, world):
        args = [sys.executable, os.path.abspath(__file__), "--peer",
                store.address(), f"shstep:{r}:{world}"]
        if "--dryrun" in sys.argv:
            args.append("--dryrun")
        peers.append(subprocess.Popen(args, env=env))
    _apply_cap("shstep")
    tree = _shstep_tree(1.0)
    jax.block_until_ready(tree)
    total_bytes = sum(
        int(l.size) for l in jax.tree_util.tree_leaves(tree)
    ) * 4
    try:
        hc = HostCollectives(timeout=timedelta(seconds=600),
                             connect_timeout=timedelta(seconds=600),
                             pipeline_chunks=SHSTEP_CHUNKS,
                             stripes=SHSTEP_STRIPES)
        hc.configure(f"{store.address()}/shstep{world}", 0, world)
        m = _shstep_member(hc, tree, world)
        hc.shutdown()
        for p in peers:
            assert p.wait(timeout=900) == 0
    finally:
        for p in peers:
            if p.poll() is None:
                p.kill()
        store.shutdown()
    iters = _shstep_iters()
    fused_wire = sum(
        st.get("wire_bytes") or st["bytes"] for st in m["fused_stats"]
    ) / iters
    rs_stats = [st for st in m["sharded_stats"]
                if st["op"] == "plan_reduce_scatter"]
    ag_stats = [st for st in m["sharded_stats"]
                if st["op"] == "plan_allgather_into"]
    rs_wire = sum(st["wire_bytes"] for st in rs_stats) / iters
    ag_wire = sum(st["wire_bytes"] for st in ag_stats) / iters
    # Optimizer residency: the momentum buffer each member actually
    # holds — the full model for the fused schedule, the owned shard
    # for the sharded one (~1/W).
    opt_fused = sum(mm.nbytes for mm in m["fbox"]["m"])
    opt_sharded = int(m["sbox"]["m"].nbytes)
    row = {
        "world": world,
        "payload_MB": _shstep_payload_mb(),
        "fused_s": round(m["fused_s"], 3),
        "sharded_s": round(m["sharded_s"], 3),
        "steps_per_s_fused": round(1.0 / m["fused_s"], 3),
        "steps_per_s_sharded": round(1.0 / m["sharded_s"], 3),
        "speedup": round(m["fused_s"] / m["sharded_s"], 3),
        "fused_wire_MB_per_step": round(fused_wire / (1 << 20), 2),
        "rs_wire_MB_per_step": round(rs_wire / (1 << 20), 2),
        "ag_wire_MB_per_step": round(ag_wire / (1 << 20), 2),
        "model_bytes": total_bytes,
        "opt_state_bytes_fused": opt_fused,
        "opt_state_bytes_sharded": opt_sharded,
    }
    print(
        f"W={world}: fused {m['fused_s']:.3f}s/step, sharded "
        f"{m['sharded_s']:.3f}s/step -> {row['speedup']:.2f}x; wire/step "
        f"fused {row['fused_wire_MB_per_step']}MB vs rs "
        f"{row['rs_wire_MB_per_step']}MB + ag "
        f"{row['ag_wire_MB_per_step']}MB; opt bytes {opt_fused} -> "
        f"{opt_sharded}",
        flush=True,
    )
    return row


def _run_hier():
    """Spawns W-1 member processes, runs the measurer in-process, then
    verifies cross-member digests and peer exit codes (the kill victim
    must die by SIGKILL, everyone else exits clean)."""
    from torchft_tpu import Store
    from torchft_tpu._native import StoreClient

    os.environ["TORCHFT_HC_WIRE_CAP_MBPS"] = str(HIER_WIRE_CAP_MBPS)
    os.environ.pop("TORCHFT_HC_WIRE_CAP_INTRA_MBPS", None)
    store = Store()
    W = _hier_world()
    victim = W // 2
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    peers = []
    for r in range(1, W):
        args = [sys.executable, os.path.abspath(__file__), "--peer",
                store.address(), f"hier:{r}"]
        if "--dryrun" in sys.argv:
            args.append("--dryrun")
        peers.append(subprocess.Popen(args, env=env))
    rec = {}
    try:
        _hier_member(store.address(), 0, rec)
        # Two SIGKILL victims across the probe sequence: the region
        # leader (original rank W//2), then the co-hosted member
        # (original rank 1 — new_rank 1 of the surviving cohort).
        victims = {victim, 1}
        for i, p in enumerate(peers):
            r = i + 1
            code = p.wait(timeout=900)
            if r in victims:
                assert code != 0, f"kill victim {r} exited cleanly"
            else:
                assert code == 0, f"peer {r} exited {code}"
        client = StoreClient(
            store.address(), connect_timeout=timedelta(seconds=30)
        )
        t = timedelta(seconds=30)

        def digests(cfg, world):
            return {
                client.get(f"hier_digest/{cfg}/{r}", timeout=t).decode()
                for r in range(world)
            }

        for cfg, row in rec.items():
            if cfg in ("leader_kill", "cohost_kill"):
                continue
            row["digests_identical_across_members"] = (
                len(digests(cfg, W)) == 1
            )
        rec["uneven_regions_bit_identity"] = len(digests("uneven", W)) == 1
        rec["uneven_hosts_bit_identity"] = (
            len(digests("shm3_uneven", W)) == 1
        )
        rec["leader_kill"]["recover_bit_identity"] = (
            len(digests("recover", W - 1)) == 1
        )
        rec["cohost_kill"]["recover_bit_identity"] = (
            len(digests("cohost_recover", W - 2)) == 1
        )

        # Three-tier ORACLE pinning: the numpy host->region->fleet oracle
        # (the test suite's own, imported — one source of truth) must
        # match every member's bytes on every wire.
        sys.path.insert(0, os.path.join(REPO, "tests"))
        from test_hier_collectives import hier_oracle

        import hashlib

        regions = _hier_regions(W)
        hosts3 = ["hostA"] * (W // 2) + ["hostB"] * (W - W // 2)
        oracle_count = 50_000
        odatas = [
            (np.arange(oracle_count, dtype=np.float32) % 997) * 0.01
            + (r + 1)
            for r in range(W)
        ]
        oracle_ok = {}
        for wname, wire in (("f32", None), ("bf16", "bf16"), ("q8", "q8")):
            expect = hier_oracle(odatas, regions, wire=wire, hosts=hosts3)
            exp_digest = hashlib.sha256(
                np.ascontiguousarray(expect[0]).tobytes()
            ).hexdigest()
            got = digests(f"shm3_oracle_{wname}", W)
            oracle_ok[wname] = got == {exp_digest}
        rec["three_tier_oracle_ok"] = oracle_ok
    finally:
        for p in peers:
            if p.poll() is None:
                p.kill()
        store.shutdown()
    return rec


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--peer":
        peer(sys.argv[2], sys.argv[3] if len(sys.argv) > 3 else "overlap")
        return

    import jax

    if "--sharded-sweep" in sys.argv:
        results = _run_mode("sharded_capped")
        # Headline: the f32-delta configs — the regime where the sharded
        # schedule strictly cuts wire bytes on top of the ~W× compute/h2d
        # savings. q8 rows stay in the artifact: there the fused ring
        # already ships ~2B/elem so the bf16 param leg can cost wire, and
        # the honest number shows it.
        f32_rows = {k: v for k, v in results.items() if v["wire"] == "f32"}
        best_key = max(f32_rows, key=lambda k: f32_rows[k]["speedup"])
        report = {
            "platform": jax.devices()[0].platform,
            "payload_MB": _shard_payload_mb(),
            "leaves": N_LEAVES,
            "iters": _shard_iters(),
            "world_size": 2,
            "outer": {"optimizer": "nesterov-sgd",
                      "lr": SHARD_OUTER_LR, "momentum": SHARD_OUTER_MOM},
            "bdp_emulated": {
                "per_connection_cap_MBps": SHARD_WIRE_CAP_MBPS,
                "how": "TORCHFT_HC_WIRE_CAP_MBPS send pacing per ring "
                       "connection, both directions — the top of the "
                       "per-connection rates measured through real "
                       "tunneled links here (OVERLAP_BENCH.json)",
            },
            "sync": "full = fused allreduce(delta) + redundant full-model "
                    "outer update on every member; sharded = "
                    "reduce_scatter(delta) -> outer update on the owned "
                    "1/W shard -> allgather_into(params, bf16 wire)",
            "configs": results,
            "headline_config": best_key,
            "headline_full_s": f32_rows[best_key]["full_s"],
            "headline_sharded_s": f32_rows[best_key]["sharded_s"],
            "sharded_speedup": f32_rows[best_key]["speedup"],
        }
        if "--dryrun" in sys.argv:
            print(json.dumps({"dryrun": True,
                              "sharded_speedup": report["sharded_speedup"]}))
            return
        with open(os.path.join(REPO, "SHARD_BENCH.json"), "w") as f:
            json.dump(report, f, indent=2)
        print(json.dumps({
            "sharded_speedup": report["sharded_speedup"],
            "headline_config": best_key,
        }))
        return

    if "--sharded-step-sweep" in sys.argv:
        rows = [_run_shstep(w) for w in SHSTEP_WORLDS]
        per_step = {
            "platform": jax.devices()[0].platform,
            "leaves": N_LEAVES,
            "iters": _shstep_iters(),
            "stripes": SHSTEP_STRIPES,
            "per_connection_cap_MBps": SHSTEP_WIRE_CAP_MBPS,
            "sync": "fused = plan-f32 allreduce + redundant full-model "
                    "update on every member; sharded = plan "
                    "reduce-scatter (q8 grad wire, owner shard full "
                    "f32) -> update on the owned ~1/W shard -> bf16 "
                    "param allgather",
            "optimizer": {"kind": "nesterov-sgd", "lr": SHARD_OUTER_LR,
                          "momentum": SHARD_OUTER_MOM},
            "rows": rows,
            "note": "wins steps/s vs plan-f32 (fewer f32 wire bytes AND "
                    "~W x less update work); vs a fused q8 ring it wins "
                    "memory/FLOPs, not bytes — the rs+ag legs ship "
                    "~1.5B/elem where fused q8 ships ~1B/elem",
        }
        if "--dryrun" in sys.argv:
            r2 = next(r for r in rows if r["world"] == 2)
            r3 = next(r for r in rows if r["world"] == 3)
            ratio = (r2["opt_state_bytes_sharded"]
                     / max(r3["opt_state_bytes_sharded"], 1))
            # 1/W scaling: W=2 shard ~ 1.5x the W=3 shard (3/2).
            assert 1.2 < ratio < 1.9, f"opt shard not ~1/W: {ratio}"
            for r in rows:
                assert (r["opt_state_bytes_sharded"]
                        < r["opt_state_bytes_fused"])
                assert r["rs_wire_MB_per_step"] > 0
                assert r["ag_wire_MB_per_step"] > 0
            print(json.dumps({
                "dryrun": True,
                "speedup_w2": r2["speedup"],
                "speedup_w3": r3["speedup"],
                "opt_bytes_w2": r2["opt_state_bytes_sharded"],
                "opt_bytes_w3": r3["opt_state_bytes_sharded"],
            }))
            return
        path = os.path.join(REPO, "SHARD_BENCH.json")
        with open(path) as f:
            report = json.load(f)
        report["per_step"] = per_step
        with open(path, "w") as f:
            json.dump(report, f, indent=2)
        print(json.dumps({
            "per_step_speedups": {str(r["world"]): r["speedup"]
                                  for r in rows},
        }))
        return

    if "--plan-sweep" in sys.argv:
        results = _run_mode("plan_capped")
        worst = min(results.values(), key=lambda r: r["speedup"])
        best = max(results.values(), key=lambda r: r["speedup"])
        report = {
            "platform": jax.devices()[0].platform,
            "model": "ddp_small gradient signature (~0.72M params, the "
                     "real leaf structure of bench.py's link-sized "
                     "per-step DDP config)",
            "iters": _plan_iters(),
            "world_size": 2,
            "stripes": PLAN_STRIPES,
            "bdp_emulated": {
                "per_connection_cap_MBps": PLAN_WIRE_CAP_MBPS,
                "how": "TORCHFT_HC_WIRE_CAP_MBPS send pacing per ring "
                       "connection, both directions — the top of the "
                       "per-connection rates measured through real "
                       "tunneled links here (OVERLAP_BENCH.json)",
            },
            "sync": "legacy = what PipelinedDDP ships today per wire "
                    "(device-packed managed allreduce; jitted bf16 "
                    "downcast; jitted int8 quantize+EF into the q8 "
                    "ring); planned = ONE native comm-plan call (cast/"
                    "EF/staging/striped ring/unpack below Python), "
                    "bit-identical results",
            "adaptive_mode": {
                "rule": "AdaptiveDDP probes blocking/plan/pipelined, "
                        "allgathers cohort timings, locks the argmin; "
                        "ties resolve to blocking, so the locked mode "
                        "is never slower than blocking as measured "
                        "(TORCHFT_DDP_MODE pins it explicitly)",
            },
            "configs": results,
            "worst_wire": worst["wire"],
            "worst_speedup": worst["speedup"],
            "best_wire": best["wire"],
            "best_speedup": best["speedup"],
            "planned_not_slower": all(
                r["speedup"] >= 0.98 for r in results.values()
            ),
            "zero_py_staging_allocs": all(
                r["py_staging_allocs_after_warmup"] == 0
                for r in results.values()
            ),
        }
        if "--dryrun" in sys.argv:
            print(json.dumps({
                "dryrun": True,
                "worst_speedup": report["worst_speedup"],
                "zero_py_staging_allocs": report["zero_py_staging_allocs"],
            }))
            return
        with open(os.path.join(REPO, "PLAN_BENCH.json"), "w") as f:
            json.dump(report, f, indent=2)
        print(json.dumps({
            "plan_worst_speedup": report["worst_speedup"],
            "plan_best_speedup": report["best_speedup"],
            "zero_py_staging_allocs": report["zero_py_staging_allocs"],
        }))
        return

    if "--device-pack-sweep" in sys.argv:
        results = _run_mode("devpack_capped")
        f32_d2h = results["f32"]["d2h_bytes_host_pack"]
        ratios = {
            w: round(results[w]["d2h_bytes_device_pack"] / f32_d2h, 4)
            for w in results
        }
        compressed = [results["bf16"], results["q8"]]
        worst_raw = min(
            results.values(), key=lambda r: r["devpack_speedup_raw"]
        )
        worst_tun = min(
            compressed, key=lambda r: r["devpack_speedup_tunnel"]
        )
        report = {
            "platform": jax.devices()[0].platform,
            "model": "ddp_small gradient signature (~0.72M params, the "
                     "real leaf structure of bench.py's link-sized "
                     "per-step DDP config)",
            "iters": _plan_iters(),
            "world_size": 2,
            "stripes": PLAN_STRIPES,
            "bdp_emulated": {
                "per_connection_cap_MBps": PLAN_WIRE_CAP_MBPS,
                "how": "TORCHFT_HC_WIRE_CAP_MBPS send pacing per ring "
                       "connection, both directions — the top of the "
                       "per-connection rates measured through real "
                       "tunneled links here (OVERLAP_BENCH.json)",
            },
            "sync": "host-pack = the PR-3 comm plan (full-width leaves "
                    "cross d2h, native cast/EF packs on the host); "
                    "device-pack = Pallas quantize/cast kernels emit the "
                    "wire encoding on the accelerator, only wire bytes "
                    "cross d2h, the prepacked plan decodes into the "
                    "SAME staging — bit-identical results either way",
            "measurement_note": "this host is CPU-only: the kernels run "
                    "in interpret mode and d2h is a memcpy, so the RAW "
                    "steps/s column is device pack's worst case (it "
                    "pays the kernel cost and banks no link saving — "
                    "kept as the honest control, like the stripe "
                    "sweep's raw-loopback pass). The tunnel_* columns "
                    "apply the stated linear model of the throttled "
                    "device link the feature targets: wall + "
                    "d2h_bytes / cap, same 12 MB/s as the ring cap. "
                    "d2h_bytes itself is exact accounting either way.",
            "configs": results,
            "d2h_ratio_vs_f32_host": ratios,
            "q8_d2h_ratio": ratios["q8"],
            "bf16_d2h_ratio": ratios["bf16"],
            "q8_d2h_target_0p3_met": ratios["q8"] <= 0.3,
            "bf16_d2h_target_0p55_met": ratios["bf16"] <= 0.55,
            "worst_wire_raw": worst_raw["wire"],
            "worst_devpack_speedup_raw": worst_raw["devpack_speedup_raw"],
            # The acceptance comparison, on the compressed wires (f32
            # stays in configs as the no-byte-win control): under the
            # tunneled-link model device pack must not lose to host pack.
            "worst_compressed_devpack_speedup_tunnel":
                worst_tun["devpack_speedup_tunnel"],
            "devpack_not_slower_tunnel": all(
                r["devpack_speedup_tunnel"] >= 1.0 for r in compressed
            ),
        }
        if "--dryrun" in sys.argv:
            print(json.dumps({
                "dryrun": True,
                "q8_d2h_ratio": report["q8_d2h_ratio"],
                "bf16_d2h_ratio": report["bf16_d2h_ratio"],
                "devpack_not_slower_tunnel":
                    report["devpack_not_slower_tunnel"],
            }))
            return
        with open(os.path.join(REPO, "DEVPACK_BENCH.json"), "w") as f:
            json.dump(report, f, indent=2)
        print(json.dumps({
            "q8_d2h_ratio": report["q8_d2h_ratio"],
            "bf16_d2h_ratio": report["bf16_d2h_ratio"],
            "worst_devpack_speedup_raw":
                report["worst_devpack_speedup_raw"],
            "devpack_not_slower_tunnel":
                report["devpack_not_slower_tunnel"],
        }))
        return

    if "--hier-sweep" in sys.argv:
        rec = _run_hier()
        W, L = _hier_world(), HIER_REGIONS
        count = int(_hier_payload_mb() * (1 << 20)) // 4
        _extra_keys = (
            "leader_kill", "cohost_kill", "uneven_regions_bit_identity",
            "uneven_hosts_bit_identity", "three_tier_oracle_ok",
            "shm3_shm", "shm3_tcp",
        )
        configs = {k: v for k, v in rec.items() if k not in _extra_keys}
        # Accounting check: the leader's inter-tier bytes per ring phase
        # must be ~(L-1)/L of the WIRE-sized payload — measured from the
        # duplex tx counters, not modeled. Wire esize: f32 4, bf16 2,
        # q8+EF ~1 (+ per-hop scales, allowed in the upper bound).
        esize = {"f32": 4, "bf16": 2, "q8": 1}
        for row in configs.values():
            expected = count * esize[row["wire"]] * (L - 1) // L
            inter = row["tiers"]["inter"]
            row["expected_inter_phase_bytes"] = expected
            row["inter_bytes_ok"] = all(
                expected <= inter[k] <= expected * 1.10 + 8192
                for k in ("rs_tx_bytes", "ag_tx_bytes")
            )
        f32_rows = {k: v for k, v in configs.items() if v["wire"] == "f32"}
        best_key = max(f32_rows, key=lambda k: f32_rows[k]["hier_speedup"])
        kill = rec["leader_kill"]
        report = {
            "platform": jax.devices()[0].platform,
            "world_size": W,
            "regions": {"east": W // 2, "west": W - W // 2},
            "payload_MB": _hier_payload_mb(),
            "iters": _hier_iters(),
            "emulation": {
                "inter_cap_MBps": HIER_WIRE_CAP_MBPS,
                "how": "TORCHFT_HC_WIRE_CAP_MBPS send pacing per "
                       "connection: in FLAT mode it paces EVERY ring edge "
                       "(topology-oblivious placement — any hop may cross "
                       "the DCN); the hier schedule's inter (leader) tier "
                       "is paced by the same knob while the intra tier "
                       "rides unpaced loopback "
                       "(TORCHFT_HC_WIRE_CAP_INTRA_MBPS unset) — the "
                       "fast-intra/slow-inter fabric the topology exists "
                       "for",
            },
            "sync": "both sides ride the comm-plan path (the AdaptiveDDP "
                    "plan vs plan_hier candidates): flat = one striped "
                    "ring over all W members; hier = intra-region "
                    "reduce-scatter -> intra allgather -> inter ring "
                    "among the 2 region leaders (the only capped-link "
                    "traffic) -> chunk-pipelined intra broadcast. Wires "
                    "apply to the whole flat ring vs the inter hop only "
                    "(f32 / bf16 / q8+EF at the leader).",
            "determinism": "hier results are bit-identical across members "
                    "and across iterations (sha256 digests in configs); "
                    "the SUM ORDER differs from the flat ring, so "
                    "flat-vs-hier values agree at the f32 reordering "
                    "tolerance, never bit-for-bit (documented contract)",
            "configs": configs,
            "headline_config": best_key,
            "hier_speedup": f32_rows[best_key]["hier_speedup"],
            "hier_speedup_target_1p5_met":
                f32_rows[best_key]["hier_speedup"] >= 1.5,
            "inter_bytes_accounting_ok": all(
                r["inter_bytes_ok"] for r in configs.values()
            ),
            "bit_identity_ok": all(
                r["digests_identical_across_members"]
                and r["deterministic_across_iters"] is not False
                for r in configs.values()
            ) and rec["uneven_regions_bit_identity"],
            "uneven_regions_bit_identity": rec[
                "uneven_regions_bit_identity"],
            "leader_kill": kill,
            "leader_kill_ok": bool(
                kill["errored"]
                and kill["error_s"] < kill["op_timeout_s"]
                and kill.get("recovered_commit")
                and kill.get("recover_bit_identity")
            ),
        }
        # ---- the three-tier (host -> region -> fleet) SHM section ----
        shm_row, tcp_row = rec["shm3_shm"], rec["shm3_tcp"]
        ck = rec["cohost_kill"]
        shm_speedup = (
            tcp_row["host_phase_s"] / shm_row["host_phase_s"]
            if shm_row["host_phase_s"] > 0 else float("inf")
        )
        report["SHM_BENCH"] = {
            "topology": "three tiers: 2 hosts x W/2 co-hosted members "
                        "(the host ring: shared-memory rings vs the "
                        "TORCHFT_HC_SHM=0 loopback-TCP control, same "
                        "geometry) -> inter leader ring under the "
                        "wire cap; each host is one region's whole "
                        "membership",
            "hosts": {"hostA": W // 2, "hostB": W - W // 2},
            "payload_MB": _hier_payload_mb(),
            "rows": {"shm": shm_row, "tcp": tcp_row},
            # The tentpole number: wall of the intra-host ring phases
            # (rs + ag + bcast) moving the identical payload.
            "host_phase_speedup_shm_vs_tcp": round(shm_speedup, 3),
            "host_phase_speedup_target_2x_met": shm_speedup >= 2.0,
            # Honest zero-tx contract: shm hops hand nothing to the
            # kernel, the TCP control pays for every byte.
            "shm_zero_tx_bytes_ok": (
                shm_row["tiers"]["host"]["tx_bytes"] == 0
                and tcp_row["tiers"]["host"]["tx_bytes"] > 0
            ),
            "transports_ok": (
                shm_row["transport"] == "shm"
                and tcp_row["transport"] == "tcp"
            ),
            "bit_identity": {
                "across_members": bool(
                    shm_row.get("digests_identical_across_members")
                    and tcp_row.get("digests_identical_across_members")
                ),
                "uneven_hosts": rec["uneven_hosts_bit_identity"],
                "three_tier_numpy_oracle": rec["three_tier_oracle_ok"],
            },
            "cohost_kill": ck,
            "cohost_kill_ok": bool(
                ck["errored"]
                and ck["error_s"] < ck["op_timeout_s"]
                and ck.get("recovered_commit")
                and ck.get("recover_bit_identity")
            ),
        }
        if "--dryrun" in sys.argv:
            shm_bench = report["SHM_BENCH"]
            print(json.dumps({
                "dryrun": True,
                "hier_speedup": report["hier_speedup"],
                "inter_bytes_accounting_ok":
                    report["inter_bytes_accounting_ok"],
                "bit_identity_ok": report["bit_identity_ok"],
                "leader_kill_ok": report["leader_kill_ok"],
                "leader_kill": kill,
                "shm_host_phase_speedup":
                    shm_bench["host_phase_speedup_shm_vs_tcp"],
                "shm_zero_tx_bytes_ok": shm_bench["shm_zero_tx_bytes_ok"],
                "shm_bit_identity": shm_bench["bit_identity"],
                "cohost_kill_ok": shm_bench["cohost_kill_ok"],
            }))
            # The CI smoke ASSERTS the contracts it exists for (a broken
            # schedule must fail the step, not just print false). The
            # speedups are NOT asserted here — a loaded CI runner's
            # timing is noise at the dryrun payload; the accounting,
            # identity and fault contracts are timing-free.
            assert report["inter_bytes_accounting_ok"], (
                "per-leader inter-tier bytes drifted from (L-1)/L * wire "
                "payload"
            )
            assert report["bit_identity_ok"], (
                "cross-member/cross-iteration bit identity broken"
            )
            assert report["leader_kill_ok"], (
                f"leader-kill contract broken: {kill}"
            )
            # Three-tier smoke contracts: a real 3-tier record with shm
            # phase keys, the honest zero-tx split, the numpy oracle
            # across wires, uneven host layouts, and the co-hosted kill.
            assert shm_bench["transports_ok"], (
                f"host tier transports wrong: {shm_bench['rows']}"
            )
            for trow in shm_bench["rows"].values():
                assert trow["tiers"]["host"]["world"] >= 2
                assert trow["host_phase_s"] > 0, (
                    "host tier phase walls missing from the record"
                )
            assert shm_bench["shm_zero_tx_bytes_ok"], (
                "shm tier billed kernel bytes (or the TCP control "
                "billed none)"
            )
            assert all(
                shm_bench["bit_identity"]["three_tier_numpy_oracle"]
                .values()
            ), f"three-tier oracle broken: {shm_bench['bit_identity']}"
            assert shm_bench["bit_identity"]["uneven_hosts"], (
                "uneven host layout bit identity broken"
            )
            assert shm_bench["cohost_kill_ok"], (
                f"co-hosted kill contract broken: {shm_bench['cohost_kill']}"
            )
            return
        with open(os.path.join(REPO, "HIER_BENCH.json"), "w") as f:
            json.dump(report, f, indent=2)
        print(json.dumps({
            "hier_speedup": report["hier_speedup"],
            "headline_config": best_key,
            "inter_bytes_accounting_ok":
                report["inter_bytes_accounting_ok"],
            "bit_identity_ok": report["bit_identity_ok"],
            "leader_kill_ok": report["leader_kill_ok"],
        }))
        return

    if "--stripe-sweep" in sys.argv:
        capped = _run_mode("stripes_capped")
        raw = _run_mode("stripes")
        base = capped["cap_stripe1"]
        # Headline = the capped pass, ranked on the ring leg: striping is a
        # transport optimization for per-connection-limited paths, and the
        # capped pass is the loopback-measurable stand-in for them. The
        # raw pass stays in the artifact as the control (CPU-bound here:
        # parity is the expected result, see module docstring).
        best_s = max(STRIPE_COUNTS,
                     key=lambda s: capped[f"cap_stripe{s}"]["ring_MBps"])
        best = capped[f"cap_stripe{best_s}"]
        report = {
            "platform": jax.devices()[0].platform,
            "payload_MB": TOTAL_MB,
            "leaves": N_LEAVES,
            "iters": ITERS,
            "pipeline_chunks": STRIPE_CHUNKS,
            "bdp_emulated": {
                "per_connection_cap_MBps": WIRE_CAP_MBPS,
                "how": "TORCHFT_HC_WIRE_CAP_MBPS send pacing per ring "
                       "connection, both directions — models the "
                       "window/BDP-limited DCN and tunneled links the "
                       "striped transport targets",
                "stripes": {
                    str(s): capped[f"cap_stripe{s}"] for s in STRIPE_COUNTS
                },
            },
            "raw_loopback_control": {
                "note": "this sandbox's loopback is CPU-bound (~700 MB/s "
                        "at 1 raw connection, slower with more), so "
                        "stripe parity — not speedup — is the honest "
                        "expectation here",
                "stripes": {
                    str(s): raw[f"stripe{s}"] for s in STRIPE_COUNTS
                },
            },
            "single_connection_MBps": base["MBps"],
            "single_connection_ring_MBps": base["ring_MBps"],
            "best_stripes": best_s,
            "best_MBps": best["MBps"],
            "best_ring_MBps": best["ring_MBps"],
            "speedup_vs_single_connection": round(
                best["MBps"] / base["MBps"], 3
            ),
            "ring_speedup_vs_single_connection": round(
                best["ring_MBps"] / base["ring_MBps"], 3
            ),
        }
        with open(os.path.join(REPO, "STRIPE_BENCH.json"), "w") as f:
            json.dump(report, f, indent=2)
        print(json.dumps({
            "stripe_speedup": report["speedup_vs_single_connection"],
            "ring_speedup": report["ring_speedup_vs_single_connection"],
            "best_stripes": best_s,
        }))
        return

    results = _run_mode("overlap")
    report = {
        "platform": jax.devices()[0].platform,
        "payload_MB": TOTAL_MB,
        "leaves": N_LEAVES,
        "iters": ITERS,
    }
    report.update(results)
    report["speedup"] = round(
        report["single_shot"]["s"] / report["pipelined"]["s"], 3
    )
    with open(os.path.join(REPO, "OVERLAP_BENCH.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps({"overlap_speedup": report["speedup"]}))


if __name__ == "__main__":
    main()
