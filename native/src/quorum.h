// Pure quorum logic: the lighthouse's quorum_compute and the manager's
// compute_quorum_results, kept side-effect free so they can be unit tested
// directly (mirroring the reference's pure-function tests,
// src/lighthouse.rs:567-1141 / src/manager.rs:482-851).
#pragma once

#include <cstdint>
#include <deque>
#include <map>
#include <optional>
#include <string>
#include <utility>
#include <vector>

#include "json.h"
#include "torchft.pb.h"

namespace tft {

struct LighthouseOpt {
  int64_t join_timeout_ms = 60000;
  uint64_t min_replicas = 1;
  int64_t quorum_tick_ms = 100;
  int64_t heartbeat_timeout_ms = 5000;
};

struct ParticipantDetails {
  int64_t joined_ms = 0;
  torchft_tpu::QuorumMember member;
};

// Mutable lighthouse state guarded by the caller's lock.
// Reference: src/lighthouse.rs:48-57 (State).
struct LighthouseState {
  std::map<std::string, ParticipantDetails> participants;
  std::optional<torchft_tpu::Quorum> prev_quorum;
  int64_t quorum_id = 0;
  std::map<std::string, int64_t> heartbeats; // replica_id -> last now_ms()
  // Dashboard telemetry (reference templates/status.html shows live
  // per-member recovery state; here membership/heal transitions are also
  // kept as a short event log).
  int64_t quorum_formed_ms = -1;            // now_ms() of last quorum_id bump
  std::deque<std::string> events;           // newest first, capped
};

// True iff membership (the ordered list of replica ids) differs.
// Reference: src/lighthouse.rs:105-110.
bool quorum_changed(const std::vector<torchft_tpu::QuorumMember>& a,
                    const std::vector<torchft_tpu::QuorumMember>& b);

// Decides whether a quorum can be formed right now. Returns the participant
// list (sorted by replica_id) when one can, plus a human-readable reason
// either way. Reference: src/lighthouse.rs:113-241.
std::pair<std::optional<std::vector<torchft_tpu::QuorumMember>>, std::string>
quorum_compute(int64_t now, const LighthouseState& state, const LighthouseOpt& opt);

// Per-rank view of a quorum: replica rank, max-step cohort, primary store,
// round-robin recovery assignments. Throws std::runtime_error if replica_id is
// not in the quorum. Reference: src/manager.rs:357-480.
torchft_tpu::ManagerQuorumResponse compute_quorum_results(
    const std::string& replica_id, int64_t rank, const torchft_tpu::Quorum& quorum);

// ---- JSON conversions (C-API boundary + pure-function test entry points) ----

Json member_to_json(const torchft_tpu::QuorumMember& m);
torchft_tpu::QuorumMember member_from_json(const Json& j);
Json quorum_to_json(const torchft_tpu::Quorum& q);
torchft_tpu::Quorum quorum_from_json(const Json& j);
Json quorum_response_to_json(const torchft_tpu::ManagerQuorumResponse& r);
LighthouseState lighthouse_state_from_json(const Json& j);
LighthouseOpt lighthouse_opt_from_json(const Json& j);

} // namespace tft
