// Minimal JSON value type used only at the C-API boundary (structured results
// and pure-function test entry points). The wire protocol is protobuf
// (native/torchft.proto); JSON keeps the Python binding dependency-free.
#pragma once

#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace tft {

class Json;
using JsonArray = std::vector<Json>;
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Int, Double, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Int), int_(v) {}
  Json(int64_t v) : type_(Type::Int), int_(v) {}
  Json(uint64_t v) : type_(Type::Int), int_(static_cast<int64_t>(v)) {}
  Json(double v) : type_(Type::Double), double_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }

  bool as_bool() const {
    check(Type::Bool);
    return bool_;
  }
  int64_t as_int() const {
    if (type_ == Type::Double) return static_cast<int64_t>(double_);
    check(Type::Int);
    return int_;
  }
  double as_double() const {
    if (type_ == Type::Int) return static_cast<double>(int_);
    check(Type::Double);
    return double_;
  }
  const std::string& as_string() const {
    check(Type::String);
    return str_;
  }
  const JsonArray& as_array() const {
    check(Type::Array);
    return arr_;
  }
  JsonArray& as_array() {
    check(Type::Array);
    return arr_;
  }
  const JsonObject& as_object() const {
    check(Type::Object);
    return obj_;
  }
  JsonObject& as_object() {
    check(Type::Object);
    return obj_;
  }

  bool contains(const std::string& key) const {
    check(Type::Object);
    return obj_.count(key) > 0;
  }
  // Missing keys read as null, so optional fields need no special casing.
  const Json& at(const std::string& key) const {
    check(Type::Object);
    auto it = obj_.find(key);
    if (it == obj_.end()) {
      static const Json kNull;
      return kNull;
    }
    return it->second;
  }
  int64_t get_int(const std::string& key, int64_t dflt) const {
    const Json& v = at(key);
    return v.is_null() ? dflt : v.as_int();
  }
  std::string get_string(const std::string& key, const std::string& dflt) const {
    const Json& v = at(key);
    return v.is_null() ? dflt : v.as_string();
  }
  bool get_bool(const std::string& key, bool dflt) const {
    const Json& v = at(key);
    return v.is_null() ? dflt : v.as_bool();
  }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size()) throw std::runtime_error("json: trailing data");
    return v;
  }

 private:
  void check(Type t) const {
    if (type_ != t) throw std::runtime_error("json: wrong type access");
  }

  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::Null:
        os << "null";
        break;
      case Type::Bool:
        os << (bool_ ? "true" : "false");
        break;
      case Type::Int:
        os << int_;
        break;
      case Type::Double:
        if (std::isfinite(double_)) {
          os << double_;
        } else {
          os << "null";
        }
        break;
      case Type::String:
        write_string(os, str_);
        break;
      case Type::Array: {
        os << '[';
        bool first = true;
        for (const auto& v : arr_) {
          if (!first) os << ',';
          first = false;
          v.write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& [k, v] : obj_) {
          if (!first) os << ',';
          first = false;
          write_string(os, k);
          os << ':';
          v.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"':
          os << "\\\"";
          break;
        case '\\':
          os << "\\\\";
          break;
        case '\n':
          os << "\\n";
          break;
        case '\r':
          os << "\\r";
          break;
        case '\t':
          os << "\\t";
          break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  static void skip_ws(const std::string& t, size_t& pos) {
    while (pos < t.size() &&
           (t[pos] == ' ' || t[pos] == '\t' || t[pos] == '\n' || t[pos] == '\r'))
      pos++;
  }

  static Json parse_value(const std::string& t, size_t& pos) {
    skip_ws(t, pos);
    if (pos >= t.size()) throw std::runtime_error("json: unexpected end");
    char c = t[pos];
    if (c == '{') return parse_object(t, pos);
    if (c == '[') return parse_array(t, pos);
    if (c == '"') return Json(parse_string(t, pos));
    if (c == 't') {
      expect(t, pos, "true");
      return Json(true);
    }
    if (c == 'f') {
      expect(t, pos, "false");
      return Json(false);
    }
    if (c == 'n') {
      expect(t, pos, "null");
      return Json();
    }
    return parse_number(t, pos);
  }

  static void expect(const std::string& t, size_t& pos, const char* lit) {
    size_t n = strlen(lit);
    if (t.compare(pos, n, lit) != 0) throw std::runtime_error("json: bad literal");
    pos += n;
  }

  static Json parse_number(const std::string& t, size_t& pos) {
    size_t start = pos;
    bool is_double = false;
    if (pos < t.size() && (t[pos] == '-' || t[pos] == '+')) pos++;
    while (pos < t.size() &&
           (isdigit(t[pos]) || t[pos] == '.' || t[pos] == 'e' || t[pos] == 'E' ||
            t[pos] == '-' || t[pos] == '+')) {
      if (t[pos] == '.' || t[pos] == 'e' || t[pos] == 'E') is_double = true;
      pos++;
    }
    std::string num = t.substr(start, pos - start);
    if (num.empty()) throw std::runtime_error("json: bad number");
    if (is_double) return Json(std::stod(num));
    return Json(static_cast<int64_t>(std::stoll(num)));
  }

  static std::string parse_string(const std::string& t, size_t& pos) {
    if (t[pos] != '"') throw std::runtime_error("json: expected string");
    pos++;
    std::string out;
    while (pos < t.size() && t[pos] != '"') {
      char c = t[pos];
      if (c == '\\') {
        pos++;
        if (pos >= t.size()) throw std::runtime_error("json: bad escape");
        char e = t[pos];
        switch (e) {
          case '"':
            out += '"';
            break;
          case '\\':
            out += '\\';
            break;
          case '/':
            out += '/';
            break;
          case 'n':
            out += '\n';
            break;
          case 'r':
            out += '\r';
            break;
          case 't':
            out += '\t';
            break;
          case 'b':
            out += '\b';
            break;
          case 'f':
            out += '\f';
            break;
          case 'u': {
            if (pos + 4 >= t.size()) throw std::runtime_error("json: bad \\u");
            unsigned int cp = std::stoul(t.substr(pos + 1, 4), nullptr, 16);
            pos += 4;
            // Encode BMP code point as UTF-8 (surrogate pairs unsupported;
            // control-plane strings are ASCII identifiers/addresses).
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            throw std::runtime_error("json: bad escape char");
        }
        pos++;
      } else {
        out += c;
        pos++;
      }
    }
    if (pos >= t.size()) throw std::runtime_error("json: unterminated string");
    pos++; // closing quote
    return out;
  }

  static Json parse_array(const std::string& t, size_t& pos) {
    pos++; // '['
    JsonArray arr;
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == ']') {
      pos++;
      return Json(std::move(arr));
    }
    while (true) {
      arr.push_back(parse_value(t, pos));
      skip_ws(t, pos);
      if (pos >= t.size()) throw std::runtime_error("json: unterminated array");
      if (t[pos] == ',') {
        pos++;
        continue;
      }
      if (t[pos] == ']') {
        pos++;
        return Json(std::move(arr));
      }
      throw std::runtime_error("json: bad array");
    }
  }

  static Json parse_object(const std::string& t, size_t& pos) {
    pos++; // '{'
    JsonObject obj;
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == '}') {
      pos++;
      return Json(std::move(obj));
    }
    while (true) {
      skip_ws(t, pos);
      std::string key = parse_string(t, pos);
      skip_ws(t, pos);
      if (pos >= t.size() || t[pos] != ':') throw std::runtime_error("json: bad object");
      pos++;
      obj[key] = parse_value(t, pos);
      skip_ws(t, pos);
      if (pos >= t.size()) throw std::runtime_error("json: unterminated object");
      if (t[pos] == ',') {
        pos++;
        continue;
      }
      if (t[pos] == '}') {
        pos++;
        return Json(std::move(obj));
      }
      throw std::runtime_error("json: bad object");
    }
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

} // namespace tft
