from torchft_tpu.models import cnn, moe
from torchft_tpu.models.cnn import CNNConfig, tiny_cnn_config
from torchft_tpu.models.moe import MoEConfig, tiny_moe_config
from torchft_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    param_sharding_rules,
    tiny_config,
)

__all__ = [
    "CNNConfig",
    "MoEConfig",
    "TransformerConfig",
    "cnn",
    "tiny_cnn_config",
    "forward",
    "init_params",
    "loss_fn",
    "make_train_step",
    "moe",
    "param_sharding_rules",
    "tiny_config",
    "tiny_moe_config",
]
