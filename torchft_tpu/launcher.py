"""Replica-group launcher: one supervised process per replica group.

The reference ships a torchx component producing one torchrun role per
replica group with ``max_restarts=10`` and the fault-tolerance env plumbed
through (reference torchft/torchx.py:27-76); process-level restart is
delegated to torchelastic (reference torchx.py:54). This module plays both
parts for TPU deployments: ``replica_group_spec`` emits the command + env
for external schedulers (GKE/xpk-style), and ``launch``/the CLI supervise
locally with restart-on-failure — the restart half of the recovery story
(the healing half is the Manager's).

CLI::

    python -m torchft_tpu.launcher --num-replica-groups 2 -- \
        python examples/train_ddp.py
"""

from __future__ import annotations

import argparse
import logging
import os
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

logger = logging.getLogger(__name__)


def replica_group_spec(
    cmd: Sequence[str],
    replica_group: int,
    num_replica_groups: int,
    lighthouse_addr: str,
    env: Optional[Dict[str, str]] = None,
    max_restarts: int = 10,
) -> Dict[str, object]:
    """Process spec for one replica group (the reference's torchx role,
    torchx.py:37-69): command, env, and restart budget."""
    spec_env = {
        "TORCHFT_LIGHTHOUSE": lighthouse_addr,
        "REPLICA_GROUP_ID": str(replica_group),
        "NUM_REPLICA_GROUPS": str(num_replica_groups),
        # Shared persistent jit cache: a RESTARTED group reloads the
        # executables compiled before it died instead of re-jitting, the
        # main lever on heal latency (platform.apply_compilation_cache_env;
        # entry scripts opt in by calling it). Overridable; "0" disables.
        "TORCHFT_COMPILE_CACHE": os.environ.get(
            "TORCHFT_COMPILE_CACHE",
            os.path.join(
                os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
                "torchft_tpu", "jax_cache",
            ),
        ),
        **(env or {}),
    }
    return {
        "name": f"replica_group_{replica_group}",
        "cmd": list(cmd),
        "env": spec_env,
        "max_restarts": max_restarts,
    }


@dataclass
class _Supervised:
    spec: Dict[str, object]
    proc: Optional[subprocess.Popen] = None
    restarts: int = 0
    returncode: Optional[int] = None


def launch(
    cmd: Sequence[str],
    num_replica_groups: int,
    lighthouse_addr: str,
    max_restarts: int = 10,
    env: Optional[Dict[str, str]] = None,
) -> int:
    """Runs one process per replica group locally, restarting any that exit
    non-zero up to ``max_restarts`` times (torchelastic's role in the
    reference stack). Returns 0 iff every group eventually exited cleanly."""
    groups = [
        _Supervised(
            replica_group_spec(
                cmd, g, num_replica_groups, lighthouse_addr, env, max_restarts
            )
        )
        for g in range(num_replica_groups)
    ]

    def spawn(s: _Supervised) -> None:
        full_env = {**os.environ, **s.spec["env"]}  # type: ignore[arg-type]
        s.proc = subprocess.Popen(list(s.spec["cmd"]), env=full_env)  # type: ignore[arg-type]
        logger.info(f"{s.spec['name']}: started pid {s.proc.pid}")

    for s in groups:
        spawn(s)

    try:
        while True:
            running = 0
            for s in groups:
                if s.returncode is not None or s.proc is None:
                    continue
                rc = s.proc.poll()
                if rc is None:
                    running += 1
                elif rc == 0:
                    s.returncode = 0
                    logger.info(f"{s.spec['name']}: exited cleanly")
                elif s.restarts < int(s.spec["max_restarts"]):  # type: ignore[arg-type]
                    s.restarts += 1
                    logger.warning(
                        f"{s.spec['name']}: exited rc={rc}, restart "
                        f"{s.restarts}/{s.spec['max_restarts']}"
                    )
                    spawn(s)
                    running += 1
                else:
                    s.returncode = rc
                    logger.error(
                        f"{s.spec['name']}: exhausted restarts (rc={rc}); "
                        "failing the job"
                    )
                    # A permanently failed group fails the whole job
                    # (torchelastic semantics): survivors could otherwise
                    # block forever in quorum waiting for it.
                    for other in groups:
                        if other.proc is not None and other.proc.poll() is None:
                            other.proc.terminate()
            if running == 0:
                break
            time.sleep(0.2)
    except KeyboardInterrupt:
        for s in groups:
            if s.proc is not None and s.proc.poll() is None:
                s.proc.terminate()
        raise
    return 0 if all(s.returncode == 0 for s in groups) else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="torchft_tpu.launcher",
        description="Launch one supervised process per replica group.",
    )
    parser.add_argument("--num-replica-groups", type=int, default=2)
    parser.add_argument(
        "--lighthouse",
        default=os.environ.get("TORCHFT_LIGHTHOUSE", ""),
        help="lighthouse address; spawns an in-process one when omitted",
    )
    parser.add_argument("--max-restarts", type=int, default=10)
    parser.add_argument("cmd", nargs="+", help="command to run per group")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    lighthouse = None
    lighthouse_addr = args.lighthouse
    if not lighthouse_addr:
        from . import _native

        lighthouse = _native.Lighthouse(bind="[::]:0", min_replicas=1)
        lighthouse_addr = lighthouse.address()
        logger.info(f"started lighthouse at {lighthouse_addr}")
    try:
        return launch(
            args.cmd,
            num_replica_groups=args.num_replica_groups,
            lighthouse_addr=lighthouse_addr,
            max_restarts=args.max_restarts,
        )
    finally:
        if lighthouse is not None:
            lighthouse.shutdown()


if __name__ == "__main__":
    sys.exit(main())
