#include "store.h"

#include <sys/socket.h>

#include <functional>

#include "wire.h"

namespace tft {

using torchft_tpu::ErrorResponse;

StoreServer::StoreServer(const std::string& bind_addr)
    : listener_(std::make_unique<Listener>(bind_addr)),
      hostname_(local_hostname()) {
  accept_thread_ = std::thread([this] { serve(); });
}

StoreServer::~StoreServer() { shutdown(); }

uint16_t StoreServer::port() const { return listener_->port(); }

std::string StoreServer::address() const {
  return hostname_ + ":" + std::to_string(listener_->port());
}

void StoreServer::shutdown() {
  {
    // Flag + notify under the cv's mutex so waiters can't miss the wakeup.
    std::lock_guard<std::mutex> lock(mu_);
    if (shutting_down_.exchange(true)) return;
    cv_.notify_all();
  }
  listener_->close();
  if (accept_thread_.joinable()) accept_thread_.join();
  conns_.shutdown_all();
}

void StoreServer::serve() {
  while (true) {
    Socket sock = listener_->accept();
    if (!sock.valid()) return; // shut down
    conns_.spawn(std::move(sock), [this](Socket& s) { handle_conn(s); });
  }
}

void StoreServer::handle_conn(Socket& sock) {
  try {
    while (true) {
      auto [type, payload] = recv_frame(sock);
      switch (type) {
        case MsgType::kStoreSetReq: {
          torchft_tpu::StoreSetRequest req;
          req.ParseFromString(payload);
          {
            std::lock_guard<std::mutex> lock(mu_);
            data_[req.key()] = req.value();
          }
          cv_.notify_all();
          send_msg(sock, MsgType::kStoreSetResp, torchft_tpu::StoreSetResponse());
          break;
        }
        case MsgType::kStoreGetReq: {
          torchft_tpu::StoreGetRequest req;
          req.ParseFromString(payload);
          int64_t deadline =
              req.timeout_ms() < 0 ? -1 : now_ms() + req.timeout_ms();
          std::unique_lock<std::mutex> lock(mu_);
          bool timed_out = false;
          while (!data_.count(req.key()) && !shutting_down_) {
            if (deadline < 0) {
              cv_.wait(lock);
            } else {
              int64_t remain = deadline - now_ms();
              if (remain <= 0) {
                timed_out = true;
                break;
              }
              cv_.wait_for(lock, std::chrono::milliseconds(remain));
            }
          }
          if (!data_.count(req.key())) {
            bool cancelled = shutting_down_ && !timed_out;
            lock.unlock();
            if (cancelled) {
              send_error(sock, ErrorResponse::CANCELLED, "store shutting down");
            } else {
              send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                         "timed out waiting for key " + req.key());
            }
            break;
          }
          torchft_tpu::StoreGetResponse resp;
          resp.set_value(data_[req.key()]);
          lock.unlock();
          send_msg(sock, MsgType::kStoreGetResp, resp);
          break;
        }
        case MsgType::kStoreAddReq: {
          torchft_tpu::StoreAddRequest req;
          req.ParseFromString(payload);
          int64_t value;
          {
            std::unique_lock<std::mutex> lock(mu_);
            std::string& cur = data_[req.key()];
            int64_t v = 0;
            if (!cur.empty()) {
              try {
                v = std::stoll(cur);
              } catch (const std::exception&) {
                lock.unlock();
                send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                           "add on non-numeric key " + req.key());
                break;
              }
            }
            v += req.delta();
            cur = std::to_string(v);
            value = v;
          }
          cv_.notify_all();
          torchft_tpu::StoreAddResponse resp;
          resp.set_value(value);
          send_msg(sock, MsgType::kStoreAddResp, resp);
          break;
        }
        default:
          send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad store request");
          return;
      }
    }
  } catch (const std::exception&) {
    // connection closed or reset; drop it
  }
}

StoreClient::StoreClient(const std::string& addr, int64_t connect_timeout_ms)
    : addr_(addr), connect_timeout_ms_(connect_timeout_ms) {
  reconnect();
}

void StoreClient::reconnect() {
  sock_ = connect_with_retry(addr_, connect_timeout_ms_);
}

namespace {

// One request/response on a persistent connection. A SocketError before the
// request was fully sent triggers one reconnect+resend (store ops are
// idempotent); any failure after that — including a client-side timeout, which
// leaves an unconsumed response in flight — invalidates the socket so the next
// op starts on a fresh connection instead of reading a stale frame.
template <typename Req, typename Resp>
Resp store_roundtrip(Socket& sock, const std::function<void()>& reconnect,
                     MsgType req_type, const Req& req, MsgType resp_type,
                     int64_t timeout_ms) {
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  try {
    if (!sock.valid()) reconnect();
    try {
      send_msg(sock, req_type, req, deadline);
    } catch (const SocketError&) {
      reconnect();
      send_msg(sock, req_type, req, deadline);
    }
    return recv_expect<Resp>(sock, resp_type, deadline);
  } catch (const TimeoutError&) {
    sock.close();
    throw;
  } catch (const SocketError&) {
    sock.close();
    throw;
  }
}

} // namespace

void StoreClient::set(const std::string& key, const std::string& value,
                      int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(mu_);
  torchft_tpu::StoreSetRequest req;
  req.set_key(key);
  req.set_value(value);
  store_roundtrip<torchft_tpu::StoreSetRequest, torchft_tpu::StoreSetResponse>(
      sock_, [this] { reconnect(); }, MsgType::kStoreSetReq, req,
      MsgType::kStoreSetResp, timeout_ms);
}

std::string StoreClient::get(const std::string& key, int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(mu_);
  torchft_tpu::StoreGetRequest req;
  req.set_key(key);
  req.set_timeout_ms(timeout_ms);
  return store_roundtrip<torchft_tpu::StoreGetRequest,
                         torchft_tpu::StoreGetResponse>(
             sock_, [this] { reconnect(); }, MsgType::kStoreGetReq, req,
             MsgType::kStoreGetResp, timeout_ms)
      .value();
}

int64_t StoreClient::add(const std::string& key, int64_t delta, int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(mu_);
  torchft_tpu::StoreAddRequest req;
  req.set_key(key);
  req.set_delta(delta);
  return store_roundtrip<torchft_tpu::StoreAddRequest,
                         torchft_tpu::StoreAddResponse>(
             sock_, [this] { reconnect(); }, MsgType::kStoreAddReq, req,
             MsgType::kStoreAddResp, timeout_ms)
      .value();
}

} // namespace tft
