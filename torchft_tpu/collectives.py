"""Reconfigurable collective communication for cross-replica-group traffic.

Plays the role of the reference's reconfigurable ProcessGroup abstraction
(reference torchft/process_group.py:109-166): a ``Collectives`` object can be
``configure()``d onto a new membership every time the quorum changes, using a
per-quorum store prefix so stale members never cross-talk (reference
torchft/manager.py:470-477).

TPU-first design: these collectives deliberately run on the HOST, outside
XLA. Intra-replica-group parallelism (the HSDP "shard" dimension) belongs to
pjit/``shard_map`` over the slice's ICI mesh and never spans a failure
domain; only the cross-group gradient average travels through this layer
(over DCN in production). Because the transport is plain sockets, a dead
replica group surfaces as an abortable socket error instead of a wedged
device collective — the property the reference buys with subprocess-isolated
NCCL ("Baby" process groups, reference torchft/process_group.py:551-1064).

Ops are asynchronous: each returns a :class:`Work` whose result is the
reduced pytree. A single-thread executor issues ops in submission order (the
ordering contract collective backends require), and the GIL is released for
the duration of each native call.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from abc import ABC, abstractmethod
from concurrent.futures import Future, ThreadPoolExecutor
from datetime import timedelta
from enum import IntEnum
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from . import _native
from ._native import _check, _lib, _ms


class ReduceOp(IntEnum):
    """Matches tft::ReduceOp in native/src/collectives.h. AVG is SUM followed
    by a host-side divide (the reference divides in the manager too,
    torchft/manager.py:279-291)."""

    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 100


# Native dtype codes (tft::Dtype). Other dtypes (e.g. f16) are accumulated
# in f32 and cast back. bfloat16 ships natively — 2 bytes on the wire, half
# the DCN traffic of an f32 upcast; reduction math is f32 per ring hop with
# round-to-nearest-even back to bf16 (for long-chain exact accumulation,
# cast leaves to f32 before the allreduce).
import ml_dtypes

_BF16 = np.dtype(ml_dtypes.bfloat16)
_NATIVE_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    _BF16: 4,
}


class Work:
    """Handle for an async collective; the result is the output pytree.

    Mirrors the role of torch.distributed Work / torch futures in the
    reference (torchft/process_group.py:318-330).
    """

    def __init__(self, future: "Future[Any]") -> None:
        self._future = future

    def wait(self, timeout: Optional[timedelta] = None) -> Any:
        return self._future.result(
            timeout=timeout.total_seconds() if timeout is not None else None
        )

    def result(self, timeout: Optional[timedelta] = None) -> Any:
        return self.wait(timeout)

    def done(self) -> bool:
        return self._future.done()

    def exception(self) -> Optional[BaseException]:
        return self._future.exception()

    def add_done_callback(self, fn: Callable[["Future[Any]"], None]) -> None:
        self._future.add_done_callback(fn)

    def then(self, fn: Callable[[Any], Any]) -> "Work":
        """Returns a Work whose result is fn(result); errors propagate."""
        out: "Future[Any]" = Future()

        def _chain(f: "Future[Any]") -> None:
            exc = f.exception()
            if exc is not None:
                out.set_exception(exc)
                return
            try:
                out.set_result(fn(f.result()))
            except Exception as e:  # noqa: BLE001 - propagate into future
                out.set_exception(e)

        self._future.add_done_callback(_chain)
        return Work(out)


def _completed(value: Any) -> Work:
    f: "Future[Any]" = Future()
    f.set_result(value)
    return Work(f)


def _divide_leaf(leaf: Any, divisor: float) -> Any:
    """Same-dtype divide for the divisor/AVG contract: integers
    floor-divide (matching the multi-member ring), floats keep their
    dtype. Handles numpy and jax leaves alike."""
    dtype = np.dtype(getattr(leaf, "dtype", np.float64))
    if np.issubdtype(dtype, np.integer):
        return leaf // int(divisor)
    return (leaf / divisor).astype(dtype)


def _flatten(tree: Any) -> Tuple[List[Any], Any]:
    """Flatten a pytree without importing jax at module load."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def _unflatten(treedef: Any, leaves: Sequence[Any]) -> Any:
    import jax

    return jax.tree_util.tree_unflatten(treedef, leaves)


class Collectives(ABC):
    """Reconfigurable collectives over replica groups.

    Reference interface: torchft/process_group.py:109-166 (configure /
    allreduce / allgather / broadcast / size).
    """

    @abstractmethod
    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        """(Re)builds the communicator for a new membership. ``store_addr``
        is ``host:port/prefix`` with a prefix unique to the quorum."""

    @abstractmethod
    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        """Reduces a pytree of arrays across the group; result pytree has the
        same structure/dtypes. Bit-identical on every rank.

        ``divisor`` (SUM only) divides the reduced result before it returns
        — the manager's num_participants average, applied host-side where
        the data already is, so no extra device dispatch or jit program is
        needed. ``op=AVG`` is equivalent to SUM with divisor=world_size.

        ``wire="q8"`` (SUM/AVG only): ship int8-quantized chunks with
        per-chunk f32 scales through the ring, dequant-accumulating per
        hop — ~4x fewer wire bytes than f32, CONSTANT in world size
        (unlike a quantized allgather's O(world) traffic). The result is
        lossy at the int8 quantization class; callers doing error
        feedback should treat the RETURNED tree as what was shipped.
        Implementations without a quantized wire may raise for it."""

    @abstractmethod
    def allgather(self, tree: Any) -> Work:
        """Gathers each rank's pytree; result is a list of pytrees in rank
        order (all ranks must pass identical structures and shapes)."""

    @abstractmethod
    def broadcast(self, tree: Any, root: int = 0) -> Work:
        """Broadcasts root's pytree to all ranks."""

    @abstractmethod
    def barrier(self) -> Work:
        ...

    @abstractmethod
    def size(self) -> int:
        ...

    @abstractmethod
    def rank(self) -> int:
        ...

    def abort(self) -> None:
        """Unblocks in-flight ops with an error (safe from any thread)."""

    def shutdown(self) -> None:
        ...


# Cap on the per-stripe timing readback; matches tft::kMaxStripes.
_MAX_STRIPES = 64


def _as_numpy(leaf: Any) -> np.ndarray:
    """Host copy of a leaf (device→host transfer for jax arrays)."""
    return np.asarray(leaf)


def _is_jax_array(leaf: Any) -> bool:
    import jax

    return isinstance(leaf, jax.Array)


class _DevicePacker:
    """Jitted pack/unpack of a fixed tree signature into ONE flat buffer per
    accumulation dtype.

    Per-transfer latency dominates device↔host links (PCIe DMA setup; far
    worse on tunneled devices), so shipping ~100 gradient leaves
    individually costs ~100 round-trips. Packing on-device via a jitted
    concatenate makes the whole pytree cross as one transfer per dtype
    group, and unpacking (split + reshape + cast back) stays on-device too.
    """

    def __init__(
        self,
        leaves: Sequence[Any],
        exact_dtypes: bool = False,
        force_f32: bool = False,
    ) -> None:
        """``exact_dtypes``: group by each leaf's own dtype with no
        casting — for BYTE-PRESERVING ops (allgather ships opaque bytes,
        e.g. int8-quantized payloads, where upcasting to an accumulation
        dtype would 4x the wire). ``force_f32``: ONE f32 group for the
        whole tree — the quantized (q8) ring reduces a single flat f32
        buffer. Reduction ops keep the default accumulation-dtype
        grouping (the ring arithmetic needs native dtypes)."""
        import jax
        import jax.numpy as jnp

        assert not (exact_dtypes and force_f32)
        self.sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        groups: dict = {}
        for i, (_, dt) in enumerate(self.sig):
            if force_f32:
                acc = np.dtype(np.float32)
            elif exact_dtypes:
                acc = dt
            else:
                acc = dt if dt in _NATIVE_DTYPES else np.dtype(np.float32)
            groups.setdefault(acc, []).append(i)
        self.groups = groups
        sig = self.sig

        def pack(ls):
            return {
                str(acc): jnp.concatenate(
                    [ls[i].ravel().astype(acc) for i in idxs]
                )
                for acc, idxs in groups.items()
            }

        def unpack(bufs):
            out = [None] * len(sig)
            for acc, idxs in groups.items():
                buf = bufs[str(acc)]
                off = 0
                for i in idxs:
                    shape, dt = sig[i]
                    n = int(np.prod(shape)) if shape else 1
                    out[i] = buf[off : off + n].reshape(shape).astype(dt)
                    off += n
            return out

        self.pack = jax.jit(pack)
        self.unpack = jax.jit(unpack)


class HostCollectives(Collectives):
    """Deterministic TCP ring collectives (native C++), the Gloo role.

    One contiguous buffer per dtype group is reduced per op — leaves are
    packed ON DEVICE (jitted concatenate, one device↔host transfer per
    dtype group) when the tree is jax arrays, host-side otherwise — so a
    whole gradient pytree costs a single ring pass per dtype (the bucketing
    the reference gets from DDP's reducer).
    """

    def __init__(
        self,
        timeout: timedelta = timedelta(seconds=60),
        connect_timeout: timedelta = timedelta(seconds=60),
        pipeline_chunks: Optional[int] = None,
        pipeline_min_bytes: int = 4 << 20,
        stripes: Optional[int] = None,
    ) -> None:
        """``pipeline_chunks`` > 1 splits large device-packed buffers so
        device->host DMA, the TCP ring, and host->device upload overlap
        (chunk i rides the ring while chunk i+1 is still downloading and
        chunk i-1 re-uploads — and the pipeline runs ACROSS dtype buckets,
        not just within one packed buffer). Buffers under
        ``pipeline_min_bytes`` take the single-shot path — per-transfer
        latency would beat the overlap. Chunk boundaries depend only on
        size, so results stay bit-identical across ranks and against the
        unchunked path.

        Default: env ``TORCHFT_HC_PIPELINE_CHUNKS`` (else 4). Set it to 1
        on hosts whose device runtime wedges in-flight transfers under
        overlapping async dispatch (observed on tunneled/proxied device
        sessions) — every member of a ring must use the same value.

        ``stripes`` > 1 spreads every ring op over that many parallel TCP
        connections per neighbor (contiguous payload sub-ranges, one
        reducer thread per stripe) — a single TCP connection is
        window-limited on high-bandwidth-delay links, so striping
        multiplies achievable cross-group throughput the way NCCL
        channels do. Default: env ``TORCHFT_HC_STRIPES`` (else 4). Every
        member of a ring must use the same value; configure() negotiates
        it through the rendezvous store (exactly like the pipeline knobs)
        and fails fast on a mismatch."""
        self._handle = _lib.tft_hc_create()
        self._timeout = timeout
        self._connect_timeout = connect_timeout
        if pipeline_chunks is None:
            pipeline_chunks = int(
                os.environ.get("TORCHFT_HC_PIPELINE_CHUNKS", "4")
            )
        self._pipeline_chunks = max(int(pipeline_chunks), 1)
        self._pipeline_min_bytes = int(pipeline_min_bytes)
        if stripes is None:
            stripes = int(os.environ.get("TORCHFT_HC_STRIPES", "4"))
        self._stripes = min(max(int(stripes), 1), _MAX_STRIPES)
        self._world_size = 0
        self._rank = -1
        # One thread: collectives must issue in submission order.
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="host_collectives"
        )
        self._shutdown = False
        self._packers: dict = {}
        # Per-op phase timings recorded by the device-packed paths (see
        # pop_op_stats): on tunneled device runtimes the d2h leg can cost
        # 10x the ring leg, and nothing else distinguishes them.
        self._op_stats: List[dict] = []

    def _record_op_stats(self, stats: dict) -> None:
        self._op_stats.append(stats)
        # Bounded: diagnostics, not a log. 256 keeps a full per-step
        # breakdown window alive — at one gradient op + a handful of
        # control ops per step, 64 silently dropped the early entries
        # before the caller's median ever saw them.
        del self._op_stats[:-256]

    def _last_stripe_seconds(self) -> List[float]:
        """Per-stripe wall times (s) of the last native ring op; safe only
        on the op-executor thread (which is where all ring calls run)."""
        buf = (ctypes.c_int64 * _MAX_STRIPES)()
        n = _lib.tft_hc_last_stripe_ns(self._handle, buf, _MAX_STRIPES)
        return [buf[i] / 1e9 for i in range(min(n, _MAX_STRIPES))]

    def pop_op_stats(self) -> List[dict]:
        """Drains the per-op phase timings (seconds) the device-packed
        paths recorded: ``pack`` (jitted concat dispatch), ``d2h`` (the
        blocking device→host read), ``ring`` (the native TCP op), ``h2d``
        (result upload + unpack DISPATCH — jax uploads asynchronously, so
        the actual transfer completes under the caller's next use/drain
        and is charged there, not here), plus ``bytes`` = the bytes that
        crossed the DEVICE link (``wire_bytes`` additionally, where the
        TCP wire ships a different encoding — the q8 ring sends ~1/4 of
        its f32 device payload). Bulk allreduce stats additionally carry
        ``buckets`` — the per-dtype-bucket phase breakdown of the
        cross-buffer op schedule, each with ``stripe_s``, the per-stripe
        ring wall times (a skewed stripe means one of the parallel
        connections is degraded). The numbers that tell a slow
        collective's transfer cost from its wire cost — per-step DDP on a
        degraded device link is diagnosable only with this split."""
        out, self._op_stats = self._op_stats, []
        return out

    # -- lifecycle --

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        # Abort synchronously so a wedged op can't block the executor, then
        # run the (blocking) rendezvous on the op thread to keep ordering.
        _lib.tft_hc_abort(self._handle)

        def do_configure() -> None:
            # The pipeline parameters are part of the ring's op schedule
            # (they decide how many native allreduce calls one logical
            # allreduce issues, and the wire has no per-op framing), so
            # every member must agree — validate against rank 0's via the
            # rendezvous store and fail fast instead of desyncing. A solo
            # member has no peers (and possibly no real store) to check.
            if world_size > 1:
                hostport, _, prefix = store_addr.partition("/")
                store = _native.StoreClient(
                    hostport, connect_timeout=self._connect_timeout
                )
                mine = (
                    f"{self._pipeline_chunks}:{self._pipeline_min_bytes}"
                    f":{self._stripes}"
                )
                key = f"{prefix}/pipecfg" if prefix else "pipecfg"
                if rank == 0:
                    store.set(key, mine.encode())
                else:
                    theirs = store.get(
                        key, timeout=self._connect_timeout
                    ).decode()
                    if theirs != mine:
                        raise RuntimeError(
                            f"pipeline config mismatch: rank {rank} has "
                            f"{mine}, rank 0 has {theirs} — all ring members "
                            "must construct HostCollectives with the same "
                            "pipeline_chunks / pipeline_min_bytes / stripes"
                        )
            _check(
                _lib.tft_hc_configure(
                    self._handle,
                    store_addr.encode(),
                    rank,
                    world_size,
                    _ms(self._connect_timeout),
                    self._stripes,
                )
            )
            # Assign on the op thread: ops queued after this configure see
            # the new size, earlier ones the old — never a mix.
            self._rank = rank
            self._world_size = world_size

        self._executor.submit(do_configure).result()

    def abort(self) -> None:
        _lib.tft_hc_abort(self._handle)

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        _lib.tft_hc_abort(self._handle)
        self._executor.shutdown(wait=True)

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle and _lib is not None:
            try:
                self.shutdown()  # aborts + drains the executor, handle intact
            except Exception:
                pass
            self._handle = None
            _lib.tft_hc_destroy(handle)

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    # -- ops --

    def _submit(self, fn: Callable[[], Any]) -> Work:
        if self._shutdown:
            raise RuntimeError("collectives already shut down")
        return Work(self._executor.submit(fn))

    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        timeout_ms = _ms(self._timeout)
        if wire not in (None, "q8"):
            raise ValueError(f"unsupported wire: {wire!r}")
        if wire == "q8":
            if op == ReduceOp.AVG:
                divisor, op = float(self._world_size), ReduceOp.SUM
            if op != ReduceOp.SUM:
                raise ValueError("wire='q8' supports SUM/AVG only")
            return self._submit(
                lambda: self._allreduce_q8_sync(tree, divisor, timeout_ms)
            )
        return self._submit(
            lambda: self._allreduce_sync(tree, op, timeout_ms, divisor)
        )

    def _allreduce_q8_sync(
        self, tree: Any, divisor: Optional[float], timeout_ms: int
    ) -> Any:
        """Quantized ring SUM: the whole tree packs into ONE flat f32
        buffer (jitted on-device concat for jax leaves — one transfer per
        direction), the native ring ships int8 chunks with per-chunk
        scales, and the result unpacks to the original dtypes."""
        if self._world_size == 1:
            if divisor is not None and divisor != 1:
                import jax

                return jax.tree_util.tree_map(
                    lambda l: _divide_leaf(l, divisor)
                    if hasattr(l, "__truediv__")
                    else l,
                    tree,
                )
            return tree
        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        all_jax = all(_is_jax_array(l) for l in leaves)
        if all_jax:
            key = (
                "q8", treedef,
                tuple((l.shape, np.dtype(l.dtype)) for l in leaves),
            )
            packer = self._packers.get(key)
            if packer is None:
                packer = self._packers[key] = _DevicePacker(
                    leaves, force_f32=True
                )
            t0 = time.perf_counter()
            buf = np.asarray(packer.pack(leaves)[str(np.dtype(np.float32))])
            if not buf.flags.writeable or not buf.flags.c_contiguous:
                buf = np.array(buf)
            d2h_s = time.perf_counter() - t0
        else:
            arrays = [_as_numpy(l) for l in leaves]
            buf = np.concatenate(
                [a.astype(np.float32, copy=False).ravel() for a in arrays]
            )
        t1 = time.perf_counter()
        _check(
            _lib.tft_hc_allreduce_q8(
                self._handle,
                buf.ctypes.data_as(ctypes.c_void_p),
                buf.size,
                timeout_ms,
            )
        )
        stripe_s = self._last_stripe_seconds()
        if divisor is not None:
            buf /= divisor
        ring_s = time.perf_counter() - t1
        if all_jax:
            import jax.numpy as jnp

            out = _unflatten(
                treedef,
                packer.unpack({str(np.dtype(np.float32)): jnp.asarray(buf)}),
            )
            self._record_op_stats({
                "op": "allreduce_q8", "bytes": buf.nbytes,
                # TCP wire ships int8 chunks + per-chunk f32 scales, not
                # the f32 device payload
                "wire_bytes": buf.size,
                "d2h": d2h_s, "ring": ring_s,
                "h2d": time.perf_counter() - t1 - ring_s,
                "stripe_s": stripe_s,
            })
            return out
        out_leaves = []
        offset = 0
        for a in arrays:
            n = a.size
            out_leaves.append(
                buf[offset : offset + n]
                .reshape(a.shape)
                .astype(a.dtype, copy=False)
            )
            offset += n
        return _unflatten(treedef, out_leaves)

    def _allreduce_sync(
        self,
        tree: Any,
        op: ReduceOp,
        timeout_ms: int,
        divisor: Optional[float] = None,
    ) -> Any:
        if divisor is not None and op != ReduceOp.SUM:
            raise ValueError("divisor only composes with ReduceOp.SUM")
        if self._world_size == 1:
            # Identity-ish (SUM of one member; AVG divides by 1): skip the
            # host pack/transfer entirely — device arrays never leave HBM.
            # NOTE: single-member undivided results may ALIAS the input
            # tree (treat op results as immutable, the jax norm —
            # multi-member paths return fresh buffers).
            if divisor is not None and divisor != 1:
                import jax

                return jax.tree_util.tree_map(
                    lambda l: _divide_leaf(l, divisor)
                    if hasattr(l, "__truediv__")
                    else l,
                    tree,
                )
            return tree
        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        if op == ReduceOp.AVG:
            divisor = self._world_size
        native_op = int(ReduceOp.SUM if op == ReduceOp.AVG else op)

        if all(_is_jax_array(l) for l in leaves):
            return self._allreduce_device_packed(
                leaves, treedef, native_op, divisor, timeout_ms
            )

        arrays = [_as_numpy(l) for l in leaves]
        was_jax = [_is_jax_array(l) for l in leaves]
        # Group leaves by accumulation dtype; pack each group into one
        # contiguous buffer so the ring runs once per dtype.
        out_arrays: List[Optional[np.ndarray]] = [None] * len(arrays)
        groups: dict = {}
        for i, a in enumerate(arrays):
            acc = a.dtype if a.dtype in _NATIVE_DTYPES else np.dtype(np.float32)
            groups.setdefault(acc, []).append(i)
        for acc_dtype, idxs in groups.items():
            buf = np.concatenate(
                [arrays[i].astype(acc_dtype, copy=False).ravel() for i in idxs]
            )
            _check(
                _lib.tft_hc_allreduce(
                    self._handle,
                    buf.ctypes.data_as(ctypes.c_void_p),
                    buf.size,
                    _NATIVE_DTYPES[acc_dtype],
                    native_op,
                    timeout_ms,
                )
            )
            if divisor is not None:
                if buf.dtype == _BF16:
                    buf = (buf.astype(np.float32) / divisor).astype(_BF16)
                elif np.issubdtype(buf.dtype, np.floating):
                    buf /= divisor
                else:
                    buf //= divisor
            offset = 0
            for i in idxs:
                n = arrays[i].size
                out_arrays[i] = (
                    buf[offset : offset + n]
                    .reshape(arrays[i].shape)
                    .astype(arrays[i].dtype, copy=False)
                )
                offset += n
        out_leaves: List[Any] = []
        for i, a in enumerate(out_arrays):
            if was_jax[i]:
                import jax.numpy as jnp

                out_leaves.append(jnp.asarray(a))
            else:
                out_leaves.append(a)
        return _unflatten(treedef, out_leaves)

    def _allreduce_device_packed(
        self, leaves, treedef, native_op: int, divisor, timeout_ms: int
    ) -> Any:
        """All-jax-leaf fast path: pack on device, then pipeline the WHOLE
        op schedule — every dtype bucket's chunk DMAs are enqueued up
        front, so bucket i+1's d2h streams while bucket i rides the ring
        and bucket i-1's result re-uploads under jax's async dispatch. The
        old per-buffer pipeline drained between dtype groups; a mixed
        f32/bf16/int gradient tree paid a full pipeline fill+drain per
        group."""
        import jax.numpy as jnp

        key = (treedef, tuple((l.shape, np.dtype(l.dtype)) for l in leaves))
        packer = self._packers.get(key)
        if packer is None:
            packer = self._packers[key] = _DevicePacker(leaves)
        t_pack = time.perf_counter()
        bufs = packer.pack(leaves)
        names = sorted(bufs)  # deterministic bucket order = the op schedule

        # Chunk schedule across ALL buckets. Chunk boundaries depend only
        # on (size, pipeline config), both store-negotiated, so every rank
        # issues the identical sequence of native ring ops.
        schedule: List[Tuple[str, Any]] = []
        for name in names:
            dev = bufs[name]
            itemsize = np.dtype(dev.dtype).itemsize
            k = self._pipeline_chunks
            if k <= 1 or dev.size * itemsize < self._pipeline_min_bytes:
                schedule.append((name, dev))
            else:
                bounds = [dev.size * i // k for i in range(k + 1)]
                schedule.extend(
                    (name, dev[a:b]) for a, b in zip(bounds, bounds[1:])
                )
        for _, c in schedule:
            c.copy_to_host_async()  # queue every DMA before the first block
        pack_s = time.perf_counter() - t_pack

        out_chunks: dict = {name: [] for name in names}
        buckets: dict = {
            name: {"bytes": 0, "d2h": 0.0, "ring": 0.0, "h2d": 0.0,
                   "stripe_s": [], "stripe_wall": 0.0}
            for name in names
        }
        for name, c in schedule:
            st = buckets[name]
            t0 = time.perf_counter()
            arr = np.asarray(c)  # completes when THIS chunk's DMA lands
            if not arr.flags.writeable or not arr.flags.c_contiguous:
                arr = np.array(arr)  # ring reduces in place
            t1 = time.perf_counter()
            self._ring_chunk(arr, native_op, timeout_ms)
            stripe_s = self._last_stripe_seconds()
            if divisor is not None:
                arr = self._apply_divisor(arr, divisor)
            t2 = time.perf_counter()
            # Async dispatch: the upload starts now and overlaps the next
            # chunk's (possibly next bucket's) ring pass.
            out_chunks[name].append(jnp.asarray(arr))
            st["bytes"] += arr.nbytes
            st["d2h"] += t1 - t0
            st["ring"] += t2 - t1
            st["h2d"] += time.perf_counter() - t2
            # elementwise-sum the per-stripe ring seconds over the
            # bucket's chunks (chunks can use fewer effective stripes)
            acc = st["stripe_s"]
            for i, s in enumerate(stripe_s):
                if i < len(acc):
                    acc[i] += s
                else:
                    acc.append(s)
            # pure transport wall: the slowest stripe bounds each chunk's
            # ring pass; summing per-chunk maxima excludes the peer-skew
            # wait the `ring` phase absorbs at the op-header sync, so this
            # is the number a stripe-count sweep compares
            if stripe_s:
                st["stripe_wall"] += max(stripe_s)
        dev_bufs = {
            name: (chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks))
            for name, chunks in out_chunks.items()
        }
        self._record_op_stats({
            "op": "allreduce",
            "bytes": sum(b["bytes"] for b in buckets.values()),
            "chunks": len(schedule),
            "pack": pack_s,
            "d2h": sum(b["d2h"] for b in buckets.values()),
            "ring": sum(b["ring"] for b in buckets.values()),
            "h2d": sum(b["h2d"] for b in buckets.values()),
            "buckets": buckets,
        })
        return _unflatten(treedef, packer.unpack(dev_bufs))

    def _apply_divisor(self, arr: np.ndarray, divisor) -> np.ndarray:
        if arr.dtype == _BF16:
            return (arr.astype(np.float32) / divisor).astype(_BF16)
        if np.issubdtype(arr.dtype, np.floating):
            arr /= divisor
            return arr
        arr //= divisor
        return arr

    def _ring_chunk(self, arr: np.ndarray, native_op: int, timeout_ms: int) -> None:
        _check(
            _lib.tft_hc_allreduce(
                self._handle,
                arr.ctypes.data_as(ctypes.c_void_p),
                arr.size,
                _NATIVE_DTYPES[arr.dtype],
                native_op,
                timeout_ms,
            )
        )

    def allgather(self, tree: Any) -> Work:
        timeout_ms = _ms(self._timeout)
        return self._submit(lambda: self._allgather_sync(tree, timeout_ms))

    def _allgather_sync(self, tree: Any, timeout_ms: int) -> List[Any]:
        if self._world_size == 1:
            return [tree]
        leaves, treedef = _flatten(tree)
        if leaves and all(_is_jax_array(l) for l in leaves):
            # Device-packed fast path, mirroring allreduce's: without it,
            # a quantized {q, scale} payload of ~60 leaves costs ~60
            # device->host round-trips — measured 3.5 s/step on the
            # tunneled TPU (~100 ms RTT each) vs ~0.25 s of actual
            # bandwidth for the same bytes.
            return self._allgather_device_packed(leaves, treedef, timeout_ms)
        arrays = [np.ascontiguousarray(_as_numpy(l)) for l in leaves]
        was_jax = [_is_jax_array(l) for l in leaves]
        packed = b"".join(a.tobytes() for a in arrays)
        nbytes = len(packed)
        inbuf = ctypes.create_string_buffer(packed, nbytes) if nbytes else None
        out = np.empty(max(nbytes * self._world_size, 1), dtype=np.uint8)
        _check(
            _lib.tft_hc_allgather(
                self._handle,
                inbuf,
                out.ctypes.data_as(ctypes.c_void_p),
                nbytes,
                timeout_ms,
            )
        )
        results: List[Any] = []
        for r in range(self._world_size):
            offset = r * nbytes
            out_leaves: List[Any] = []
            for i, a in enumerate(arrays):
                leaf = (
                    out[offset : offset + a.nbytes]
                    .view(a.dtype)
                    .reshape(a.shape)
                    .copy()
                )
                offset += a.nbytes
                if was_jax[i]:
                    import jax.numpy as jnp

                    leaf = jnp.asarray(leaf)
                out_leaves.append(leaf)
            results.append(_unflatten(treedef, out_leaves))
        return results

    def _allgather_device_packed(
        self, leaves, treedef, timeout_ms: int
    ) -> List[Any]:
        """All-jax-leaf allgather: one jitted on-device concat per EXACT
        dtype (byte-preserving — no accumulation upcasts), one d2h per
        dtype group, one ring gather over the concatenated groups, then
        per-member on-device unpack."""
        import jax.numpy as jnp

        key = (
            "ag", treedef,
            tuple((l.shape, np.dtype(l.dtype)) for l in leaves),
        )
        packer = self._packers.get(key)
        if packer is None:
            packer = self._packers[key] = _DevicePacker(
                leaves, exact_dtypes=True
            )
        t0 = time.perf_counter()
        bufs = packer.pack(leaves)
        names = sorted(bufs)  # deterministic group order on the wire
        for name in names:  # queue every DMA before blocking on the first
            bufs[name].copy_to_host_async()
        t1 = time.perf_counter()
        host = {name: np.ascontiguousarray(np.asarray(bufs[name]))
                for name in names}
        t2 = time.perf_counter()
        packed = b"".join(host[name].tobytes() for name in names)
        nbytes = len(packed)
        inbuf = ctypes.create_string_buffer(packed, nbytes) if nbytes else None
        out = np.empty(max(nbytes * self._world_size, 1), dtype=np.uint8)
        t2b = time.perf_counter()  # host staging copies are not the wire
        _check(
            _lib.tft_hc_allgather(
                self._handle,
                inbuf,
                out.ctypes.data_as(ctypes.c_void_p),
                nbytes,
                timeout_ms,
            )
        )
        t3 = time.perf_counter()
        stripe_s = self._last_stripe_seconds()
        results: List[Any] = []
        for r in range(self._world_size):
            offset = r * nbytes
            member_bufs = {}
            for name in names:
                a = host[name]
                member_bufs[name] = jnp.asarray(
                    out[offset : offset + a.nbytes].view(a.dtype)
                )
                offset += a.nbytes
            results.append(_unflatten(treedef, packer.unpack(member_bufs)))
        self._record_op_stats({
            "op": "allgather", "bytes": nbytes,
            "pack": t1 - t0, "d2h": t2 - t1, "host_copy": t2b - t2,
            "ring": t3 - t2b, "h2d": time.perf_counter() - t3,
            "stripe_s": stripe_s,
        })
        return results

    def broadcast(self, tree: Any, root: int = 0) -> Work:
        timeout_ms = _ms(self._timeout)
        return self._submit(lambda: self._broadcast_sync(tree, root, timeout_ms))

    def _broadcast_sync(self, tree: Any, root: int, timeout_ms: int) -> Any:
        if self._world_size == 1:
            if root != 0:
                raise RuntimeError(f"bad broadcast root {root} for world size 1")
            return tree
        leaves, treedef = _flatten(tree)
        arrays = [np.ascontiguousarray(_as_numpy(l)) for l in leaves]
        was_jax = [_is_jax_array(l) for l in leaves]
        packed = bytearray(b"".join(a.tobytes() for a in arrays))
        nbytes = len(packed)
        buf = (ctypes.c_char * nbytes).from_buffer(packed) if nbytes else None
        _check(_lib.tft_hc_broadcast(self._handle, buf, nbytes, root, timeout_ms))
        offset = 0
        view = memoryview(packed)
        out_leaves: List[Any] = []
        for i, a in enumerate(arrays):
            size = a.nbytes
            out = (
                np.frombuffer(view[offset : offset + size], dtype=a.dtype)
                .reshape(a.shape)
                .copy()
            )
            offset += size
            if was_jax[i]:
                import jax.numpy as jnp

                out = jnp.asarray(out)
            out_leaves.append(out)
        return _unflatten(treedef, out_leaves)

    def barrier(self) -> Work:
        timeout_ms = _ms(self._timeout)
        return self._submit(
            lambda: _check(_lib.tft_hc_barrier(self._handle, timeout_ms))
        )


class DummyCollectives(Collectives):
    """No-op fake for tests and wrapper semantics, the reference's
    ProcessGroupDummy (torchft/process_group.py:333-384)."""

    def __init__(self, rank: int = 0, world_size: int = 1) -> None:
        self._rank = rank
        self._world_size = world_size
        self.configure_count = 0
        self.op_count = 0

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self.configure_count += 1
        self._rank = rank
        self._world_size = world_size

    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,  # accepted, ignored (lossless fake)
    ) -> Work:
        self.op_count += 1
        if divisor is not None and divisor != 1:
            # The manager's AVG contract delegates the participant divide
            # to the backend; the fake must honor it or wrapper-semantics
            # tests see undivided gradients.
            import jax

            tree = jax.tree_util.tree_map(
                lambda l: _divide_leaf(l, divisor), tree
            )
        return _completed(tree)

    def allgather(self, tree: Any) -> Work:
        self.op_count += 1
        return _completed([tree] * self._world_size)

    def broadcast(self, tree: Any, root: int = 0) -> Work:
        self.op_count += 1
        return _completed(tree)

    def barrier(self) -> Work:
        self.op_count += 1
        return _completed(None)

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank
