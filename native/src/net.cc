#include "net.h"

#include "fault.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <random>

namespace tft {

int64_t now_ms() {
  auto t = std::chrono::steady_clock::now().time_since_epoch();
  return std::chrono::duration_cast<std::chrono::milliseconds>(t).count();
}

int64_t unix_ms() {
  auto t = std::chrono::system_clock::now().time_since_epoch();
  return std::chrono::duration_cast<std::chrono::milliseconds>(t).count();
}

std::string format_unix_ms(int64_t ms) {
  time_t secs = static_cast<time_t>(ms / 1000);
  struct tm tm_utc;
  gmtime_r(&secs, &tm_utc);
  char buf[16];
  snprintf(buf, sizeof(buf), "%02d:%02d:%02d", tm_utc.tm_hour, tm_utc.tm_min,
           tm_utc.tm_sec);
  return buf;
}

int poll_timeout_or_throw(int64_t deadline_ms, const char* what) {
  if (deadline_ms < 0) return -1;
  int64_t remain = deadline_ms - now_ms();
  if (remain <= 0) throw TimeoutError(what);
  return static_cast<int>(std::min<int64_t>(remain, 1 << 30));
}

std::string local_hostname() {
  char buf[256];
  if (gethostname(buf, sizeof(buf)) != 0) return "localhost";
  buf[sizeof(buf) - 1] = '\0';
  return buf;
}

namespace {

uint16_t parse_port(const std::string& raw, const std::string& port_str) {
  if (port_str.empty() ||
      port_str.find_first_not_of("0123456789") != std::string::npos)
    throw SocketError("bad port in address: " + raw);
  long port = std::strtol(port_str.c_str(), nullptr, 10);
  if (port < 0 || port > 65535)
    throw SocketError("port out of range in address: " + raw);
  return static_cast<uint16_t>(port);
}

} // namespace

Addr parse_addr(const std::string& raw) {
  std::string s = raw;
  for (const char* scheme : {"http://", "tft://", "grpc://"}) {
    if (s.rfind(scheme, 0) == 0) {
      s = s.substr(strlen(scheme));
      break;
    }
  }
  // strip trailing slash but reject a real path
  while (!s.empty() && s.back() == '/') s.pop_back();
  if (s.find('/') != std::string::npos)
    throw SocketError("address contains a path component: " + raw);

  size_t colon;
  if (!s.empty() && s[0] == '[') {
    // [v6]:port
    size_t close = s.find(']');
    if (close == std::string::npos || close + 1 >= s.size() || s[close + 1] != ':')
      throw SocketError("bad address: " + raw);
    Addr a;
    a.host = s.substr(1, close - 1);
    a.port = parse_port(raw, s.substr(close + 2));
    return a;
  }
  colon = s.rfind(':');
  if (colon == std::string::npos) throw SocketError("address missing port: " + raw);
  Addr a;
  a.host = s.substr(0, colon);
  a.port = parse_port(raw, s.substr(colon + 1));
  if (a.host.empty()) a.host = "::";
  return a;
}

std::pair<std::string, std::string> split_store_addr(const std::string& addr) {
  std::string s = addr;
  size_t slash = s.find('/');
  if (slash == std::string::npos) return {s, ""};
  return {s.substr(0, slash), s.substr(slash + 1)};
}

Socket& Socket::operator=(Socket&& other) noexcept {
  if (this != &other) {
    close();
    fd_ = other.fd_;
    other.fd_ = -1;
  }
  return *this;
}

Socket::~Socket() { close(); }

void Socket::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Socket::shutdown_rdwr() {
  if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
}

void Socket::wait_ready(bool for_read, int64_t deadline_ms) {
  struct pollfd pfd;
  pfd.fd = fd_;
  pfd.events = for_read ? POLLIN : POLLOUT;
  while (true) {
    int timeout = poll_timeout_or_throw(deadline_ms, "socket io timed out");
    int rc = ::poll(&pfd, 1, timeout);
    if (rc > 0) return;
    if (rc == 0) throw TimeoutError("socket io timed out");
    if (errno == EINTR) continue;
    throw SocketError(std::string("poll: ") + strerror(errno));
  }
}

void Socket::send_all(const void* buf, size_t len, int64_t deadline_ms) {
  const char* p = static_cast<const char*>(buf);
  size_t sent = 0;
  // Chaos seam: the control plane's send path (store ops, manager/
  // lighthouse RPC frames, ring hellos). Disarmed this is one relaxed
  // load; armed, the seeded schedule decides per frame. `corrupt` keeps
  // a mutated copy alive for the send loop — the caller's buffer is
  // never touched, and nothing recurses back through the fault check.
  std::string corrupt;
  bool truncate_after = false;
  fault::Decision fd =
      TFT_FAULT_CHECK(fault::kSeamNetSend, /*member=*/-1, /*op_index=*/-1);
  if (fd.kind != fault::kNone && len > 0) {
    switch (fd.kind) {
      case fault::kDrop:
        shutdown_rdwr();
        throw SocketError("chaos injected: control-plane send dropped");
      case fault::kDelay: {
        // Bounded by the caller's deadline (the fault.h contract).
        int64_t ms = fd.param;
        if (deadline_ms >= 0) {
          int64_t remain = deadline_ms - now_ms();
          if (remain < 0) remain = 0;
          if (ms > remain) ms = remain;
        }
        struct timespec ts;
        ts.tv_sec = ms / 1000;
        ts.tv_nsec = (ms % 1000) * 1000000;
        nanosleep(&ts, nullptr);
        break;
      }
      case fault::kTruncate:
        // Ship a torn prefix, then die — the peer sees a partial frame
        // followed by EOF (a mid-write crash).
        corrupt.assign(p, len / 2);
        p = corrupt.data();
        len = corrupt.size();
        truncate_after = true;
        break;
      case fault::kPartition:
        // Asymmetric partition: the frame silently vanishes; the peer
        // keeps waiting until ITS deadline while our receives still
        // flow. Nothing to throw here — the stall IS the fault.
        return;
      case fault::kBitFlip:
        // Corrupt one bit of the frame on the wire: protocol framing on
        // the far side must reject it, never act on it.
        corrupt.assign(p, len);
        corrupt[fd.h % len] ^= static_cast<char>(1u << ((fd.h >> 8) % 8));
        p = corrupt.data();
        break;
      case fault::kDuplicate:
        // Repeat a prefix of the frame: every byte after it lands at
        // the wrong stream offset (the classic torn-retry desync).
        corrupt.assign(p, len < 16 ? len : 16);
        corrupt.append(p, len);
        p = corrupt.data();
        len = corrupt.size();
        break;
      default:
        break;
    }
  }
  while (sent < len) {
    ssize_t n = ::send(fd_, p + sent, len - sent, MSG_NOSIGNAL);
    if (n > 0) {
      sent += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      wait_ready(/*for_read=*/false, deadline_ms);
      continue;
    }
    if (n < 0 && errno == EINTR) continue;
    throw SocketError(std::string("send: ") + strerror(errno));
  }
  if (truncate_after) {
    shutdown_rdwr();
    throw SocketError("chaos injected: control-plane send truncated");
  }
}

void Socket::recv_all(void* buf, size_t len, int64_t deadline_ms) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd_, p + got, len - got, 0);
    if (n > 0) {
      got += static_cast<size_t>(n);
      continue;
    }
    if (n == 0) throw SocketError("connection closed by peer");
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      wait_ready(/*for_read=*/true, deadline_ms);
      continue;
    }
    if (errno == EINTR) continue;
    throw SocketError(std::string("recv: ") + strerror(errno));
  }
}

size_t Socket::peek(void* buf, size_t len, int64_t deadline_ms) {
  while (true) {
    ssize_t n = ::recv(fd_, buf, len, MSG_PEEK);
    if (n > 0) return static_cast<size_t>(n);
    if (n == 0) throw SocketError("connection closed by peer");
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      wait_ready(/*for_read=*/true, deadline_ms);
      continue;
    }
    if (errno == EINTR) continue;
    throw SocketError(std::string("peek: ") + strerror(errno));
  }
}

namespace {

void set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

// Large kernel buffers: the bulk ring crosses high-bandwidth-delay paths
// (DCN, tunneled links) where a default-window TCP connection caps
// throughput at window/RTT, and on any path a deeper buffer halves the
// poll/send wakeup count per MB. Must run BEFORE the handshake (before
// ::connect on the client, on the listening fd for accepted sockets) —
// the window-scale factor is fixed at SYN from the buffer size then in
// effect. Best-effort — the kernel clamps to net.core.{r,w}mem_max.
void set_bulk_buffers(int fd) {
  int buf = 4 << 20;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
}

void set_common_opts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // TCP keepalive plays the role of reference src/net.rs HTTP2 keep-alive.
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
  int idle = 60, intvl = 20, cnt = 3;
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
}

} // namespace

// The listener fd is non-blocking: accept() waits in poll, so a deadline is
// always enforceable and a peer that vanishes from the backlog between poll
// and ::accept surfaces as EAGAIN (retried) instead of a blocking accept.
Listener::Listener(const std::string& bind_addr) {
  Addr a = parse_addr(bind_addr);
  struct addrinfo hints, *res = nullptr;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  hints.ai_flags = AI_PASSIVE;
  std::string port_str = std::to_string(a.port);
  const char* host = a.host == "::" || a.host.empty() ? nullptr : a.host.c_str();
  int rc = getaddrinfo(host, port_str.c_str(), &hints, &res);
  if (rc != 0) throw SocketError(std::string("getaddrinfo: ") + gai_strerror(rc));

  int last_errno = 0;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) {
      last_errno = errno;
      continue;
    }
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    set_bulk_buffers(fd); // accepted sockets inherit; scale is fixed at SYN
    if (ai->ai_family == AF_INET6) {
      int zero = 0; // dual-stack
      setsockopt(fd, IPPROTO_IPV6, IPV6_V6ONLY, &zero, sizeof(zero));
    }
    if (::bind(fd, ai->ai_addr, ai->ai_addrlen) == 0 && ::listen(fd, 1024) == 0) {
      set_nonblocking(fd);
      fd_ = fd;
      struct sockaddr_storage ss;
      socklen_t slen = sizeof(ss);
      getsockname(fd, reinterpret_cast<struct sockaddr*>(&ss), &slen);
      if (ss.ss_family == AF_INET)
        port_ = ntohs(reinterpret_cast<struct sockaddr_in*>(&ss)->sin_port);
      else
        port_ = ntohs(reinterpret_cast<struct sockaddr_in6*>(&ss)->sin6_port);
      break;
    }
    last_errno = errno;
    ::close(fd);
  }
  freeaddrinfo(res);
  if (fd_ < 0)
    throw SocketError("bind " + bind_addr + ": " + strerror(last_errno));
  int wake[2];
  if (::pipe(wake) == 0) {
    for (int wfd : wake) {
      int flags = fcntl(wfd, F_GETFL, 0);
      fcntl(wfd, F_SETFL, flags | O_NONBLOCK);
      fcntl(wfd, F_SETFD, FD_CLOEXEC);
    }
    wake_rd_ = wake[0];
    wake_wr_ = wake[1];
  }
}

Listener::~Listener() {
  close();
  // The fd NUMBERS (the /dev/null placeholder close() left in the listen
  // slot, and the pipe) are released only here: a racing accept() may
  // still hold them for its poll/::accept pair for an instant after
  // close() returns. Every caller joins/serializes its accept threads
  // before destroying the Listener, so releasing the numbers here is
  // race-free.
  int fd = fd_.exchange(-1);
  if (fd >= 0) ::close(fd);
  if (wake_rd_ >= 0) ::close(wake_rd_);
  if (wake_wr_ >= 0) ::close(wake_wr_);
}

void Listener::close() {
  if (closed_.exchange(true)) return;
  // Order matters: signal the pipe BEFORE touching the listen fd, so a
  // thread blocked in poll() wakes via the pipe even though closing the
  // fd under it would not (Linux<4.5 / gVisor never wake such a poller).
  if (wake_wr_ >= 0) {
    char b = 1;
    [[maybe_unused]] ssize_t rc = ::write(wake_wr_, &b, 1);
  }
  // The listening SOCKET must die now — peers must get ECONNREFUSED and
  // the port must free immediately (shutdown() alone is a no-op for a
  // LISTENING fd on gVisor/Linux<4.5, which would leave dials landing in
  // a backlog nobody drains). But plainly ::close()ing would let the
  // kernel recycle the fd NUMBER into an unrelated socket that a racing
  // accept() — which already loaded the number for its poll/::accept
  // pair — could steal a connection from. dup2()ing /dev/null over the
  // slot does both atomically: the socket closes (port freed, dials
  // refused) while the number stays reserved until ~Listener, and the
  // racing accept() gets ENOTSOCK and exits.
  int fd = fd_.load();
  if (fd >= 0) {
    ::shutdown(fd, SHUT_RDWR);  // wakes pollers on kernels that honor it
    int nul = ::open("/dev/null", O_RDONLY | O_CLOEXEC);
    if (nul >= 0) {
      ::dup2(nul, fd);
      ::close(nul);
    } else {
      // No placeholder available: fall back to a plain close (the
      // fd-reuse window returns, but a dead /dev/null is not an option).
      fd_.store(-1);
      ::close(fd);
    }
  }
}

Socket Listener::accept() { return accept(-1); }

Socket Listener::accept(int64_t deadline_ms) {
  while (true) {
    // closed_ is the close() signal (the fd slot then holds a /dev/null
    // placeholder, not the socket; fd_ goes -1 only in the destructor or
    // the close() fallback path). Bail out before polling: poll() would
    // silently skip a negative fd and sleep the whole timeout. One load
    // per iteration: poll and ::accept below must see the same fd.
    int lfd = fd_.load();
    if (closed_ || lfd < 0) return Socket();
    struct pollfd pfds[2];
    pfds[0].fd = lfd;
    pfds[0].events = POLLIN;
    pfds[1].fd = wake_rd_; // -1 (pipe creation failed) is skipped by poll
    pfds[1].events = POLLIN;
    int timeout = poll_timeout_or_throw(deadline_ms, "accept timed out");
    int prc = ::poll(pfds, 2, timeout);
    if (prc == 0) throw TimeoutError("accept timed out");
    if (prc < 0) {
      if (errno == EINTR) continue;
      throw SocketError(std::string("poll: ") + strerror(errno));
    }
    if (pfds[1].revents & (POLLIN | POLLHUP | POLLERR)) return Socket();
    if (pfds[0].revents & POLLNVAL) return Socket(); // fd closed under us
    if (!(pfds[0].revents & (POLLIN | POLLHUP | POLLERR))) continue;
    int fd = ::accept(lfd, nullptr, nullptr);
    if (fd >= 0) {
      set_common_opts(fd);
      set_nonblocking(fd);
      return Socket(fd);
    }
    // Transient failures (peer vanished from the backlog between poll and
    // accept, fd pressure) must not stop the loop — only a closed listener
    // should. The fd is non-blocking, so the retry waits in poll above.
    if (errno == EINTR || errno == ECONNABORTED || errno == EAGAIN ||
        errno == EWOULDBLOCK)
      continue;
    if (errno == EMFILE || errno == ENFILE || errno == ENOBUFS ||
        errno == ENOMEM) {
      struct timespec ts{0, 10 * 1000 * 1000}; // 10ms breather
      nanosleep(&ts, nullptr);
      continue;
    }
    return Socket(); // listener closed (EBADF/EINVAL/ENOTSOCK)
  }
}

Socket connect_once(const Addr& addr, int64_t deadline_ms) {
  struct addrinfo hints, *res = nullptr;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  std::string host = addr.host;
  if (host == "::" || host.empty() || host == "0.0.0.0") host = "localhost";
  std::string port_str = std::to_string(addr.port);
  int rc = getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) throw SocketError(std::string("getaddrinfo: ") + gai_strerror(rc));

  std::string last_err = "no addresses";
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) {
      last_err = strerror(errno);
      continue;
    }
    set_bulk_buffers(fd); // before ::connect: window scale is fixed at SYN
    set_nonblocking(fd);
    int crc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (crc != 0 && errno != EINPROGRESS) {
      last_err = strerror(errno);
      ::close(fd);
      continue;
    }
    if (crc != 0) {
      // wait for connect completion
      struct pollfd pfd;
      pfd.fd = fd;
      pfd.events = POLLOUT;
      int64_t remain = deadline_ms < 0 ? -1 : deadline_ms - now_ms();
      if (deadline_ms >= 0 && remain <= 0) {
        ::close(fd);
        freeaddrinfo(res);
        throw TimeoutError("connect timed out");
      }
      int prc = ::poll(&pfd, 1, deadline_ms < 0 ? -1 : static_cast<int>(remain));
      if (prc <= 0) {
        ::close(fd);
        freeaddrinfo(res);
        throw TimeoutError("connect timed out");
      }
      int err = 0;
      socklen_t elen = sizeof(err);
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen);
      if (err != 0) {
        last_err = strerror(err);
        ::close(fd);
        continue;
      }
    }
    set_common_opts(fd);
    freeaddrinfo(res);
    return Socket(fd);
  }
  freeaddrinfo(res);
  throw SocketError("connect " + host + ":" + port_str + ": " + last_err);
}

Socket connect_with_retry(const std::string& addr_str, int64_t timeout_ms) {
  Addr addr = parse_addr(addr_str);
  int64_t deadline = now_ms() + timeout_ms;
  // Reference src/retry.rs: initial 100ms, multiplier 1.5, max 10s, jitter 100ms.
  double backoff = 100.0;
  std::mt19937 rng(static_cast<uint32_t>(now_ms()));
  std::uniform_real_distribution<double> jitter(0.0, 100.0);
  std::string last_err;
  while (true) {
    try {
      return connect_once(addr, deadline);
    } catch (const TimeoutError&) {
      throw TimeoutError("connect to " + addr_str + " timed out after " +
                         std::to_string(timeout_ms) + "ms" +
                         (last_err.empty() ? "" : " (last error: " + last_err + ")"));
    } catch (const SocketError& e) {
      last_err = e.what();
    }
    int64_t remain = deadline - now_ms();
    if (remain <= 0)
      throw TimeoutError("connect to " + addr_str + " timed out after " +
                         std::to_string(timeout_ms) + "ms (last error: " + last_err +
                         ")");
    int64_t sleep_ms =
        std::min<int64_t>(static_cast<int64_t>(backoff + jitter(rng)), remain);
    struct timespec ts;
    ts.tv_sec = sleep_ms / 1000;
    ts.tv_nsec = (sleep_ms % 1000) * 1000000;
    nanosleep(&ts, nullptr);
    backoff = std::min(backoff * 1.5, 10000.0);
  }
}

namespace {

// splitmix64: tiny, well-mixed, and stable across platforms — exactly what a
// deterministic (testable) jitter needs.
uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// Uniform double in [0, 1) from the top 53 bits.
double unit_double(uint64_t h) {
  return static_cast<double>(h >> 11) * (1.0 / 9007199254740992.0);
}

} // namespace

int64_t backoff_ms(int failures, int64_t base_ms, int64_t max_ms, uint64_t seed) {
  if (failures <= 0 || base_ms <= 0) return 0;
  // Cap the exponent before shifting so 63+ consecutive failures cannot
  // overflow into a negative delay.
  int exp = failures - 1 > 40 ? 40 : failures - 1;
  int64_t raw = base_ms << exp;
  if (raw > max_ms || raw <= 0) raw = max_ms;
  double jitter = 0.5 + unit_double(splitmix64(seed ^ static_cast<uint64_t>(failures)));
  int64_t out = static_cast<int64_t>(static_cast<double>(raw) * jitter);
  return out > max_ms ? max_ms : out;
}

int64_t jittered_interval_ms(int64_t interval_ms, uint64_t seed, uint64_t tick) {
  if (interval_ms <= 0) return 0;
  double f = 0.75 + 0.5 * unit_double(splitmix64(seed ^ (tick * 0x9e3779b97f4a7c15ULL)));
  return static_cast<int64_t>(static_cast<double>(interval_ms) * f);
}

std::vector<std::string> split_addr_list(const std::string& s) {
  std::vector<std::string> out;
  std::string cur;
  for (char c : s) {
    if (c == ',') {
      if (!cur.empty()) out.push_back(cur);
      cur.clear();
    } else if (!std::isspace(static_cast<unsigned char>(c))) {
      cur.push_back(c);
    }
  }
  if (!cur.empty()) out.push_back(cur);
  return out;
}

} // namespace tft
