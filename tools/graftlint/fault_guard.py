"""Every native fault-injection point sits behind the disarmed fast path.

The chaos plane's hot-path contract is that a DISARMED injection point
costs exactly one relaxed atomic load and a branch — which holds only
when every call site reaches ``tft_fault_maybe`` through the
``TFT_FAULT_CHECK`` macro (native/src/fault.h), never directly. A raw
call would pay the decision mutex + hash on every frame of every ring op
in production. The rule greps ``native/src`` for ``tft_fault_maybe``
outside the fault engine's own files (fault.h declares it and defines
the macro; fault.cc defines it) and flags any line that is not the macro
definition itself.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import List, Optional, Sequence

from . import Violation, relpath

RULE = "fault_guard"

SCAN_DIR = Path("native/src")
# The engine's own files: declaration, definition, and the macro.
ENGINE_FILES = ("fault.h", "fault.cc")

_CALL = re.compile(r"\btft_fault_maybe\b")


def check(
    root: Path, scan_dir: Optional[Path] = None,
    engine_files: Optional[Sequence[str]] = None,
) -> List[Violation]:
    base = root / (scan_dir or SCAN_DIR)
    engine = tuple(engine_files or ENGINE_FILES)
    out: List[Violation] = []
    if not base.exists():
        return out
    for path in sorted(base.rglob("*")):
        if path.suffix not in (".cc", ".h"):
            continue
        if path.name in engine:
            continue
        text = path.read_text()
        for m in _CALL.finditer(text):
            line_no = text[: m.start()].count("\n") + 1
            line = text.splitlines()[line_no - 1]
            # TFT_FAULT_CHECK expands to the guarded call; a call site
            # USING the macro never spells tft_fault_maybe itself, so
            # any literal appearance outside the engine is a violation
            # (comments included — a commented recipe showing the raw
            # call is how the next raw call gets written).
            out.append(
                Violation(
                    RULE,
                    relpath(root, path),
                    line_no,
                    "raw tft_fault_maybe call outside the "
                    "TFT_FAULT_CHECK guard (disarmed fast-path "
                    f"contract): {line.strip()[:80]!r} — route the "
                    "injection point through TFT_FAULT_CHECK "
                    "(native/src/fault.h)",
                )
            )
    return out
