# graftlint fixture: the latch discipline done right (clean-pass control).


class Manager:
    def __init__(self, collectives):
        self._collectives = collectives
        self._errored = None

    def allreduce(self, tree, op="avg"):
        if op not in ("avg", "sum"):
            # Eager static-usage error: allowed.
            raise ValueError(f"unsupported op: {op}")

        def dispatch(t):
            return self._collectives.allreduce(t)

        return self._managed_dispatch("allreduce", tree, dispatch)

    def _managed_dispatch(self, op_name, tree, dispatch):
        try:
            return dispatch(tree)
        except Exception as e:
            self.report_error(e)
            return None

    def report_error(self, e):
        self._errored = e
