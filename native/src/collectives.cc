#include "collectives.h"

#include <poll.h>
#include <string.h>

#include <algorithm>
#include <cmath>
#include <limits>
#include <vector>

#include "log.h"
#include "store.h"

namespace tft {

size_t dtype_size(Dtype d) {
  switch (d) {
    case Dtype::kF32:
    case Dtype::kI32:
      return 4;
    case Dtype::kF64:
    case Dtype::kI64:
      return 8;
    case Dtype::kBF16:
      return 2;
  }
  throw SocketError("bad dtype");
}

namespace {

// Hello magic, versioned: the low byte is the ring wire-protocol revision.
// History: the original "tftc" magic (0x74667463) spanned BOTH the
// pre-op-header wire and the build that added check_op_header, so the
// magic alone could not distinguish them; a ring mixing those desyncs
// mid-op (the old side consumes the 24-byte op header as payload). This
// versioned magic makes any mix of revisions — including byte-compatible
// "tftc" builds that already spoke op headers — fail AT CONNECT with a
// clear error; that over-rejection is the price of screening out the
// truly incompatible older builds sharing the old magic. Bump the low
// byte on any future wire change.
constexpr uint32_t kHelloMagic = 0x74667402; // "tft" + proto rev 2
// "tftp": per-op header magic (part of the wire protocol).
constexpr uint32_t kOpMagic = 0x74667470;

template <typename T>
void reduce_typed(T* dst, const T* src, size_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::kSum:
      for (size_t i = 0; i < n; i++) dst[i] += src[i];
      return;
    case ReduceOp::kProduct:
      for (size_t i = 0; i < n; i++) dst[i] *= src[i];
      return;
    case ReduceOp::kMin:
      for (size_t i = 0; i < n; i++) dst[i] = std::min(dst[i], src[i]);
      return;
    case ReduceOp::kMax:
      for (size_t i = 0; i < n; i++) dst[i] = std::max(dst[i], src[i]);
      return;
  }
  throw SocketError("bad reduce op");
}

inline float bf16_to_f32(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &bits, sizeof(f));
  return f;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, sizeof(bits));
  // Round to nearest even (NaN payloads preserved by the +0x7FFF carry-free
  // path since NaN mantissas survive truncation of the low half).
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7FFF + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

void reduce_bf16(uint16_t* dst, const uint16_t* src, size_t n, ReduceOp op) {
  for (size_t i = 0; i < n; i++) {
    float a = bf16_to_f32(dst[i]);
    float b = bf16_to_f32(src[i]);
    float r;
    switch (op) {
      case ReduceOp::kSum: r = a + b; break;
      case ReduceOp::kProduct: r = a * b; break;
      case ReduceOp::kMin: r = std::min(a, b); break;
      case ReduceOp::kMax: r = std::max(a, b); break;
      default: throw SocketError("bad reduce op");
    }
    dst[i] = f32_to_bf16(r);
  }
}

void reduce_into(void* dst, const void* src, size_t n, Dtype dtype, ReduceOp op) {
  switch (dtype) {
    case Dtype::kF32:
      reduce_typed(static_cast<float*>(dst), static_cast<const float*>(src), n, op);
      return;
    case Dtype::kF64:
      reduce_typed(static_cast<double*>(dst), static_cast<const double*>(src), n,
                   op);
      return;
    case Dtype::kI32:
      reduce_typed(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src), n,
                   op);
      return;
    case Dtype::kI64:
      reduce_typed(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src), n,
                   op);
      return;
    case Dtype::kBF16:
      reduce_bf16(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
                  n, op);
      return;
  }
  throw SocketError("bad dtype");
}

// Element range of ring chunk `c` when `count` elements are split into `ws`
// near-equal chunks (first `count % ws` chunks get one extra element).
std::pair<size_t, size_t> chunk_range(size_t count, int64_t ws, int64_t c) {
  size_t q = count / ws;
  size_t r = count % ws;
  size_t start = c * q + std::min<size_t>(c, r);
  size_t len = q + (static_cast<size_t>(c) < r ? 1 : 0);
  return {start, len};
}

} // namespace

HostCollectives::~HostCollectives() { abort(); }

void HostCollectives::abort() {
  std::lock_guard<std::mutex> lock(cfg_mu_);
  aborted_ = true;
  abort_epoch_++;
  if (listener_) listener_->close();
  next_.shutdown_rdwr();
  prev_.shutdown_rdwr();
}

namespace {

// Remaining budget before `deadline`; throws once it is exhausted (a
// non-positive timeout must never leak into a blocking call, where some
// callees read <0 as "wait forever").
int64_t remain_or_throw(int64_t deadline) {
  int64_t r = deadline - now_ms();
  if (r <= 0) throw TimeoutError("configure timed out");
  return r;
}

} // namespace

void HostCollectives::configure(const std::string& store_addr, int64_t rank,
                                int64_t world_size, int64_t timeout_ms) {
  if (rank < 0 || world_size <= 0 || rank >= world_size)
    throw SocketError("bad rank/world_size");
  abort(); // unblock any op stuck on the old ring
  std::lock_guard<std::mutex> op_lock(op_mu_); // wait for it to drain

  // Phase 1 (under cfg_mu_, non-blocking): retire the old ring, stand up the
  // new listener so a concurrent abort() can close it and wake phase 2.
  int64_t epoch;
  {
    std::lock_guard<std::mutex> lock(cfg_mu_);
    next_.close();
    prev_.close();
    listener_.reset();
    rank_ = rank;
    world_size_ = world_size;
    aborted_ = true;
    epoch = abort_epoch_;
    if (world_size == 1) {
      aborted_ = false;
      return;
    }
    listener_ = std::make_unique<Listener>("[::]:0");
  }

  // Phase 2 (no locks held, every step deadline-bounded): rendezvous through
  // the store and wire the ring. Both neighbors dial concurrently; connect()
  // lands in the peer's listen backlog, so no accept ordering is needed.
  int64_t deadline = now_ms() + timeout_ms;
  auto [kv_addr, prefix] = split_store_addr(store_addr);
  StoreClient store(kv_addr, remain_or_throw(deadline));

  std::string my_addr =
      local_hostname() + ":" + std::to_string(listener_->port());
  store.set(prefix + "/hc_addr_" + std::to_string(rank), my_addr,
            remain_or_throw(deadline));

  int64_t next_rank = (rank + 1) % world_size;
  std::string next_addr =
      store.get(prefix + "/hc_addr_" + std::to_string(next_rank),
                remain_or_throw(deadline));
  Socket next_sock = connect_with_retry(next_addr, remain_or_throw(deadline));
  uint32_t hello[2] = {kHelloMagic, static_cast<uint32_t>(rank)};
  next_sock.send_all(hello, sizeof(hello), deadline);

  Socket prev_sock = listener_->accept(deadline);
  if (!prev_sock.valid()) throw SocketError("listener closed during configure");
  uint32_t peer_hello[2];
  prev_sock.recv_all(peer_hello, sizeof(peer_hello), deadline);
  int64_t prev_rank = (rank - 1 + world_size) % world_size;
  if (peer_hello[0] != kHelloMagic)
    throw SocketError(
        "ring handshake: wire-protocol mismatch (peer binary speaks a "
        "different ring protocol revision)");
  if (peer_hello[1] != static_cast<uint32_t>(prev_rank))
    throw SocketError("ring handshake: unexpected peer rank");

  // Phase 3: publish the new ring unless an abort raced in.
  std::lock_guard<std::mutex> lock(cfg_mu_);
  if (abort_epoch_ != epoch) throw SocketError("aborted during configure");
  next_ = std::move(next_sock);
  prev_ = std::move(prev_sock);
  aborted_ = false;
}

void HostCollectives::duplex(const char* send_buf, size_t send_len,
                             char* recv_buf, size_t recv_len,
                             int64_t deadline_ms) {
  size_t sent = 0, got = 0;
  while (sent < send_len || got < recv_len) {
    struct pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (sent < send_len) {
      send_idx = n;
      pfds[n].fd = next_.fd();
      pfds[n].events = POLLOUT;
      n++;
    }
    if (got < recv_len) {
      recv_idx = n;
      pfds[n].fd = prev_.fd();
      pfds[n].events = POLLIN;
      n++;
    }
    int timeout = poll_timeout_or_throw(deadline_ms, "collective timed out");
    int prc = ::poll(pfds, n, timeout);
    if (prc == 0) throw TimeoutError("collective timed out");
    if (prc < 0) {
      if (errno == EINTR) continue;
      throw SocketError(std::string("poll: ") + strerror(errno));
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t w = ::send(next_.fd(), send_buf + sent, send_len - sent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w > 0) {
        sent += static_cast<size_t>(w);
      } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                 errno != EINTR) {
        throw SocketError(std::string("ring send: ") + strerror(errno));
      }
    }
    if (recv_idx >= 0 &&
        (pfds[recv_idx].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t r = ::recv(prev_.fd(), recv_buf + got, recv_len - got, MSG_DONTWAIT);
      if (r > 0) {
        got += static_cast<size_t>(r);
      } else if (r == 0) {
        throw SocketError("ring peer closed connection");
      } else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        throw SocketError(std::string("ring recv: ") + strerror(errno));
      }
    }
  }
}

void HostCollectives::check_op_header(uint32_t kind, uint64_t count,
                                      uint32_t dtype, uint32_t op,
                                      int64_t deadline_ms) {
  // One tiny duplex exchange describing the op each neighbor is about to
  // run. A mismatched op (different tree sizes, dtypes, or op kinds on
  // different members) otherwise DEADLOCKS silently: the small member
  // finishes, stops reading, and the large member blocks forever once
  // kernel buffers fill. ~20 bytes per collective — noise next to any
  // payload — converts that into an immediate, descriptive error.
  struct Header {
    uint32_t magic, kind;
    uint64_t count;
    uint32_t dtype, op;
  } mine{kOpMagic, kind, count, dtype, op}, theirs{};
  duplex(reinterpret_cast<const char*>(&mine), sizeof(mine),
         reinterpret_cast<char*>(&theirs), sizeof(theirs), deadline_ms);
  if (theirs.magic != kOpMagic)
    throw SocketError("ring op header corrupt (protocol desync)");
  if (theirs.kind != mine.kind || theirs.count != mine.count ||
      theirs.dtype != mine.dtype || theirs.op != mine.op)
    throw SocketError(
        "ring op mismatch: this rank kind=" + std::to_string(kind) +
        " count=" + std::to_string(count) + " dtype=" +
        std::to_string(dtype) + " op=" + std::to_string(op) +
        ", prev rank kind=" + std::to_string(theirs.kind) + " count=" +
        std::to_string(theirs.count) + " dtype=" +
        std::to_string(theirs.dtype) + " op=" + std::to_string(theirs.op) +
        " (members must reduce identical trees)");
}

void HostCollectives::allreduce(void* data, size_t count, Dtype dtype,
                                ReduceOp op, int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // header exchanged even for count==0: an empty-vs-nonempty mismatch
    // must error, not hang the nonempty member
    check_op_header(0, count, static_cast<uint32_t>(dtype),
                    static_cast<uint32_t>(op), deadline);
    if (count == 0) return;
    char* bytes = static_cast<char*>(data);
    size_t esize = dtype_size(dtype);
    size_t max_chunk = count / world_size_ + 1;
    std::vector<char> recv_tmp(max_chunk * esize);

    // Reduce-scatter: after step s, chunk (rank - s) has accumulated the
    // values of ranks rank-s..rank. After ws-1 steps chunk (rank+1) holds the
    // full reduction at this rank — computed in the identical rank order
    // everywhere.
    for (int64_t s = 0; s < world_size_ - 1; s++) {
      int64_t send_c = ((rank_ - s) % world_size_ + world_size_) % world_size_;
      int64_t recv_c =
          ((rank_ - s - 1) % world_size_ + world_size_) % world_size_;
      auto [s_start, s_len] = chunk_range(count, world_size_, send_c);
      auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
      duplex(bytes + s_start * esize, s_len * esize, recv_tmp.data(),
             r_len * esize, deadline);
      reduce_into(bytes + r_start * esize, recv_tmp.data(), r_len, dtype, op);
    }
    // Allgather: circulate the fully-reduced chunks.
    for (int64_t s = 0; s < world_size_ - 1; s++) {
      int64_t send_c =
          ((rank_ + 1 - s) % world_size_ + world_size_) % world_size_;
      int64_t recv_c = ((rank_ - s) % world_size_ + world_size_) % world_size_;
      auto [s_start, s_len] = chunk_range(count, world_size_, send_c);
      auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
      duplex(bytes + s_start * esize, s_len * esize, bytes + r_start * esize,
             r_len * esize, deadline);
    }
  });
}

namespace {

// One chunk on the q8 wire: 4-byte f32 scale, then `len` int8 codes.
void q8_encode(const float* src, size_t len, char* wire) {
  float absmax = 0.f;
  bool finite = true;
  for (size_t i = 0; i < len; i++) {
    float a = std::fabs(src[i]);
    if (!std::isfinite(a)) finite = false;
    absmax = std::max(absmax, a);
  }
  if (!finite) {
    // Non-finite gradients must poison the result the way the f32/bf16
    // wires do: std::max/min drop NaN (they return the other operand),
    // so a diverged model would otherwise be encoded as clamped finite
    // codes and the blow-up silently hidden. A NaN scale makes every
    // decoded element NaN on all ranks.
    float nan = std::numeric_limits<float>::quiet_NaN();
    memcpy(wire, &nan, sizeof(float));
    memset(wire + sizeof(float), 0, len);
    return;
  }
  float scale = absmax > 0.f ? absmax / 127.f : 1.f;
  memcpy(wire, &scale, sizeof(float));
  int8_t* q = reinterpret_cast<int8_t*>(wire + sizeof(float));
  for (size_t i = 0; i < len; i++) {
    float v = std::nearbyint(src[i] / scale);
    q[i] = static_cast<int8_t>(std::max(-127.f, std::min(127.f, v)));
  }
}

// dst[i] (+)= scale * q[i]
void q8_decode(const char* wire, size_t len, float* dst, bool accumulate) {
  float scale;
  memcpy(&scale, wire, sizeof(float));
  const int8_t* q = reinterpret_cast<const int8_t*>(wire + sizeof(float));
  if (accumulate) {
    for (size_t i = 0; i < len; i++) dst[i] += scale * static_cast<float>(q[i]);
  } else {
    for (size_t i = 0; i < len; i++) dst[i] = scale * static_cast<float>(q[i]);
  }
}

}  // namespace

void HostCollectives::allreduce_q8(float* data, size_t count,
                                   int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // distinct kind: a q8 op meeting a plain allreduce must error, not
    // desync (their wire framings differ even at equal counts)
    check_op_header(4, count, /*dtype=*/100, /*op=*/0, deadline);
    if (count == 0) return;
    size_t max_chunk = count / world_size_ + 1;
    size_t max_wire = sizeof(float) + max_chunk;
    std::vector<char> send_wire(max_wire), recv_wire(max_wire);

    // Reduce-scatter: each hop quantizes its CURRENT partial sum of the
    // outgoing chunk and dequant-accumulates the incoming one in f32.
    for (int64_t s = 0; s < world_size_ - 1; s++) {
      int64_t send_c = ((rank_ - s) % world_size_ + world_size_) % world_size_;
      int64_t recv_c =
          ((rank_ - s - 1) % world_size_ + world_size_) % world_size_;
      auto [s_start, s_len] = chunk_range(count, world_size_, send_c);
      auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
      q8_encode(data + s_start, s_len, send_wire.data());
      duplex(send_wire.data(), sizeof(float) + s_len, recv_wire.data(),
             sizeof(float) + r_len, deadline);
      q8_decode(recv_wire.data(), r_len, data + r_start, /*accumulate=*/true);
    }
    // Allgather: the OWNER quantizes its fully-reduced chunk exactly once
    // (first send); every later hop forwards the received wire bytes
    // verbatim, so all members decode identical codes — the reduced
    // values stay bit-identical across ranks (the determinism oracle).
    std::vector<std::vector<char>> stored(world_size_);
    {
      int64_t own_c = (rank_ + 1) % world_size_;
      auto [o_start, o_len] = chunk_range(count, world_size_, own_c);
      stored[own_c].resize(sizeof(float) + o_len);
      q8_encode(data + o_start, o_len, stored[own_c].data());
      // decode own chunk too: every member must hold the DECODED codes,
      // not its higher-precision f32 partial (bit-identity across ranks)
      q8_decode(stored[own_c].data(), o_len, data + o_start, false);
    }
    for (int64_t s = 0; s < world_size_ - 1; s++) {
      int64_t send_c =
          ((rank_ + 1 - s) % world_size_ + world_size_) % world_size_;
      int64_t recv_c = ((rank_ - s) % world_size_ + world_size_) % world_size_;
      auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
      stored[recv_c].resize(sizeof(float) + r_len);
      duplex(stored[send_c].data(), stored[send_c].size(),
             stored[recv_c].data(), stored[recv_c].size(), deadline);
      q8_decode(stored[recv_c].data(), r_len, data + r_start, false);
    }
  });
}

void HostCollectives::allgather(const void* in, void* out, size_t nbytes,
                                int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  char* slots = static_cast<char*>(out);
  memcpy(slots + rank_ * nbytes, in, nbytes);
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(1, nbytes, 0, 0, deadline);
    if (nbytes == 0) return;
    for (int64_t s = 0; s < world_size_ - 1; s++) {
      int64_t send_c = ((rank_ - s) % world_size_ + world_size_) % world_size_;
      int64_t recv_c =
          ((rank_ - s - 1) % world_size_ + world_size_) % world_size_;
      duplex(slots + send_c * nbytes, nbytes, slots + recv_c * nbytes, nbytes,
             deadline);
    }
  });
}

void HostCollectives::broadcast(void* data, size_t nbytes, int64_t root,
                                int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  if (root < 0 || root >= world_size_) throw SocketError("bad broadcast root");
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(2, nbytes, static_cast<uint32_t>(root), 0, deadline);
    if (nbytes == 0) return;
    char* bytes = static_cast<char*>(data);
    // Forward around the ring, root first; the last hop before root does not
    // send. recv-then-send per hop (latency is fine at control-plane sizes;
    // bulk weight transfer goes through the checkpoint transport instead).
    if (rank_ == root) {
      duplex(bytes, nbytes, nullptr, 0, deadline);
    } else {
      duplex(nullptr, 0, bytes, nbytes, deadline);
      if ((rank_ + 1) % world_size_ != root)
        duplex(bytes, nbytes, nullptr, 0, deadline);
    }
  });
}

void HostCollectives::barrier(int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(3, 0, 0, 0, deadline);
    // Two full ring passes: after the first, rank 0 knows everyone arrived;
    // the second releases everyone.
    char token = 1;
    for (int round = 0; round < 2; round++) {
      if (rank_ == 0) {
        duplex(&token, 1, nullptr, 0, deadline);
        duplex(nullptr, 0, &token, 1, deadline);
      } else {
        duplex(nullptr, 0, &token, 1, deadline);
        duplex(&token, 1, nullptr, 0, deadline);
      }
    }
  });
}

} // namespace tft
