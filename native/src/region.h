// Region lighthouse: the middle tier of the hierarchical quorum service.
//
// Speaks the full manager-facing lighthouse protocol on its own port
// (heartbeats, batched lease renewals, departs, quorum long-polls) but never
// computes a quorum itself. Instead it aggregates its jurisdiction's
// membership into a compact digest pushed to the ROOT lighthouse (periodic,
// plus an urgent push whenever a participant (re-)registers) and long-polls
// the root's global quorum back out, republishing it to local waiters.
//
// Equivalence contract: the root applies digests through the same
// apply_digest/quorum_step pure functions the flat lighthouse's state flows
// through, with all times forwarded as ages on the region's monotonic clock,
// so for any membership history the hierarchical quorum output is
// bit-identical to the flat lighthouse's (tests/test_hierarchy.py drives the
// scripted-history suite over exactly these functions).
//
// Failure behavior: a dead region simply stops digesting; its groups'
// leases at the root run out on their own TTLs while the groups demote to
// direct-root registration (manager-side failover), so no root-side region
// timeout exists. When the region returns, managers drift back and digests
// resume.
#pragma once

#include <atomic>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.h"
#include "net.h"
#include "quorum.h"
#include "thread_annotations.h"

namespace tft {

struct RegionOpt {
  // Cadence of periodic digest pushes; urgent pushes (new participant) fire
  // immediately regardless.
  int64_t digest_interval_ms = 100;
  // Default lease TTL for plain heartbeats; must match the root's
  // heartbeat_timeout_ms for flat-equivalent semantics (docs/OPERATIONS.md).
  int64_t heartbeat_timeout_ms = 5000;
  int64_t connect_timeout_ms = 10000;
};

class RegionLighthouse {
 public:
  RegionLighthouse(const std::string& bind_addr, const std::string& root_addr,
                   const std::string& region_id, const RegionOpt& opt);
  ~RegionLighthouse();

  std::string address() const; // "http://host:port"
  uint16_t port() const;
  const std::string& region_id() const { return region_id_; }
  void shutdown();

  // Machine-readable status (the /status.json payload).
  std::string status_json();

  // The region-side quorum CACHE (the /quorum.json payload): the last
  // global quorum the poll loop pulled from the root, served locally with
  // its refresh age. Read-mostly consumers (dashboards, fleet tooling,
  // the policy engine's observers) hit this instead of long-polling the
  // root per request — the root sees one standing poll per region
  // regardless of reader count. `age_ms` bounds the staleness: while the
  // root is reachable it stays within one poll round-trip of the root's
  // quorum age; with the root down the cache keeps serving (age growing)
  // and `root_connected` goes false.
  std::string quorum_json();

 private:
  void accept_loop();
  void digest_loop();
  void poll_loop();
  void handle_conn(Socket& sock);
  void handle_http(Socket& sock, const std::string& head);
  void handle_quorum_req(Socket& sock, const std::string& payload);

  // Registers a member + marks the digest urgent; called with mu_ held.
  void register_participant_locked(const torchft_tpu::QuorumMember& member)
      TFT_REQUIRES(mu_);

  std::string root_addr_;  // the configured (possibly comma-separated) list
  // Parsed endpoint list of the root failover set: the digest and poll
  // loops each keep their own cursor into it and rotate on failure (a
  // standby's UNAVAILABLE rejection counts — the loops walk to the
  // active root on the existing backoff schedule).
  std::vector<std::string> root_endpoints_;
  std::string region_id_;
  RegionOpt opt_;
  // LighthouseOpt view of opt_ for the shared pure functions (make_digest /
  // lease_ttl_for); only heartbeat_timeout_ms is meaningful here.
  LighthouseOpt lh_opt_;

  std::unique_ptr<Listener> listener_;
  std::string hostname_;

  Mutex mu_;
  CondVar digest_cv_; // wakes digest_loop for urgent pushes + shutdown
  CondVar quorum_cv_; // wakes local long-poll waiters
  // Region-local membership; prev_quorum/quorum_id fields are unused (the
  // root owns quorum formation).
  LighthouseState state_ TFT_GUARDED_BY(mu_);
  std::vector<std::string> departed_pending_ TFT_GUARDED_BY(mu_);
  bool digest_urgent_ TFT_GUARDED_BY(mu_) = false;
  // Local broadcast generation for waiters + the last root gen we consumed.
  int64_t quorum_gen_ TFT_GUARDED_BY(mu_) = 0;
  int64_t root_gen_ TFT_GUARDED_BY(mu_) = 0;
  torchft_tpu::Quorum latest_quorum_ TFT_GUARDED_BY(mu_);
  // now_ms() at which latest_quorum_ was last refreshed off the root; -1
  // until the first poll lands. The staleness stamp of the quorum cache.
  int64_t quorum_refresh_ms_ TFT_GUARDED_BY(mu_) = -1;
  bool root_connected_ TFT_GUARDED_BY(mu_) = false;
  int64_t digests_sent_ TFT_GUARDED_BY(mu_) = 0;
  int64_t last_digest_ms_ TFT_GUARDED_BY(mu_) = -1;
  // now_ms() at which the last SENT digest was built: participant
  // registrations newer than this were never forwarded, so a root quorum
  // arriving now cannot have consumed them — the poll loop's mirror-clear
  // must leave them registered (flat has no such race: registration and
  // the clearing quorum_step share one mutex).
  int64_t digest_built_ms_ TFT_GUARDED_BY(mu_) = -1;

  // Raw fds of the two root connections, published so shutdown() can wake
  // threads blocked in their socket IO (the sockets themselves are owned by
  // their loops; -1 = not connected).
  std::atomic<int> digest_fd_{-1};
  std::atomic<int> poll_fd_{-1};

  std::atomic<bool> shutting_down_{false};
  std::thread accept_thread_;
  std::thread digest_thread_;
  std::thread poll_thread_;
  ConnTracker conns_;
};

} // namespace tft
