"""Identity-stable training state for fault-tolerant JAX loops.

In torch, healing works because ``load_state_dict`` mutates the same tensors
the optimizer later steps (reference manager.py:528-543). JAX pytrees are
immutable values, so a recovered checkpoint applied through a callback can
be silently shadowed by stale ``params`` bound earlier in the step — the
divergence class the reference never has. :class:`FTTrainState` restores the
in-place property at the *holder* level: the manager's state callbacks and
the optimizer update both go through one mutable object, so post-heal reads
always see the recovered weights.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def _to_device_tree(tree: Any) -> Any:
    """Checkpointed leaves arrive as host numpy; rebuild jax arrays (same
    dtypes) so downstream jitted code never sees numpy."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda l: jnp.asarray(l) if isinstance(l, np.ndarray) else l, tree
    )


def make_apply_fn(tx: Any) -> Any:
    """Jits ``(params, opt_state, grads) -> (params, opt_state)`` for an
    optax transform, with donation (old buffers consumed by the new ones).
    Shardings are inferred from the inputs, so the same function serves
    single-device and mesh-sharded states."""
    import jax
    import optax

    def apply(params: Any, opt_state: Any, grads: Any):
        # Mixed-precision-friendly: grads may arrive in a lower wire/compute
        # dtype (bf16 ring payloads, models.make_train_step(bf16_params=True));
        # the master update always runs in the params' own (f32) dtype.
        grads = jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype) if g.dtype != p.dtype else g,
            grads, params,
        )
        updates, new_opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state

    return jax.jit(apply, donate_argnums=(0, 1))


class FTTrainState:
    """Mutable holder for ``params`` + ``opt_state`` + the optax transform.

    Wire its ``state_dict``/``load_state_dict`` into the
    :class:`~torchft_tpu.manager.Manager` so live recovery flows through the
    same object the train loop reads::

        state = FTTrainState(params, optax.adamw(1e-3))
        manager = Manager(..., state_dict=state.state_dict,
                          load_state_dict=state.load_state_dict)
    """

    def __init__(self, params: Any, tx: Any, opt_state: Optional[Any] = None) -> None:
        self.params = params
        self.tx = tx
        self.opt_state = opt_state if opt_state is not None else tx.init(params)
        self._apply_jit: Optional[Any] = None

    def state_dict(self) -> Dict[str, Any]:
        """Snapshot for recovery transfer / durable checkpoints.

        The returned dict references the CURRENT buffers, and
        ``apply_gradients`` donates them — a snapshot is only valid until
        the next update. This is safe for live recovery because the manager
        re-locks the checkpoint gate (blocking on in-flight transfers)
        before the optimizer runs (reference manager.py:591 discipline);
        for durable checkpoints, serialize before the next step."""
        return {"params": self.params, "opt_state": self.opt_state}

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self.params = _to_device_tree(state_dict["params"])
        self.opt_state = _to_device_tree(state_dict["opt_state"])

    def snapshot(self) -> Dict[str, Any]:
        """Host copy of the full state (numpy leaves, fresh buffers).

        Unlike ``state_dict`` (which aliases live device buffers), the
        snapshot survives the device backend being torn down — the
        round-trip ``XLACollectives`` reconfiguration needs: a membership
        change rebuilds the XLA distributed runtime, orphaning every live
        jax array (torchft_tpu/xla_collectives.py:19-31)."""
        import jax

        return jax.tree_util.tree_map(
            lambda l: np.asarray(l).copy() if hasattr(l, "dtype") else l,
            {"params": self.params, "opt_state": self.opt_state},
        )

    def restore(self, snapshot: Dict[str, Any]) -> None:
        """Re-uploads a :meth:`snapshot` onto the (possibly new) backend.
        Drops the cached apply jit: its executable belongs to the old
        backend after a distributed-runtime rebuild."""
        self.load_state_dict(snapshot)
        self._apply_jit = None

    def warm(self, grads_like: Any) -> None:
        """AOT warm-up of the optimizer-update executable (standby
        discipline): jits and RUNS the apply function once on throwaway
        COPIES of the live state (zeros for gradients), so the first real
        ``apply_gradients`` after a standby promotion pays no trace or
        compile. Copies are required twice over: the jit donates its
        inputs, and a zero-grad adamw step still moves params (weight
        decay + bias correction) — the live state must stay untouched.
        The executable lands in jax's jit cache AND the persistent
        compilation cache, so it also pre-warms future cold restarts."""
        import jax
        import jax.numpy as jnp

        if self._apply_jit is None:
            self._apply_jit = make_apply_fn(self.tx)
        params = jax.tree_util.tree_map(jnp.copy, self.params)
        opt_state = jax.tree_util.tree_map(jnp.copy, self.opt_state)
        zeros = jax.tree_util.tree_map(
            lambda g: jnp.zeros_like(g) if hasattr(g, "dtype") else g,
            grads_like,
        )
        jax.block_until_ready(self._apply_jit(params, opt_state, zeros))

    def apply_gradients(self, grads: Any) -> None:
        """One optimizer update, in place (holder-level).

        The update is jitted (one fused kernel instead of an eager dispatch
        per optax op) with buffer donation, so HBM stays flat: old
        params/opt_state are consumed by the new ones (see the
        ``state_dict`` snapshot-lifetime note)."""
        if self._apply_jit is None:
            self._apply_jit = make_apply_fn(self.tx)
        self.params, self.opt_state = self._apply_jit(
            self.params, self.opt_state, grads
        )
