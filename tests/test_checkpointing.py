"""Checkpoint transport tests. Mirrors reference checkpointing_test.py:17-105:
HTTP round-trip, step mismatch -> error, timeout behavior, lock gating."""

import os
import threading
import urllib.error
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.checkpointing import (
    CheckpointServer,
    deserialize_state_dict,
    serialize_state_dict,
)


@pytest.fixture
def server():
    s = CheckpointServer(timeout=timedelta(seconds=10))
    yield s
    s.shutdown()


def test_roundtrip_pytree(server):
    state = {
        "model": {"w": np.arange(12, dtype=np.float32).reshape(3, 4)},
        "opt": [np.ones(3, np.float64), 7],
        "step": 42,
    }
    server.send_checkpoint([1], step=5, state_dict=state, timeout=timedelta(seconds=5))
    out = server.recv_checkpoint(
        src_rank=0, metadata=server.metadata(), step=5, timeout=timedelta(seconds=5)
    )
    np.testing.assert_array_equal(out["model"]["w"], state["model"]["w"])
    np.testing.assert_array_equal(out["opt"][0], state["opt"][0])
    assert out["opt"][1] == 7 and out["step"] == 42


def test_roundtrip_jax_arrays(server):
    import jax.numpy as jnp

    state = {"w": jnp.arange(8, dtype=jnp.bfloat16)}
    server.send_checkpoint([1], step=0, state_dict=state, timeout=timedelta(seconds=5))
    out = server.recv_checkpoint(
        src_rank=0, metadata=server.metadata(), step=0, timeout=timedelta(seconds=5)
    )
    # Received on host as numpy with the dtype preserved.
    assert out["w"].dtype == jnp.bfloat16.dtype
    np.testing.assert_array_equal(
        np.asarray(out["w"], np.float32), np.arange(8, dtype=np.float32)
    )


def test_wrong_step_is_an_error(server):
    server.send_checkpoint([1], step=3, state_dict={"x": 1}, timeout=timedelta(seconds=5))
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        server.recv_checkpoint(
            src_rank=0,
            metadata=server.metadata(),
            step=4,
            timeout=timedelta(seconds=5),
        )
    assert exc_info.value.code == 400


def test_starts_disallowed_and_regates(server):
    # Before any send_checkpoint, reads block until the server-side timeout.
    fast = CheckpointServer(timeout=timedelta(milliseconds=100))
    try:
        with pytest.raises(Exception):
            fast.recv_checkpoint(
                src_rank=0,
                metadata=fast.metadata(),
                step=0,
                timeout=timedelta(seconds=5),
            )
        fast.send_checkpoint([1], 1, {"x": 1}, timeout=timedelta(seconds=5))
        assert (
            fast.recv_checkpoint(
                src_rank=0,
                metadata=fast.metadata(),
                step=1,
                timeout=timedelta(seconds=5),
            )["x"]
            == 1
        )
        # disallow_checkpoint re-locks the gate (manager.py:591 discipline).
        fast.disallow_checkpoint()
        with pytest.raises(Exception):
            fast.recv_checkpoint(
                src_rank=0,
                metadata=fast.metadata(),
                step=1,
                timeout=timedelta(seconds=5),
            )
    finally:
        fast.shutdown()


def test_allow_disallow_idempotent(server):
    server.disallow_checkpoint()
    server.disallow_checkpoint()
    server.allow_checkpoint(1)
    server.allow_checkpoint(2)
    out = server.recv_checkpoint(
        src_rank=0, metadata=server.metadata(), step=2, timeout=timedelta(seconds=5)
    )
    assert out is None  # no state dict was ever set


def test_concurrent_readers(server):
    state = {"w": np.ones((256, 256), np.float32)}
    server.send_checkpoint(
        [1, 2, 3], step=9, state_dict=state, timeout=timedelta(seconds=5)
    )
    results = []
    errors = []

    def fetch():
        try:
            results.append(
                server.recv_checkpoint(
                    0, server.metadata(), 9, timeout=timedelta(seconds=10)
                )
            )
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=fetch) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(results) == 4
    for r in results:
        np.testing.assert_array_equal(r["w"], state["w"])


def test_serialize_handles_scalars_and_none():
    tree = {"a": None, "b": 3.5, "c": [np.int64(2), "s"]}
    out = deserialize_state_dict(serialize_state_dict(tree))
    assert out == tree


def test_optax_state_roundtrips_through_safelist():
    # Real recovery payloads carry optax namedtuple states; the safelisted
    # unpickler must reconstruct them type-intact so tx.update still works.
    import jax
    import jax.numpy as jnp
    import optax

    params = {"w": jnp.ones((3,))}
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    out = deserialize_state_dict(
        serialize_state_dict({"params": params, "opt_state": opt_state})
    )
    restored = jax.tree_util.tree_map(jnp.asarray, out["opt_state"])
    updates, _ = tx.update(
        {"w": jnp.ones((3,))},
        restored,
        jax.tree_util.tree_map(jnp.asarray, out["params"]),
    )
    assert jax.tree_util.tree_structure(restored) == (
        jax.tree_util.tree_structure(opt_state)
    )


def test_malicious_pickle_rejected():
    # The classic RCE gadget must not resolve (reference posture is
    # torch.load(weights_only=False); this transport is stricter).
    import pickle

    class Evil:
        def __reduce__(self):
            return (os.system, ("echo pwned",))

    payload = pickle.dumps(Evil())
    with pytest.raises(pickle.UnpicklingError, match="disallowed global"):
        deserialize_state_dict(payload)


def test_safelist_not_extensible_from_payload():
    # The bypass class: a payload that calls register_safe_modules("os")
    # mid-load and then resolves os.system. Both hops must fail — functions
    # are never resolvable and the safelist is snapshotted per load.
    import pickle

    from torchft_tpu.checkpointing import register_safe_modules

    class Sneaky:
        def __reduce__(self):
            return (register_safe_modules, ("os",))

    with pytest.raises(pickle.UnpicklingError, match="disallowed global"):
        deserialize_state_dict(pickle.dumps(Sneaky()))

    class Evil:
        def __reduce__(self):
            return (os.system, ("echo pwned",))

    # ...and "os" must not have leaked into the process-global safelist.
    with pytest.raises(pickle.UnpicklingError, match="disallowed global"):
        deserialize_state_dict(pickle.dumps(Evil()))


def test_functions_in_safe_modules_rejected():
    # Class-only rule: numpy itself is safelisted, but a REDUCE on one of
    # its functions (arbitrary-call primitive) must not resolve.
    import pickle

    class FnGadget:
        def __reduce__(self):
            return (np.array, ([1, 2],))

    with pytest.raises(pickle.UnpicklingError, match="disallowed global"):
        deserialize_state_dict(pickle.dumps(FnGadget()))


def test_register_safe_modules_extends_allowlist():
    from torchft_tpu.checkpointing import (
        _SAFE_MODULE_ROOTS,
        register_safe_modules,
    )

    assert "fractions" not in _SAFE_MODULE_ROOTS
    import fractions
    import pickle

    payload = pickle.dumps(fractions.Fraction(1, 3))
    with pytest.raises(pickle.UnpicklingError):
        deserialize_state_dict(payload)
    register_safe_modules("fractions")
    try:
        assert deserialize_state_dict(payload) == fractions.Fraction(1, 3)
    finally:
        _SAFE_MODULE_ROOTS.discard("fractions")


def test_streaming_no_full_payload_buffer(server, monkeypatch):
    """The HTTP path must STREAM (reference checkpointing.py:139-170):
    chunked transfer on the wire, no serialize_state_dict() full-bytes
    buffer on the server, incremental unpickle on the receiver. The state
    is several times larger than any internal chunk, so a buffering
    implementation would materialize tens of MB here."""
    import urllib.request
    from datetime import timedelta

    import numpy as np

    from torchft_tpu import checkpointing as C

    def boom(_):
        raise AssertionError(
            "serialize_state_dict (full-payload buffer) used on the "
            "HTTP serving path"
        )

    monkeypatch.setattr(C, "serialize_state_dict", boom)
    big = {
        f"w{i}": np.random.default_rng(i).standard_normal((1 << 20,))
        for i in range(8)  # 8 x 8 MB leaves
    }
    server.send_checkpoint([1], step=3, state_dict=big,
                           timeout=timedelta(seconds=10))
    # wire-level check: chunked, no Content-Length
    with urllib.request.urlopen(f"{server.address()}3", timeout=10) as f:
        assert f.headers.get("Content-Length") is None
        assert f.headers.get("Transfer-Encoding") == "chunked"
        out = C.load_state_dict_stream(f)
    for k, v in big.items():
        np.testing.assert_array_equal(out[k], v)
    # stripes=1 selects the streamed client path directly (the striped
    # default trades this bounded-memory property for bandwidth, so it
    # must be pinned here for the assertion to mean anything)
    monkeypatch.setenv("TORCHFT_CKPT_STRIPES", "1")
    out2 = server.recv_checkpoint(
        0, server.address(), 3, timeout=timedelta(seconds=10)
    )
    np.testing.assert_array_equal(out2["w0"], big["w0"])


def test_striped_parallel_fetch_roundtrip(server):
    """The striped path: N byte ranges over N parallel connections
    (/checkpoint/{step}/part/{i}/{n}), reassembled and deserialized
    through the same safelist. Parts are ranged (Content-Length), not
    chunked — the server serves them from a per-step pickle cache."""
    import urllib.request

    big = {
        f"w{i}": np.random.default_rng(i).standard_normal((1 << 18,))
        for i in range(4)
    }
    server.send_checkpoint([1], step=9, state_dict=big,
                           timeout=timedelta(seconds=10))
    out = CheckpointServer.load_from_address(
        f"{server.address()}9", timeout=timedelta(seconds=10), stripes=4
    )
    for k, v in big.items():
        np.testing.assert_array_equal(out[k], v)
    with urllib.request.urlopen(f"{server.address()}9/part/0/4",
                                timeout=10) as f:
        assert f.headers.get("Content-Length") is not None
    # a part request for the wrong step is the same 400 contract
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(f"{server.address()}8/part/0/4", timeout=10)
    assert exc_info.value.code == 400
    # out-of-range part index is a 404, not a hang
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(f"{server.address()}9/part/4/4", timeout=10)
    assert exc_info.value.code == 404


def test_striped_fetch_falls_back_on_legacy_server(server, monkeypatch):
    """Against a pre-striping peer (no /part/ nor /stream/ endpoint ->
    404/500) the client must heal at single-stream speed, not fail."""
    import urllib.request

    state = {"w": np.arange(32, dtype=np.float32)}
    server.send_checkpoint([1], step=2, state_dict=state,
                           timeout=timedelta(seconds=10))
    real = urllib.request.urlopen

    def legacy(url, timeout=None):
        u = str(url)
        if "/part/" in u or "/stream" in u:
            raise urllib.error.HTTPError(u, 404, "no such path", {}, None)
        return real(url, timeout=timeout)

    monkeypatch.setattr(urllib.request, "urlopen", legacy)
    out = CheckpointServer.load_from_address(
        f"{server.address()}2", timeout=timedelta(seconds=10), stripes=4
    )
    np.testing.assert_array_equal(out["w"], state["w"])


# -- streamed zero-copy heal pipeline ---------------------------------------


def _donor_state():
    """A realistic heal payload: f32 params, optax adamw state (f32
    moments + int count), manager counters, and a non-array leaf mix."""
    import jax
    import jax.numpy as jnp
    import optax

    params = {
        "dense": jnp.asarray(
            np.random.default_rng(0).standard_normal((257, 31), np.float32)
        ),
        "bias": jnp.asarray(
            np.random.default_rng(1).standard_normal((31,), np.float32)
        ),
    }
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    # make the moments non-trivial so bf16 rounding is observable
    opt_state = jax.tree_util.tree_map(
        lambda l: l + 0.1234567 if hasattr(l, "dtype")
        and l.dtype == jnp.float32 else l,
        opt_state,
    )
    return {
        "user": {
            "params": params,
            "opt_state": opt_state,
            # f32 leaf OUTSIDE both params and opt_state: the bf16 wire
            # must protect-by-default (ship raw), not round it
            "ema_weights": jnp.asarray(
                np.random.default_rng(2).standard_normal((19,), np.float32)
            ),
        },
        "torchft": {"step": 17, "batches_committed": 51},
    }


@pytest.mark.parametrize("wire", [None, "bf16"])
@pytest.mark.parametrize("streams", [1, 2, 4])
def test_stream_heal_params_bit_identical(server, wire, streams):
    """The acceptance oracle: across every wire x stream-count
    combination, the healed replica's PARAMS are bit-identical to the
    donor's f32 buffers. The bf16 wire may round ONLY f32 leaves under
    an ``opt_state`` key (optimizer moments); everything else —
    params, and any leaf the predicate doesn't recognize — ships raw
    (protect-by-default)."""
    import jax

    state = _donor_state()
    server.send_checkpoint([1], step=7, state_dict=state,
                           timeout=timedelta(seconds=10))
    out, stats = CheckpointServer._fetch(
        f"{server.address()}7", timeout=timedelta(seconds=10),
        wire=wire, streams=streams,
    )
    assert stats["path"] == "stream"
    assert stats["streams"] == streams and stats["wire"] == wire
    for key in ("dense", "bias"):
        donor = np.asarray(state["user"]["params"][key])
        healed = np.asarray(out["user"]["params"][key])
        assert healed.dtype == donor.dtype
        assert healed.tobytes() == donor.tobytes()  # BIT identity
    # optimizer state: exact on the raw wire, bf16-rounded under bf16
    donor_leaves = jax.tree_util.tree_leaves(state["user"]["opt_state"])
    healed_leaves = jax.tree_util.tree_leaves(out["user"]["opt_state"])
    assert len(donor_leaves) == len(healed_leaves)
    import ml_dtypes

    for d, h in zip(donor_leaves, healed_leaves):
        d = np.asarray(d)
        h = np.asarray(h)
        assert h.dtype == d.dtype
        if wire == "bf16" and d.dtype == np.dtype(np.float32):
            expected = d.astype(ml_dtypes.bfloat16).astype(np.float32)
            np.testing.assert_array_equal(h, expected)
        else:
            assert h.tobytes() == d.tobytes()
    # a leaf outside params AND opt_state ships raw on EVERY wire:
    # protect-by-default, never silent rounding of maybe-weights
    assert (
        np.asarray(out["user"]["ema_weights"]).tobytes()
        == np.asarray(state["user"]["ema_weights"]).tobytes()
    )
    # skeleton-borne non-array leaves survive untouched
    assert out["torchft"] == {"step": 17, "batches_committed": 51}


def test_stream_heal_donor_never_pickles_bulk(server, monkeypatch):
    """The zero-copy contract on the donor: serving a streamed heal must
    not serialize the state dict (no per-request pickle, no full-payload
    cache) — only the small skeleton meta is pickled."""
    from torchft_tpu import checkpointing as C

    def boom(_):
        raise AssertionError(
            "serialize_state_dict used on the streamed heal path"
        )

    monkeypatch.setattr(C, "serialize_state_dict", boom)
    state = _donor_state()
    server.send_checkpoint([1], step=4, state_dict=state,
                           timeout=timedelta(seconds=10))
    out = server.recv_checkpoint(
        0, server.metadata(), 4, timeout=timedelta(seconds=10)
    )
    assert server.last_fetch_stats["path"] == "stream"
    assert server.last_fetch_stats["bytes"] > 0
    np.testing.assert_array_equal(
        np.asarray(out["user"]["params"]["dense"]),
        np.asarray(state["user"]["params"]["dense"]),
    )


def test_stream_heal_wrong_step_is_an_error(server):
    server.send_checkpoint([1], step=3, state_dict=_donor_state(),
                           timeout=timedelta(seconds=10))
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        CheckpointServer._fetch(
            f"{server.address()}5", timeout=timedelta(seconds=10)
        )
    assert exc_info.value.code == 400


def test_stream_heal_env_knobs(server, monkeypatch):
    """TORCHFT_HEAL_WIRE / TORCHFT_HEAL_STREAMS select the default wire
    and stream depth for recv_checkpoint (the manager heal path)."""
    monkeypatch.setenv("TORCHFT_HEAL_WIRE", "bf16")
    monkeypatch.setenv("TORCHFT_HEAL_STREAMS", "3")
    state = _donor_state()
    server.send_checkpoint([1], step=11, state_dict=state,
                           timeout=timedelta(seconds=10))
    out = server.recv_checkpoint(
        0, server.metadata(), 11, timeout=timedelta(seconds=10)
    )
    stats = server.last_fetch_stats
    assert stats["path"] == "stream"
    assert stats["wire"] == "bf16" and stats["streams"] == 3
    # params still bit-identical under the env-selected bf16 wire
    assert (
        np.asarray(out["user"]["params"]["bias"]).tobytes()
        == np.asarray(state["user"]["params"]["bias"]).tobytes()
    )


def test_stream_stale_publish_rejected(server):
    """A range request carrying the nonce of a SUPERSEDED publish must
    400, even at the same step: serving it from the new staging would
    hand a straggler-striped reader a torn mix of two checkpoints."""
    import urllib.request

    from torchft_tpu import checkpointing as C

    s1 = {"w": np.ones(256, np.float32)}
    server.send_checkpoint([1], step=6, state_dict=s1,
                           timeout=timedelta(seconds=10))
    with urllib.request.urlopen(
        f"{server.address()}6/streammeta/none", timeout=10
    ) as f:
        seq = C._SafeUnpickler(f).load()["seq"]
    # range with the live nonce serves
    with urllib.request.urlopen(
        f"{server.address()}6/stream/0/2/none/{seq}", timeout=10
    ) as f:
        assert len(f.read()) == 512  # half of 256 f32
    # republish at the SAME step
    server.disallow_checkpoint()
    server.send_checkpoint([1], step=6,
                           state_dict={"w": np.zeros(256, np.float32)},
                           timeout=timedelta(seconds=10))
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(
            f"{server.address()}6/stream/0/2/none/{seq}", timeout=10
        )
    assert exc_info.value.code == 400
    # a fresh fetch (meta + ranges under the new nonce) heals fine
    out = CheckpointServer.load_from_address(
        f"{server.address()}6", timeout=timedelta(seconds=10)
    )
    np.testing.assert_array_equal(
        np.asarray(out["w"]), np.zeros(256, np.float32)
    )


def test_stream_disallow_clears_staging_and_regates(server):
    """disallow_checkpoint must invalidate the stream staging (it aliases
    the live buffers) and re-gate the endpoints."""
    state = _donor_state()
    server.send_checkpoint([1], step=1, state_dict=state,
                           timeout=timedelta(seconds=10))
    CheckpointServer.load_from_address(
        f"{server.address()}1", timeout=timedelta(seconds=10)
    )
    assert server._stagings  # staging was built
    server.disallow_checkpoint()
    assert not server._stagings
    fast = CheckpointServer(timeout=timedelta(milliseconds=200))
    try:
        fast.send_checkpoint([1], 1, {"x": np.ones(4, np.float32)},
                             timeout=timedelta(seconds=5))
        fast.disallow_checkpoint()
        with pytest.raises(Exception):
            fast.recv_checkpoint(
                0, fast.metadata(), 1, timeout=timedelta(seconds=5)
            )
    finally:
        fast.shutdown()


# -- heal-stream fault fallback (the PR 5 contract under injected faults) ----
# Previously only timeout exhaustion was exercised; these cover the torn
# donor responses the chaos plane injects: a TRUNCATED range body and a
# mid-range CONNECTION RESET. Contract: the receiver cancels its
# surviving range readers and falls back to the pickled paths WITHOUT
# double-counting the timeout budget, and the healed bytes are exact.


def _proxy_for(server):
    import urllib.parse

    from torchft_tpu.chaos import HealFaultProxy

    parts = urllib.parse.urlparse(server.address())
    proxy = HealFaultProxy(f"{parts.scheme}://{parts.netloc}")
    return proxy, proxy.address() + parts.path


@pytest.mark.parametrize("mode", ["truncate_body", "reset_mid_range"])
def test_stream_fault_falls_back_within_budget(server, mode):
    import time

    state = {"params": {"w": np.arange(65536, dtype=np.float32)}}
    server.send_checkpoint([1], step=2, state_dict=state,
                           timeout=timedelta(seconds=10))
    proxy, addr = _proxy_for(server)
    try:
        proxy.mode = mode
        proxy.only_paths = ("/stream/",)
        proxy.max_faults = 1
        budget = timedelta(seconds=10)
        t0 = time.monotonic()
        out, stats = CheckpointServer._fetch(
            addr + "2", timeout=budget, streams=4
        )
        wall = time.monotonic() - t0
        assert proxy.faults_fired == 1
        # fell back off the stream path; data exact
        assert stats["path"] != "stream"
        np.testing.assert_array_equal(
            out["params"]["w"], state["params"]["w"]
        )
        # no budget double-counting: a torn response fails FAST (the
        # range reader sees a short read/reset immediately), so the
        # whole heal — stream attempt + fallback — stays well inside
        # ONE budget, not stacked fresh budgets per fallback tier
        assert wall < budget.total_seconds(), wall
    finally:
        proxy.shutdown()


def test_stream_fault_cancels_surviving_readers(server):
    """After a torn range kills the stream fetch, the donor's in-flight
    reader count must drain promptly — the surviving range readers were
    CANCELLED, not left downloading against the fallback (which would
    pin the donor's next disallow_checkpoint)."""
    import time

    state = {"params": {"w": np.arange(1 << 18, dtype=np.float32)}}
    server.send_checkpoint([1], step=3, state_dict=state,
                           timeout=timedelta(seconds=10))
    proxy, addr = _proxy_for(server)
    try:
        proxy.mode = "reset_mid_range"
        proxy.only_paths = ("/stream/",)
        proxy.max_faults = 1
        out, _stats = CheckpointServer._fetch(
            addr + "3", timeout=timedelta(seconds=10), streams=4
        )
        np.testing.assert_array_equal(
            out["params"]["w"], state["params"]["w"]
        )
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:  # deadline-bounded poll
            with server._stream_cv:
                if server._stream_inflight == 0:
                    break
            time.sleep(0.05)
        assert server._stream_inflight == 0
    finally:
        proxy.shutdown()


# ---------------------------------------------------------------------------
# Range-limited capture (the durable tier's shard_of= discipline)
# ---------------------------------------------------------------------------


def _shard_state():
    # Odd sizes chosen so W=3 floor-split boundaries land mid-leaf AND
    # mid-element on the bf16 wire (opt_state halves its byte width).
    return {
        "params": {
            "w": np.arange(1001, dtype=np.float32),
            "b": np.arange(7, dtype=np.float32) * 0.5,
        },
        "opt_state": {
            "m": np.arange(503, dtype=np.float32) * 0.25,
            "v": np.arange(129, dtype=np.float32) * 4.0,
        },
        "step": 9,
    }


@pytest.mark.parametrize("wire", [None, "bf16"])
@pytest.mark.parametrize("world", [1, 2, 3, 5])
def test_range_capture_reassembles_full_stream(wire, world):
    """Concatenating every member's shard_of capture over its
    shard_bounds span must be byte-identical to the unsharded stream —
    straddling leaves contribute exactly their in-range element slice,
    with the wire-itemsize outward alignment covering split elements."""
    import io

    from torchft_tpu.checkpointing import _StreamStaging
    from torchft_tpu.durable import shard_bounds

    state = _shard_state()
    full = _StreamStaging(state, wire, snapshot=True)
    buf = io.BytesIO()
    full.write_range(buf, 0, full.total)
    want = buf.getvalue()
    assert len(want) == full.total

    got = b""
    for rank in range(world):
        bounds = shard_bounds(full.total, world)
        begin, end = bounds[rank], bounds[rank + 1]
        st = _StreamStaging(
            state, wire, snapshot=True, shard_of=(rank, world)
        )
        assert st.total == full.total  # layout is shard-blind
        b = io.BytesIO()
        st.write_range(b, begin, end)
        piece = b.getvalue()
        assert len(piece) == end - begin
        # capture cost is the member's span plus at most one wire
        # element of outward alignment per straddled boundary (params
        # stay f32 even on the bf16 wire, so the element is <= 4 bytes)
        assert st.captured_bytes <= (end - begin) + 2 * 4
        assert st.range_crc32c(begin, end) == full.range_crc32c(begin, end)
        got += piece
    assert got == want


def test_range_capture_out_of_span_read_raises():
    """A shard-limited staging must refuse reads outside its captured
    span — a silent zero-fill or a bisect wrap to the wrong segment
    would ship a torn shard that still CRCs clean."""
    import io

    from torchft_tpu.checkpointing import _StreamStaging
    from torchft_tpu.durable import shard_bounds

    state = _shard_state()
    probe = _StreamStaging(state, None, snapshot=True)
    bounds = shard_bounds(probe.total, 3)
    begin, end = bounds[1], bounds[2]
    st = _StreamStaging(state, None, snapshot=True, shard_of=(1, 3))
    for bad in [(0, end), (begin, probe.total), (begin - 1, end)]:
        with pytest.raises(ValueError, match="outside captured span"):
            st.write_range(io.BytesIO(), *bad)
        with pytest.raises(ValueError, match="outside captured span"):
            st.range_crc32c(*bad)
    # the span itself stays servable after the failed reads
    b = io.BytesIO()
    st.write_range(b, begin, end)
    assert len(b.getvalue()) == end - begin


def test_range_capture_pinned_defers_wire_cast():
    """pin_leaves=True with jax leaves: capture stores views + deferred
    (slice, wdtype) casts, and the writer-side _seg() resolution yields
    bytes identical to the eager-copy capture."""
    import io

    import jax.numpy as jnp

    from torchft_tpu.checkpointing import _StreamStaging
    from torchft_tpu.durable import shard_bounds

    state = {
        "params": {"w": jnp.arange(257, dtype=jnp.float32)},
        "opt_state": {"m": jnp.arange(130, dtype=jnp.float32) * 0.5},
    }
    eager = _StreamStaging(state, "bf16", snapshot=True, shard_of=(0, 2))
    pinned = _StreamStaging(
        state, "bf16", snapshot=True, shard_of=(0, 2), pin_leaves=True
    )
    assert pinned._pins  # jax leaves really were pinned, not copied
    begin, end = shard_bounds(eager.total, 2)[:2]
    be, bp = io.BytesIO(), io.BytesIO()
    eager.write_range(be, begin, end)
    pinned.write_range(bp, begin, end)
    assert be.getvalue() == bp.getvalue()
    assert pinned.range_crc32c(begin, end) == eager.range_crc32c(begin, end)
