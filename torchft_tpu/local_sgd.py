"""Fault-tolerant LocalSGD and DiLoCo: communication-efficient data
parallelism across replica groups.

Reference: torchft/local_sgd.py. Inner steps run purely locally (no
cross-group traffic); every ``sync_every`` steps the groups synchronize
through the manager — a quorum + fault-tolerant allreduce + commit vote. On
a failed commit the whole window is discarded and parameters reset to the
last synchronized state, preserving exactly-``sync_every`` semantics
(reference local_sgd.py:35-46).

JAX shape: the reference hooks ``optimizer.step``; here the train loop calls
``local_sgd.step(grads)`` explicitly (optax has no hooks), which applies the
inner update and triggers ``sync()`` on the window boundary. The backup copy
stays ON DEVICE — the reference offloads it to pinned CPU memory
(local_sgd.py:81-91) because GPU memory is scarce, but on TPU a second
params copy is cheap HBM while every device↔host crossing rides the slow
link; an HBM↔HBM copy per window replaces two full-tree transfers. The
checkpoint transport converts to host only when a recovery peer actually
asks (checkpointing._to_host).

DiLoCo (https://arxiv.org/pdf/2311.08105): inner optimizer steps locally;
at the window boundary the *pseudogradient* Δ = θ_global_old − θ_local_new
is averaged across groups and fed to an outer optimizer (typically SGD with
Nesterov momentum) on the restored global params. Note the sign: this
follows the paper; the reference snapshot computes ``p.data - backup``
(local_sgd.py:214), the negation (fixed upstream later).
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from .collectives import ReduceOp
from .manager import Manager
from .train_state import FTTrainState, _to_device_tree

logger: logging.Logger = logging.getLogger(__name__)


_copy_jit: Any = None


def _detached_copy(tree: Any) -> Any:
    """Detached same-device copy of every array leaf (HBM→HBM for jax
    arrays — never crosses the host link); numpy leaves are copied on
    host. All-jax trees copy through ONE jitted program (one dispatch per
    window instead of one per leaf — eager per-leaf RPCs add up on remote
    device runtimes)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if leaves and all(isinstance(l, jax.Array) for l in leaves):
        global _copy_jit
        if _copy_jit is None:
            # jit outputs never alias non-donated inputs: fresh buffers.
            _copy_jit = jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t)
            )
        return _copy_jit(tree)
    return jax.tree_util.tree_map(
        lambda l: l.copy() if isinstance(l, jax.Array) else np.array(l), tree
    )


class LocalSGD:
    """Periodic parameter averaging (https://arxiv.org/pdf/1805.09767),
    fault-tolerant. Reference local_sgd.py:26-174.

    Usage::

        local = LocalSGD(manager, state, sync_every=32)
        for batch in data:
            grads = grad_fn(state.params, batch)
            local.step(grads)           # inner update; syncs every 32 steps

    Wire the manager's state callbacks to :meth:`state_dict` /
    :meth:`load_state_dict` (NOT the bare train state) so recovering
    replicas receive the backup copy and sync bookkeeping too.
    """

    def __init__(self, manager: Manager, state: FTTrainState, sync_every: int) -> None:
        assert sync_every >= 1, "sync_every must be >= 1"
        self._manager = manager
        self._state = state
        self._sync_every = sync_every
        self._local_step = 0
        # On-device backup of the last synchronized params (role of the
        # reference's CPU backup, :81-95; see module docstring).
        self._backup_params: Any = _detached_copy(state.params)

    # -- train-loop surface --

    def step(self, grads: Any) -> None:
        """One inner optimizer step; synchronizes on the window boundary
        (the reference's optimizer post-hook, local_sgd.py:133-141)."""
        self._state.apply_gradients(grads)
        self.step_applied()

    def step_applied(self) -> None:
        """Window accounting for a caller that already applied the inner
        update itself — e.g. a FUSED grad+apply train step
        (models.make_train_step), one program launch instead of two and
        measured ~8% faster per inner step on v5e at the 111M-param
        config. Inner steps have no per-step cross-group work, so the
        LocalSGD family only needs the count::

            train_step = make_train_step(cfg, optax.adamw(1e-3))
            for batch in data:
                state.params, state.opt_state, loss = train_step(
                    state.params, state.opt_state, batch)
                local.step_applied()      # syncs every sync_every steps
        """
        self._local_step += 1
        if self._local_step >= self._sync_every:
            self.sync()

    def sync(self) -> None:
        """Synchronizes across replica groups. Reference local_sgd.py:143-149."""
        self._manager.start_quorum()
        self._perform_sync()
        self._local_step = 0

    # -- checkpoint plumbing (manager state callbacks) --

    def state_dict(self) -> Dict[str, Any]:
        return {
            "state": self._state.state_dict(),
            "backup_params": self._backup_params,
            "local_step": self._local_step,
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._state.load_state_dict(sd["state"])
        # Checkpoints deliver numpy leaves; bring the backup to device.
        self._backup_params = _to_device_tree(sd["backup_params"])
        self._local_step = sd["local_step"]

    # -- internals --

    def _save_parameters(self) -> None:
        self._backup_params = _detached_copy(self._state.params)

    def _restore_parameters(self) -> None:
        # COPY, never alias: FTTrainState.apply_gradients donates its
        # params buffers, so handing the backup itself to state.params
        # would let the next inner step delete the backup.
        self._state.params = _detached_copy(self._backup_params)

    def _perform_sync(self) -> None:
        """Average params; commit -> new backup, abort -> roll the whole
        window back (reference local_sgd.py:151-162)."""
        averaged = self._manager.allreduce(
            self._state.params, op=ReduceOp.AVG
        ).wait()
        if self._manager.should_commit():
            self._state.params = averaged
            self._save_parameters()
        else:
            self._restore_parameters()


class DiLoCo(LocalSGD):
    """Distributed Low-Communication training. Reference local_sgd.py:177-239.

    Requires sync quorum (``use_async_quorum=False``) so a recovering
    replica restores the checkpoint before its first inner step (reference
    :195-199)."""

    def __init__(
        self,
        manager: Manager,
        state: FTTrainState,
        outer_tx: Any,
        sync_every: int,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        super().__init__(manager, state, sync_every)
        self._outer_tx = outer_tx
        self._outer_state = outer_tx.init(state.params)

    def state_dict(self) -> Dict[str, Any]:
        sd = super().state_dict()
        sd["outer_state"] = self._outer_state
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        super().load_state_dict(sd)
        self._outer_state = _to_device_tree(sd["outer_state"])

    def _perform_sync(self) -> None:
        """Average pseudogradients, outer-step from the restored global
        params on commit (reference local_sgd.py:205-225)."""
        import jax
        import optax

        old_global = _to_device_tree(self._backup_params)
        # Paper sign: Δ = θ_global_old − θ_local_new, so the outer optimizer
        # descends toward the inner-trained weights.
        pseudo_grads = jax.tree_util.tree_map(
            lambda old, new: old - new, old_global, self._state.params
        )
        averaged = self._manager.allreduce(pseudo_grads, op=ReduceOp.AVG).wait()

        # Restore to the last global state before applying the outer step.
        # Copy: state.params buffers get donated by the next inner step,
        # and old_global aliases the on-device backup.
        self._state.params = _detached_copy(old_global)

        if self._manager.should_commit():
            updates, self._outer_state = self._outer_tx.update(
                averaged, self._outer_state, self._state.params
            )
            self._state.params = optax.apply_updates(
                self._state.params, updates
            )
            self._save_parameters()


class AsyncDiLoCo(DiLoCo):
    """DiLoCo with the cross-group sync OVERLAPPED with the next window's
    inner steps (the delayed/eager outer-update idea of Streaming DiLoCo,
    https://arxiv.org/pdf/2501.18512): at a window boundary the
    pseudogradient allreduce is *launched* asynchronously and training
    continues immediately; the outer update is applied one window late,
    reconciled against the inner progress made in the meantime.

    This is the bandwidth-appropriate cross-replica-group mode on TPU pods:
    the host ring rides DCN at a fraction of step time only if it can hide
    behind compute, and inner steps never leave the chip. Let B be the last
    global params, θ the live params. At boundary k:

      1. finish window k-1's in-flight sync (below),
      2. compute Δ = B − θ, launch ``allreduce(Δ)`` (device→host packing and
         ring transfer run on the collectives' op thread), keep training.

    When the result lands (checked at boundary k+1):
      commit → G' = outer_update(B, Δ_avg);  θ += G' − (B − Δ);  B = G'
               (replaces window k's local-only progress with the
               globally-agreed version, keeping window k+1's progress)
      abort  → θ += Δ   (rolls back window k, keeps window k+1's progress)

    With a single group and outer SGD(lr=1), G' = B − Δ and the correction
    vanishes — AsyncDiLoCo degenerates to pure local training, the identity
    the unit tests pin. Inherits DiLoCo's sync-quorum requirement for heal
    correctness; call :meth:`flush` before checkpointing or shutdown so no
    window is left in flight."""

    def __init__(
        self,
        manager: Manager,
        state: FTTrainState,
        outer_tx: Any,
        sync_every: int,
        compress: Any = None,
        overlap: bool = True,
    ) -> None:
        """``compress="bf16"`` casts pseudogradients to bfloat16 on-device
        before the allreduce — halving device→host, wire (native bf16
        dtype), and host→device bytes. Standard DiLoCo practice: the outer
        optimizer sees bf16-rounded pseudogradients, the f32 master params
        are untouched.

        Quantized modes (both: per-leaf int8 with a f32 scale and ERROR
        FEEDBACK — the quantization residual is added to the next
        window's delta, so rounding error never accumulates). Two
        transports for two bottlenecks:

        ``compress="int8"``: the int8 payload itself ({q, scale} leaves)
        rides a managed device-packed ALLGATHER and is dequantize-averaged
        member-wise — the DEVICE<->HOST link carries int8 bytes (4x fewer
        than f32, 2x fewer than bf16), for hosts where that link is the
        bottleneck. Allgather traffic grows with cohort size; intended
        for small cohorts.

        ``compress="q8"``: the dequantized (int8-gridded f32) delta rides
        the native ring's quantized wire (int8 chunks with per-chunk
        scales, dequant-accumulated per hop): TCP sync bytes are CONSTANT
        in cohort size, for DCN deployments where the network is the
        bottleneck and cohorts are larger. The ring's per-chunk regrid
        adds at most one quantization step of noise, which the next
        window's error feedback does not see (documented lossy wire).

        ``overlap=False`` completes the sync AT the boundary instead of one
        window later (the reconciliation degenerates to θ = G', i.e. exact
        synchronous DiLoCo, but through the same jitted ops). Use it on
        hosts where device↔host transfers contend with compute dispatch
        (e.g. a tunneled/proxied device runtime): there, an in-flight
        transfer under a stream of async dispatches can starve for far
        longer than its serial wall time, and a blocking boundary sync is
        strictly faster."""
        if compress not in (None, "bf16", "int8", "q8"):
            raise ValueError(f"unsupported compress mode: {compress}")
        super().__init__(manager, state, outer_tx, sync_every)
        self._compress = compress
        self._overlap = overlap
        # (work, shipped delta, pre-launch residual) of the in-flight window
        self._pending: Any = None
        self._delta_fn: Any = None  # jitted Δ = B − θ (with optional cast)
        self._commit_fn: Any = None  # jitted delayed outer update + reconcile
        self._abort_fn: Any = None  # jitted window rollback
        self._quant_fn: Any = None    # int8/q8: jitted quantize + EF update
        self._combine_fns: Dict[int, Any] = {}  # int8: per-cohort avg
        self._residual: Any = None    # int8/q8: error-feedback carry

    def sync(self) -> None:
        self._finish_pending()
        self._manager.start_quorum()
        self._launch_sync()
        if not self._overlap:
            self._finish_pending()
        self._local_step = 0

    def flush(self) -> None:
        """Completes any in-flight window sync (call before reading final
        params, checkpointing durably, or shutdown)."""
        self._finish_pending()

    def state_dict(self) -> Dict[str, Any]:
        self._finish_pending()
        return super().state_dict()

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        super().load_state_dict(sd)
        # The int8 error-feedback carry is trajectory-local: after a heal
        # or durable restore the replica is on ANOTHER trajectory's
        # params, so the stale residual would inject a fraction of a
        # discarded correction into the next window. Reset it (a clean
        # restart's state).
        self._residual = None

    def _launch_sync(self) -> None:
        import time

        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        old_global = _to_device_tree(self._backup_params)

        if self._compress in ("int8", "q8"):
            if self._residual is None:
                self._residual = jax.tree_util.tree_map(
                    lambda l: jnp.zeros(l.shape, jnp.float32),
                    self._state.params,
                )
            if self._quant_fn is None:
                from .quantize import quantize_with_feedback

                def quant_fn(old, new, residual):
                    delta = jax.tree_util.tree_map(
                        lambda o, n: o - n, old, new
                    )
                    return quantize_with_feedback(delta, residual)

                self._quant_fn = jax.jit(quant_fn)

            prev_residual = self._residual
            out = self._quant_fn(
                old_global, self._state.params, prev_residual
            )
            self._residual = out["res"]  # EF carry (restored on abort)
            if self._compress == "int8":
                # int8 BYTES cross the device link (device-packed
                # allgather); the finish side dequantize-averages
                work = self._manager.allgather(
                    {"q": out["q"], "scale": out["scale"]}
                )
            else:
                # q8: ship the DEQUANTIZED delta over the ring's
                # quantized wire — the values are already on the int8
                # grid leaf-wise (EF accounts for that rounding); the
                # ring re-grids per chunk and returns the averaged f32
                # tree directly, constant TCP bytes in cohort size
                work = self._manager.allreduce(
                    out["dq"], op=ReduceOp.AVG, wire="q8"
                )
            # reconcile against what we actually SHIPPED (the dequantized
            # local delta), same role as the bf16-rounded delta below
            self._pending = (work, out["dq"], prev_residual)
            logger.debug(
                "int8 sync launched in %.2fs", time.perf_counter() - t0
            )
            return

        if self._delta_fn is None:
            wire_dtype = jnp.bfloat16 if self._compress == "bf16" else None

            def delta_fn(old, new):
                return jax.tree_util.tree_map(
                    lambda o, n: (o - n).astype(wire_dtype)
                    if wire_dtype is not None
                    else o - n,
                    old,
                    new,
                )

            self._delta_fn = jax.jit(delta_fn)

        delta = self._delta_fn(old_global, self._state.params)
        work = self._manager.allreduce(delta, op=ReduceOp.AVG)
        self._pending = (work, delta, None)
        logger.debug(
            "sync launched in %.2fs", time.perf_counter() - t0
        )

    def _finish_pending(self) -> None:
        import time

        import jax
        import optax

        if self._pending is None:
            return
        work, delta, prev_residual = self._pending
        self._pending = None
        t0 = time.perf_counter()
        result = work.wait()
        logger.debug("sync ring wait %.2fs", time.perf_counter() - t0)
        t0 = time.perf_counter()
        if self._compress == "int8":
            # member-wise dequantize, then average over PARTICIPANTS:
            # non-participating (healing/spare) entries arrive zeroed
            # (Manager.allgather) and must not dilute the divisor
            import jax.numpy as jnp

            cohort = len(result)
            combine = self._combine_fns.get(cohort)
            if combine is None:
                from .quantize import make_dequant_average

                combine = self._combine_fns[cohort] = \
                    make_dequant_average()
            averaged = combine(
                result,
                jnp.float32(max(self._manager.num_participants(), 1)),
            )
        else:
            # bf16 / q8 / plain: the wire returns the averaged delta tree
            averaged = result
        old_global = _to_device_tree(self._backup_params)

        if self._commit_fn is None:
            outer_tx = self._outer_tx

            def commit_fn(avg, glob, dlt, outer_state, theta):
                # Upcast the (possibly bf16) averaged pseudogradient to the
                # master param dtype before the outer update.
                avg = jax.tree_util.tree_map(
                    lambda a, g: a.astype(g.dtype), avg, glob
                )
                updates, new_outer = outer_tx.update(avg, outer_state, glob)
                new_global = optax.apply_updates(glob, updates)
                # θ += G' − L0 where L0 = B − Δ is the launch point: window
                # k's local-only progress is replaced by the agreed version,
                # window k+1's progress (already in θ) is kept.
                new_theta = jax.tree_util.tree_map(
                    lambda th, g, b, d: th + (g - (b - d.astype(th.dtype))),
                    theta, new_global, glob, dlt,
                )
                return new_theta, new_global, new_outer

            def abort_fn(theta, dlt):
                return jax.tree_util.tree_map(
                    lambda th, d: th + d.astype(th.dtype), theta, dlt
                )

            self._commit_fn = jax.jit(commit_fn)
            self._abort_fn = jax.jit(abort_fn)
        logger.debug(
            "sync reconcile prep %.2fs", time.perf_counter() - t0
        )

        t0 = time.perf_counter()
        if self._manager.should_commit():
            self._state.params, new_global, self._outer_state = self._commit_fn(
                averaged, old_global, delta, self._outer_state,
                self._state.params,
            )
            self._backup_params = _detached_copy(new_global)
            logger.debug(
                "sync commit apply+backup %.2fs", time.perf_counter() - t0
            )
        else:
            # Window k discarded; window k+1's local progress survives.
            self._state.params = self._abort_fn(self._state.params, delta)
            if prev_residual is not None:
                # discard the aborted window's EF update with it
                self._residual = prev_residual
            logger.debug(
                "sync abort rollback %.2fs", time.perf_counter() - t0
            )
