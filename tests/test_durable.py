"""Durable checkpointer v2: async sharded snapshots, WAL-fenced manifest
commits, torn-tail discipline, and no-donor restore across fleet widths."""

import json
import os
import struct
import threading
from datetime import timedelta

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import (
    DistributedSampler,
    DummyCollectives,
    DurableCheckpointer,
    FTTrainState,
    Lighthouse,
    LocalDirStore,
    Manager,
    ManifestLog,
    StatefulDataLoader,
    Store,
)
from torchft_tpu.durable import shard_bounds, store_from_env

# ---------------------------------------------------------------------------
# live-manager rig (single member, real commit boundary)


@pytest.fixture
def rig():
    lighthouse = Lighthouse(
        bind="[::]:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=50, heartbeat_timeout_ms=1000,
    )
    store = Store()

    def make_manager(state):
        return Manager(
            collectives=DummyCollectives(world_size=1),
            load_state_dict=state.load_state_dict,
            state_dict=state.state_dict,
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            timeout=timedelta(seconds=10),
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="durable_test",
        )

    yield make_manager
    store.shutdown()
    lighthouse.shutdown()


def _train(manager, state, ckpt, steps, save=True):
    for _ in range(steps):
        manager.start_quorum()
        grads = {"w": jnp.full((4,), 0.1, jnp.float32)}
        avg = manager.allreduce(grads).wait()
        assert manager.should_commit()
        updates, state.opt_state = state.tx.update(
            avg, state.opt_state, state.params
        )
        state.params = optax.apply_updates(state.params, updates)
        if save:
            ckpt.maybe_save()


def _no_tmp_litter(root):
    for dirpath, _, files in os.walk(root):
        for f in files:
            assert ".tmp" not in f, os.path.join(dirpath, f)


def test_save_restore_roundtrip(rig, tmp_path):
    state = FTTrainState({"w": jnp.ones((4,), jnp.float32)}, optax.sgd(1.0))
    manager = rig(state)
    sampler = DistributedSampler(
        dataset_len=64, replica_group=0, num_replica_groups=1
    )
    loader = StatefulDataLoader(sampler, batch_size=4)
    for _ in range(3):
        next(loader)
    ckpt = DurableCheckpointer(
        str(tmp_path), manager, state, loader=loader, every=2, keep=2
    )
    try:
        _train(manager, state, ckpt, 5)  # snapshots at steps 2 and 4
        assert ckpt.flush(30)
        params_after = np.asarray(state.params["w"])
        assert manager.current_step() == 5
        assert ckpt.committed_steps() == [2, 4]
        _no_tmp_litter(tmp_path)
    finally:
        ckpt.close()
        manager.shutdown()

    # fresh process equivalent: new state/manager/loader restore at step 4
    state2 = FTTrainState(
        {"w": jnp.zeros((4,), jnp.float32)}, optax.sgd(1.0)
    )
    manager2 = rig(state2)
    loader2 = StatefulDataLoader(sampler, batch_size=4)
    ckpt2 = DurableCheckpointer(
        str(tmp_path), manager2, state2, loader=loader2, every=2
    )
    try:
        assert ckpt2.restore_latest() == 4
        assert manager2.current_step() == 4
        # restored params = params at step 4 (one step behind final)
        np.testing.assert_allclose(
            np.asarray(state2.params["w"]), params_after + 0.1, atol=1e-6
        )
        # same replica id -> per-member loader position comes back
        assert loader2.state_dict() == loader.state_dict()
        stats = ckpt2.last_restore_stats
        assert stats is not None and stats["world"] == 1
        assert stats["dropped_tail_bytes"] == 0
    finally:
        ckpt2.close()
        manager2.shutdown()


def test_commit_hook_drives_captures(rig, tmp_path):
    # register_hook=True: no maybe_save call anywhere in the loop — the
    # Manager commit hook fires the capture at the commit boundary.
    state = FTTrainState({"w": jnp.ones((4,), jnp.float32)}, optax.sgd(1.0))
    manager = rig(state)
    ckpt = DurableCheckpointer(
        str(tmp_path), manager, state, every=2, register_hook=True
    )
    try:
        _train(manager, state, ckpt, 4, save=False)
        assert ckpt.flush(30)
        assert ckpt.committed_steps() == [2, 4]
        assert [r["step"] for r in ckpt.snapshots] == [2, 4]
    finally:
        ckpt.close()
        manager.shutdown()


def test_restore_empty_dir_is_none(rig, tmp_path):
    state = FTTrainState({"w": jnp.ones((2,), jnp.float32)}, optax.sgd(1.0))
    manager = rig(state)
    ckpt = DurableCheckpointer(str(tmp_path), manager, state)
    try:
        assert ckpt.restore_latest() is None
    finally:
        ckpt.close()
        manager.shutdown()


def test_no_tmp_litter_and_retention(rig, tmp_path):
    state = FTTrainState({"w": jnp.ones((4,), jnp.float32)}, optax.sgd(1.0))
    manager = rig(state)
    ckpt = DurableCheckpointer(
        str(tmp_path), manager, state, every=1, keep=1
    )
    try:
        _train(manager, state, ckpt, 3)
        assert ckpt.flush(30)
        assert ckpt.committed_steps() == [3]  # keep=1 retired 1 and 2
        snap_dirs = sorted((tmp_path / "snap").iterdir())
        assert len(snap_dirs) == 1, snap_dirs  # retired objects deleted
        _no_tmp_litter(tmp_path)
    finally:
        ckpt.close()
        manager.shutdown()


def test_no_resave_at_same_step_after_abort(rig, tmp_path):
    # current_step only advances on COMMIT: if the loop calls maybe_save
    # again at the same boundary step (after an aborted step), the good
    # snapshot must NOT be re-captured with drifted loader position.
    state = FTTrainState({"w": jnp.ones((4,), jnp.float32)}, optax.sgd(1.0))
    manager = rig(state)
    ckpt = DurableCheckpointer(str(tmp_path), manager, state, every=1)
    try:
        _train(manager, state, ckpt, 1)  # commit step 1, capture
        assert ckpt.flush(30)
        assert len(ckpt.snapshots) == 1
        assert ckpt.maybe_save() is None  # same step again: no re-capture
        assert len(ckpt.snapshots) == 1
    finally:
        ckpt.close()
        manager.shutdown()


def test_restore_arms_same_step_guard(rig, tmp_path):
    # The re-save guard must survive a restore: an aborted first
    # post-restore step at the boundary must not republish the set.
    state = FTTrainState({"w": jnp.ones((4,), jnp.float32)}, optax.sgd(1.0))
    manager = rig(state)
    ckpt = DurableCheckpointer(str(tmp_path), manager, state, every=1)
    try:
        _train(manager, state, ckpt, 1)
        assert ckpt.flush(30)
    finally:
        ckpt.close()
        manager.shutdown()

    state2 = FTTrainState({"w": jnp.zeros((4,), jnp.float32)}, optax.sgd(1.0))
    manager2 = rig(state2)
    ckpt2 = DurableCheckpointer(str(tmp_path), manager2, state2, every=1)
    try:
        assert ckpt2.restore_latest() == 1
        assert ckpt2.maybe_save() is None  # restored step: guard armed
    finally:
        ckpt2.close()
        manager2.shutdown()


# ---------------------------------------------------------------------------
# multi-member fleet rig (fake managers over one shared store)


class _FakeManager:
    def __init__(self, rank, world, replica_id, quorum_id=1):
        self._rank, self._world = rank, world
        self._rid = replica_id
        self._step, self._bc, self._qid = 0, 0, quorum_id

    def current_step(self):
        return self._step

    def quorum_id(self):
        return self._qid

    def participating_rank(self):
        return self._rank

    def num_participants(self):
        return self._world

    def replica_id(self):
        return self._rid

    def state_dict(self):
        return {"step": self._step, "batches_committed": self._bc}

    def load_state_dict(self, sd):
        self._step = sd["step"]
        self._bc = sd["batches_committed"]

    def add_commit_hook(self, hook):
        pass


class _RepState:
    """Replicated user state: numpy params + f32 opt_state (the bf16
    wire's target) — every member holds identical leaves."""

    def __init__(self, seed=0, n=256):
        rng = np.random.RandomState(seed)
        self.sd = {
            "params": {"w": rng.randn(n).astype(np.float32)},
            "opt_state": {"m": rng.randn(n).astype(np.float32)},
        }

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        import jax

        self.sd = jax.tree_util.tree_map(np.asarray, sd)


def _fleet(root, world, store=None, **kw):
    store = store or LocalDirStore(str(root))
    kw.setdefault("commit_timeout_s", 20.0)
    mgrs = [_FakeManager(r, world, f"rep{r}") for r in range(world)]
    states = [_RepState(0) for _ in range(world)]
    cps = [
        DurableCheckpointer(
            str(root), mgrs[r], states[r], store=store, **kw
        )
        for r in range(world)
    ]
    return store, mgrs, states, cps


def _fleet_step(mgrs, cps, step):
    for m in mgrs:
        m._step = step
        m._bc = step * len(mgrs)
    return [c.maybe_save() for c in cps]


def test_shard_bytes_scale_inverse_w(tmp_path):
    # per-member durable bytes ~ total/W: the 1/W headline
    totals = {}
    for world in (1, 2, 4):
        root = tmp_path / f"w{world}"
        _, mgrs, _, cps = _fleet(root, world, every=1)
        _fleet_step(mgrs, cps, 1)
        assert all(c.flush(30) for c in cps)
        rows = [c.snapshots[0] for c in cps]
        assert rows[0]["committed"], rows  # rank 0 runs the committer
        assert cps[0].committed_steps() == [1]
        total = rows[0]["total_bytes"]
        for r in rows:
            assert abs(r["shard_bytes"] - total // world) <= world
        totals[world] = sum(r["shard_bytes"] for r in rows)
        for c in cps:
            c.close()
    # whole-stream bytes written once regardless of W (no W-way
    # redundancy): sums equal across widths
    assert len(set(totals.values())) == 1, totals


def test_restore_across_widths_bit_identical(tmp_path):
    # W_old=3 snapshot; cold fleets of W_new in {1, 2, 4} all rebuild
    # the FULL tree bit-identically — the reshard oracle for the durable
    # tier: re-partitioning at any W_new starts from identical bytes, so
    # shard_bounds(total, W_new) ranges of the rebuilt stream tile into
    # exactly the original stream.
    store, mgrs, states, cps = _fleet(tmp_path, 3, every=1)
    _fleet_step(mgrs, cps, 1)
    assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()
    want = states[0].sd

    for w_new in (1, 2, 4):
        mgr = _FakeManager(0, w_new, f"cold{w_new}")
        st = _RepState(seed=99)  # different until restored
        cp = DurableCheckpointer(str(tmp_path), mgr, st, store=store)
        assert cp.restore_latest() == 1
        assert mgr._step == 1 and mgr._bc == 3
        np.testing.assert_array_equal(
            st.sd["params"]["w"], want["params"]["w"]
        )
        # opt_state rode the bf16 wire: equals the bf16 roundtrip of the
        # original (params stay exact under protect-params)
        import ml_dtypes

        np.testing.assert_array_equal(
            st.sd["opt_state"]["m"],
            want["opt_state"]["m"]
            .astype(np.dtype(ml_dtypes.bfloat16))
            .astype(np.float32),
        )
        cp.close()


def test_raw_wire_restores_opt_state_exact(tmp_path):
    store, mgrs, states, cps = _fleet(tmp_path, 2, every=1, wire=None)
    _fleet_step(mgrs, cps, 1)
    assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()
    st = _RepState(seed=5)
    cp = DurableCheckpointer(
        str(tmp_path), _FakeManager(0, 1, "cold"), st, store=store
    )
    assert cp.restore_latest() == 1
    np.testing.assert_array_equal(
        st.sd["opt_state"]["m"], states[0].sd["opt_state"]["m"]
    )
    cp.close()


class _GatedStore(LocalDirStore):
    """Blocks shard-payload writes until released: pins the writer
    thread mid-snapshot so the trainer can run ahead (overlap) or the
    quorum can move (abort) while the set is in flight."""

    def __init__(self, root):
        super().__init__(root)
        self.gate = threading.Event()

    def put_from(self, name, write_fn):
        if "/shard_" in name and name.endswith(".bin"):
            assert self.gate.wait(30), f"gate never released for {name}"
        return super().put_from(name, write_fn)


def test_snapshot_purity_while_writer_overlaps(tmp_path):
    # The donation/aliasing guard: a snapshot captured at step N must
    # never contain step N+1..N+k tensors even though the writer only
    # runs AFTER those steps mutated the live state in place.
    store = _GatedStore(str(tmp_path))
    _, mgrs, states, cps = _fleet(tmp_path, 2, store=store, every=1)
    want_w = states[0].sd["params"]["w"].copy()
    want_m = states[0].sd["opt_state"]["m"].copy()
    _fleet_step(mgrs, cps, 1)  # capture queued; writer gated
    # steps 2..4 mutate the SAME buffers in place (worst-case aliasing)
    for k in range(3):
        for st in states:
            st.sd["params"]["w"] += 1.0
            st.sd["opt_state"]["m"] *= -1.0
    store.gate.set()
    assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()

    st = _RepState(seed=7)
    cp = DurableCheckpointer(
        str(tmp_path), _FakeManager(0, 1, "cold"), st, store=store
    )
    assert cp.restore_latest() == 1
    np.testing.assert_array_equal(st.sd["params"]["w"], want_w)
    import ml_dtypes

    np.testing.assert_array_equal(
        st.sd["opt_state"]["m"],
        want_m.astype(np.dtype(ml_dtypes.bfloat16)).astype(np.float32),
    )


def test_zero_copy_pins_survive_functional_updates(tmp_path):
    # zero_copy=True captures uncompressed jax leaves as pinned
    # zero-copy views — no owning host copy at the commit boundary. The
    # trainer then REPLACES its arrays functionally (the only update
    # style the knob is sound for) and drops every reference to the
    # step-1 arrays; the pins must keep those buffers alive until the
    # gated writer finally ships them.
    import gc

    import jax.numpy as jnp

    class _JaxState:
        def __init__(self):
            self.sd = {
                "params": {"w": jnp.arange(512, dtype=jnp.float32)},
                "opt_state": {"m": jnp.ones(512, dtype=jnp.float32)},
            }

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            import jax

            self.sd = jax.tree_util.tree_map(np.asarray, sd)

    store = _GatedStore(str(tmp_path))
    mgrs = [_FakeManager(r, 2, f"rep{r}") for r in range(2)]
    states = [_JaxState() for _ in range(2)]
    cps = [
        DurableCheckpointer(
            str(tmp_path), mgrs[r], states[r], store=store, every=1,
            wire=None, zero_copy=True, commit_timeout_s=20.0,
        )
        for r in range(2)
    ]
    want_w = np.asarray(states[0].sd["params"]["w"]).copy()
    want_m = np.asarray(states[0].sd["opt_state"]["m"]).copy()
    _fleet_step(mgrs, cps, 1)  # capture queued; writer gated
    for st in states:  # functional replacement, old arrays unreferenced
        st.sd = {
            "params": {"w": st.sd["params"]["w"] * -3.0},
            "opt_state": {"m": st.sd["opt_state"]["m"] + 9.0},
        }
    gc.collect()
    store.gate.set()
    assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()

    cold = _JaxState()
    cp = DurableCheckpointer(
        str(tmp_path), _FakeManager(0, 1, "cold"), cold, store=store,
        wire=None,
    )
    assert cp.restore_latest() == 1
    np.testing.assert_array_equal(cold.sd["params"]["w"], want_w)
    np.testing.assert_array_equal(cold.sd["opt_state"]["m"], want_m)
    cp.close()


def test_quorum_change_mid_snapshot_aborts(tmp_path):
    # A quorum move invalidates an in-flight set (its W no longer tiles
    # the fleet): the set must abort, never commit, and leave no
    # published marker behind.
    store = _GatedStore(str(tmp_path))
    _, mgrs, states, cps = _fleet(tmp_path, 2, store=store, every=1)
    dirs = _fleet_step(mgrs, cps, 1)  # in flight under quorum_id=1
    assert all(dirs)
    for m in mgrs:
        m._qid = 2  # membership moved
    for m in mgrs:
        m._step = 2
    aborted_dir = dirs[0]
    _ = [c.maybe_save() for c in cps]  # fences old set, captures new
    store.gate.set()
    assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()
    assert cps[0].committed_steps() == [2]
    assert cps[0].snapshots[0]["aborted"], cps[0].snapshots
    assert cps[0].snapshots[1]["committed"]
    # the aborted set published no markers and no manifest record
    assert not store.list(aborted_dir + "/") or all(
        not n.endswith(".json") for n in store.list(aborted_dir + "/")
    )
    records, _ = ManifestLog(store).replay()
    assert all(r.get("dir") != aborted_dir for r in records)


def test_committer_timeout_abandons_partial_set(tmp_path):
    # One member never writes its shard (died mid-step): rank 0's
    # committer must give up at the deadline and the set must stay
    # invisible to restore.
    store = LocalDirStore(str(tmp_path))
    mgrs = [_FakeManager(r, 2, f"rep{r}") for r in range(2)]
    states = [_RepState(0) for _ in range(2)]
    # only rank 0 exists; rank 1's shard never appears
    cp = DurableCheckpointer(
        str(tmp_path), mgrs[0], states[0], store=store, every=1,
        commit_timeout_s=0.3,
    )
    mgrs[0]._step = 1
    assert cp.maybe_save()
    assert cp.flush(30)
    cp.close()
    assert cp.snapshots[0]["aborted"]
    assert not cp.snapshots[0]["committed"]
    assert cp.committed_steps() == []
    st = _RepState(seed=3)
    cp2 = DurableCheckpointer(
        str(tmp_path), _FakeManager(0, 1, "cold"), st, store=store
    )
    assert cp2.restore_latest() is None
    cp2.close()


def test_manifest_truncate_sweep_never_yields_torn_commit(tmp_path):
    # The wal_write crash-mid-append discipline against the manifest:
    # truncate the log at EVERY byte inside the last commit record — the
    # torn record must never win; restore always falls back to the
    # previous committed set.
    store, mgrs, states, cps = _fleet(tmp_path, 2, every=1, keep=10)
    for step in (1, 2):
        _fleet_step(mgrs, cps, step)
        assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()
    mpath = tmp_path / "MANIFEST.log"
    raw = mpath.read_bytes()
    frame = struct.Struct("<II")
    pos, bounds = 0, []
    while pos + frame.size <= len(raw):
        ln, _ = frame.unpack_from(raw, pos)
        bounds.append(pos)
        pos += frame.size + ln
    last = bounds[-1]
    for cut in range(last + 1, len(raw)):
        mpath.write_bytes(raw[:cut])
        st = _RepState(seed=11)
        cp = DurableCheckpointer(
            str(tmp_path), _FakeManager(0, 1, "cold"), st, store=store
        )
        assert cp.restore_latest() == 1, cut
        assert cp.last_restore_stats["dropped_tail_bytes"] == cut - last
        cp.close()
    mpath.write_bytes(raw)


def test_corrupt_shard_falls_back_to_older_set(tmp_path):
    store, mgrs, states, cps = _fleet(tmp_path, 2, every=1, keep=10)
    for step in (1, 2):
        _fleet_step(mgrs, cps, step)
        assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()
    # flip one payload byte of the NEWEST set's shard 1
    newest = cps[0].latest_path()
    path = tmp_path / newest / "shard_0001.bin"
    blob = bytearray(path.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    path.write_bytes(bytes(blob))
    st = _RepState(seed=13)
    cp = DurableCheckpointer(
        str(tmp_path), _FakeManager(0, 1, "cold"), st, store=store
    )
    assert cp.restore_latest() == 1  # CRC catches it; older set wins
    cp.close()


def test_corrupt_meta_falls_back_to_older_set(tmp_path):
    store, mgrs, states, cps = _fleet(tmp_path, 2, every=1, keep=10)
    for step in (1, 2):
        _fleet_step(mgrs, cps, step)
        assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()
    newest = cps[0].latest_path()
    path = tmp_path / newest / "meta.pkl"
    blob = bytearray(path.read_bytes())
    blob[0] ^= 0xFF
    path.write_bytes(bytes(blob))
    st = _RepState(seed=17)
    cp = DurableCheckpointer(
        str(tmp_path), _FakeManager(0, 1, "cold"), st, store=store
    )
    assert cp.restore_latest() == 1
    cp.close()


def test_shard_bounds_tile():
    for total in (0, 1, 7, 100, 1 << 20):
        for world in (1, 2, 3, 7, 16):
            b = shard_bounds(total, world)
            assert b[0] == 0 and b[-1] == total
            assert all(b[i] <= b[i + 1] for i in range(world))
    with pytest.raises(ValueError):
        shard_bounds(10, 0)


def test_localdirstore_api(tmp_path):
    s = LocalDirStore(str(tmp_path))
    s.put("a/b/x.bin", b"hello")
    assert s.exists("a/b/x.bin") and s.get("a/b/x.bin") == b"hello"
    assert s.read_range("a/b/x.bin", 1, 3) == b"ell"
    s.append("log", b"12")
    s.append("log", b"34")
    assert s.get("log") == b"1234"
    s.put("a/c.bin", b"z")
    assert s.list("a/") == ["a/b/x.bin", "a/c.bin"]
    s.delete_prefix("a/b/")
    assert s.list("a/") == ["a/c.bin"]
    assert not os.path.exists(tmp_path / "a" / "b")  # empty dirs pruned
    s.delete("missing")  # no-op
    for bad in ("../evil", "a/../../evil", "", "."):
        with pytest.raises(ValueError):
            s.put(bad, b"x")


def test_manifest_log_compaction(tmp_path):
    s = LocalDirStore(str(tmp_path))
    log = ManifestLog(s)
    for i in range(10):
        log.append({"t": "commit", "step": i, "dir": f"d{i}"})
    records, dropped = log.replay()
    assert len(records) == 10 and dropped == 0
    log.compact(records[-2:])
    records2, dropped2 = log.replay()
    assert [r["step"] for r in records2] == [8, 9] and dropped2 == 0


def test_staging_cap_skips_capture(tmp_path):
    # With the writer pinned and a tiny staging budget, the next capture
    # must be SKIPPED (dropped), never block the trainer.
    store = _GatedStore(str(tmp_path))
    _, mgrs, states, cps = _fleet(
        tmp_path, 1, store=store, every=1, max_staging_mb=0.0001
    )
    _fleet_step(mgrs, cps, 1)  # in flight, gated
    _fleet_step(mgrs, cps, 2)  # exceeds the cap -> skipped
    store.gate.set()
    assert all(c.flush(30) for c in cps)
    for c in cps:
        c.close()
    rows = cps[0].snapshots
    assert [r["step"] for r in rows] == [1, 2]
    assert rows[0]["committed"] and rows[1]["skipped"]
    assert cps[0].committed_steps() == [1]


def test_sync_mode_commits_inline(tmp_path):
    _, mgrs, states, cps = _fleet(tmp_path, 2, every=1, mode="sync")
    for m in mgrs:
        m._step = 1
    # rank 0 last: its inline committer polls for rank 1's marker, which
    # in sync mode only exists once rank 1's save already returned
    assert cps[1].maybe_save()
    assert cps[0].maybe_save()
    # no flush needed: sync mode returns only after the manifest commit
    for c in cps:
        c.close()
    assert cps[0].committed_steps() == [1]
    assert cps[0].snapshots[0]["committed"]


def test_store_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("TORCHFT_DURABLE_STORE", raising=False)
    s = store_from_env(str(tmp_path / "d"))
    assert isinstance(s, LocalDirStore) and s.root == str(tmp_path / "d")
    monkeypatch.setenv("TORCHFT_DURABLE_STORE", f"file:{tmp_path}/e")
    assert store_from_env("x").root == str(tmp_path / "e")
    monkeypatch.setenv("TORCHFT_DURABLE_STORE", "s3://bucket/prefix")
    with pytest.raises(ValueError):
        store_from_env("x")


def test_marker_consistency_rejected(tmp_path):
    # A marker claiming a different (step, quorum_id, total) than the
    # set it sits in must abort the commit (defense against a stale
    # writer racing a re-used directory name).
    store = LocalDirStore(str(tmp_path))
    mgr = _FakeManager(0, 2, "rep0")
    st = _RepState(0)
    cp = DurableCheckpointer(
        str(tmp_path), mgr, st, store=store, every=1, commit_timeout_s=2.0
    )
    mgr._step = 1
    d = None
    # forge rank 1's marker with a mismatched total BEFORE capture so
    # the committer sees both markers immediately
    from torchft_tpu.durable import snapshot_dir

    d = snapshot_dir(1, 1, 2)
    store.put(
        f"{d}/shard_0001.json",
        json.dumps({
            "v": 1, "step": 1, "quorum_id": 1, "rank": 1, "world": 2,
            "begin": 0, "end": 1, "nbytes": 1, "crc": "00000000",
            "wire": "bf16", "total": 999999, "name": f"{d}/shard_0001.bin",
        }).encode(),
    )
    assert cp.maybe_save() == d
    assert cp.flush(30)
    cp.close()
    assert cp.snapshots[0]["aborted"]
    assert cp.committed_steps() == []


def test_ctor_registers_durable_restore(tmp_path):
    # Constructing the checkpointer wires the manager's cold-start
    # fallback (restore-time donor/durable arbitration) — and managers
    # without the hook (this file's _FakeManager) keep working.
    class _Registering(_FakeManager):
        def __init__(self):
            super().__init__(0, 1, "rep0")
            self.registered = None

        def set_durable_restore(self, fn):
            self.registered = fn

    mgr = _Registering()
    cp = DurableCheckpointer(str(tmp_path), mgr, _RepState(0))
    assert mgr.registered == cp.restore_latest
    cp.close()

    plain = _FakeManager(0, 1, "rep1")
    cp2 = DurableCheckpointer(str(tmp_path), plain, _RepState(0))
    cp2.close()
