"""Churn benchmark: throughput under replica-group kills (the north star).

Measures the driver-set target from BASELINE.md: steps/sec with one
replica-group kill every ``--kill-every`` steps must stay >= 90% of
healthy-state steps/sec. The reference makes this claim qualitatively
("avoid stop the world training on errors", reference README.md:46-47) and
exercises the recovery flow in tests (reference torchft/manager.py:470-526);
this benchmark puts a number on it.

Topology: N replica groups as local processes (CPU JAX), one real
HostCollectives TCP ring between them, one lighthouse. Two phases with the
same model/config:

  healthy: all groups train ``--steps`` steps, no faults.
  churn:   a supervisor SIGKILLs one (rotating, never group 0) group each
           time group 0 commits ``--kill-every`` more steps, then restarts
           it; the restarted process heals from a live peer over HTTP.

Reported (CHURN_BENCH.json + one JSON line on stdout):
  steps_per_sec_healthy / steps_per_sec_churn  (group 0's committed steps)
  ratio  = churn / healthy       (north star: >= 0.90)
  heal_p50_s = median time from SIGKILL to the restarted group's first
               committed step (includes process restart + jit recompile —
               on real multi-host deployments each group has its own host,
               so single-host numbers are pessimistic: the restarting
               process competes for this machine's CPUs).

Usage::

    python bench_churn.py --groups 4 --steps 300 --kill-every 100
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


# --------------------------------------------------------------------------
# worker: one replica group
# --------------------------------------------------------------------------


def worker() -> None:
    """Trains the flagship transformer (small config) with the full FT path,
    appending one JSONL record per attempted step."""
    from torchft_tpu.platform import apply_jax_platform_env

    apply_jax_platform_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from datetime import timedelta

    from torchft_tpu import (
        FTTrainState,
        HostCollectives,
        Manager,
        OptimizerWrapper,
    )
    from torchft_tpu.models import TransformerConfig, init_params, loss_fn

    group = int(os.environ["REPLICA_GROUP_ID"])
    num_steps = int(os.environ["NUM_STEPS"])
    log_path = os.environ["BENCH_LOG"]

    cfg = TransformerConfig(
        vocab_size=2048, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=64,
    )
    batch_size, seq_len = 4, 64
    rng = np.random.default_rng(group)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq_len), dtype=np.int32)
    )

    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), optax.adamw(1e-3))
    grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b)))

    # Compile BEFORE joining the quorum, then hold at the start line until
    # every group is ready (parent touches the go file). Without this the
    # first group up forms a solo quorum and races at world-size-1 speed
    # while peers are still importing/compiling, polluting the measured
    # window. Restarted workers find the go file already present and rejoin
    # immediately through the normal heal path.
    jax.block_until_ready(grad_fn(state.params, batch))
    go_path = os.environ["BENCH_GO"]
    open(log_path + ".ready", "w").close()
    while not os.path.exists(go_path):
        time.sleep(0.05)

    collectives = HostCollectives(timeout=timedelta(seconds=30))
    manager = Manager(
        collectives=collectives,
        load_state_dict=state.load_state_dict,
        state_dict=state.state_dict,
        min_replica_size=1,
        heartbeat_interval=timedelta(milliseconds=50),
        replica_id=f"bench_{group}",
    )
    optimizer = OptimizerWrapper(manager, state)

    with open(log_path, "a", buffering=1) as log:
        while manager.current_step() < num_steps:
            t0 = time.perf_counter()
            optimizer.zero_grad()
            t1 = time.perf_counter()
            loss, grads = grad_fn(state.params, batch)
            jax.block_until_ready(grads)
            t2 = time.perf_counter()
            avg = manager.allreduce(grads).wait()
            t3 = time.perf_counter()
            committed = optimizer.step(avg)
            t4 = time.perf_counter()
            log.write(
                json.dumps(
                    {
                        "t": time.time(),
                        "step": manager.current_step(),
                        "committed": bool(committed),
                        "participants": manager.num_participants(),
                        "ms": {
                            "quorum_start": round((t1 - t0) * 1e3, 1),
                            "grad": round((t2 - t1) * 1e3, 1),
                            "allreduce": round((t3 - t2) * 1e3, 1),
                            "commit": round((t4 - t3) * 1e3, 1),
                        },
                    }
                )
                + "\n"
            )
    manager.shutdown()
    collectives.shutdown()


# --------------------------------------------------------------------------
# parent: orchestration + measurement
# --------------------------------------------------------------------------


class _Group:
    def __init__(self, gid: int, log_path: str, env: Dict[str, str]) -> None:
        self.gid = gid
        self.log_path = log_path
        self.env = env
        self.proc: Optional[subprocess.Popen] = None

    def spawn(self) -> None:
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env={**os.environ, **self.env},
            cwd=REPO,
        )

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def _read_log(path: str) -> List[dict]:
    records = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn write
    except FileNotFoundError:
        pass
    return records


def _committed(records: List[dict]) -> List[dict]:
    return [r for r in records if r["committed"]]


def _steps_per_sec(records: List[dict], skip: int = 5) -> float:
    """Committed steps/sec, excluding the first ``skip`` commits (compile +
    ramp)."""
    done = _committed(records)[skip:]
    if len(done) < 2:
        return 0.0
    return (len(done) - 1) / (done[-1]["t"] - done[0]["t"])


def _run_phase(
    name: str,
    groups: int,
    steps: int,
    kill_every: int,
    out_dir: str,
    lighthouse_addr: str,
) -> dict:
    go_path = os.path.join(out_dir, f"{name}.go")
    gs: List[_Group] = []
    for g in range(groups):
        log_path = os.path.join(out_dir, f"{name}_g{g}.jsonl")
        gs.append(
            _Group(
                g,
                log_path,
                {
                    "JAX_PLATFORMS": "cpu",
                    "TORCHFT_LIGHTHOUSE": lighthouse_addr,
                    "REPLICA_GROUP_ID": str(g),
                    "NUM_REPLICA_GROUPS": str(groups),
                    "NUM_STEPS": str(steps),
                    "BENCH_LOG": log_path,
                    "BENCH_GO": go_path,
                },
            )
        )
    for g in gs:
        g.spawn()

    # Start line: release every group at once, after all have compiled.
    ready_deadline = time.time() + 300
    while time.time() < ready_deadline:
        if all(os.path.exists(g.log_path + ".ready") for g in gs):
            break
        time.sleep(0.25)
    open(go_path, "w").close()

    kills: List[dict] = []
    next_kill = kill_every if kill_every > 0 else None
    victim = 1  # rotate over groups 1..N-1; group 0 is the measurement group
    deadline = time.time() + 1200
    try:
        while any(g.alive() for g in gs) and time.time() < deadline:
            time.sleep(0.25)
            # Restart any dead group (supervisor role, launcher semantics).
            for g in gs:
                if g.proc is not None and g.proc.poll() not in (None, 0):
                    g.spawn()
            if next_kill is not None:
                lead = len(_committed(_read_log(gs[0].log_path)))
                if lead >= next_kill and lead < steps - 5:
                    v = gs[victim]
                    if v.alive():
                        v.proc.send_signal(signal.SIGKILL)
                        kills.append(
                            {"t": time.time(), "gid": v.gid, "at_step": lead}
                        )
                        victim = victim % (groups - 1) + 1
                    next_kill += kill_every
    finally:
        for g in gs:
            if g.alive():
                g.proc.terminate()
        for g in gs:
            if g.proc is not None:
                try:
                    g.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    g.proc.kill()

    # Heal latency: kill -> first commit recorded by the restarted process.
    heal_s = []
    for k in kills:
        log = _read_log(gs[k["gid"]].log_path)
        after = [r["t"] for r in _committed(log) if r["t"] > k["t"]]
        if after:
            heal_s.append(after[0] - k["t"])
    heal_s.sort()

    return {
        "steps_per_sec": round(_steps_per_sec(_read_log(gs[0].log_path)), 3),
        "kills": len(kills),
        "heal_s": [round(h, 2) for h in heal_s],
        "heal_p50_s": round(heal_s[len(heal_s) // 2], 2) if heal_s else None,
        "committed_steps_g0": len(_committed(_read_log(gs[0].log_path))),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--groups", type=int, default=4)
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--kill-every", type=int, default=100)
    parser.add_argument("--out", default=os.path.join(REPO, "CHURN_BENCH.json"))
    args = parser.parse_args()

    if args.worker:
        worker()
        return

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from torchft_tpu import Lighthouse

    out_dir = os.path.join(REPO, ".bench_churn_logs")
    os.makedirs(out_dir, exist_ok=True)
    for f in os.listdir(out_dir):
        os.unlink(os.path.join(out_dir, f))

    # Fast failure detection so a kill costs survivors ~join_timeout, not
    # the CLI-default 60 s (reference defaults: src/lighthouse.rs:77-102).
    lighthouse = Lighthouse(
        bind="[::]:0",
        min_replicas=1,
        join_timeout_ms=200,
        quorum_tick_ms=50,
        heartbeat_timeout_ms=500,
    )

    healthy = _run_phase(
        "healthy", args.groups, args.steps, 0, out_dir, lighthouse.address()
    )
    churn = _run_phase(
        "churn", args.groups, args.steps, args.kill_every, out_dir,
        lighthouse.address(),
    )
    lighthouse.shutdown()

    ratio = (
        round(churn["steps_per_sec"] / healthy["steps_per_sec"], 3)
        if healthy["steps_per_sec"]
        else 0.0
    )
    result = {
        "config": {
            "groups": args.groups,
            "steps": args.steps,
            "kill_every": args.kill_every,
            "host_cpus": os.cpu_count(),
        },
        "healthy": healthy,
        "churn": churn,
        "ratio": ratio,
        "target": 0.90,
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(
        json.dumps(
            {
                "metric": "steps_per_sec_churn_ratio",
                "value": ratio,
                "unit": "ratio",
                "vs_baseline": round(ratio / 0.90, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
