"""Benchmark: fault-tolerant training throughput on the flagship model.

Measures the FULL fault-tolerance path against a raw jitted train loop on
the same model and hardware — with a REAL cross-replica-group data plane: a
second replica group (peer process on host CPU) joins the quorum and the
host TCP ring, so every cross-group byte is actually packed, shipped, and
unpacked (no world-size-1 identity shortcut).

Configurations measured (details in BENCH_DETAIL.json):

  raw           jitted loss/grad/apply loop, no FT machinery.
  ft_diloco     AsyncDiLoCo on the smoke model — the bandwidth-appropriate
                cross-group mode for DCN-class links: inner steps stay
                on-chip and the compressed pseudogradient sync runs once
                per window (bf16 ring allreduce on healthy links;
                int8+error-feedback allgather on degraded ones). Window
                sized from the measured link; full FT machinery (quorum +
                commit vote) every window; best of 2 timed windows. Lands
                the PROVISIONAL headline early so later phases can't lose
                the round's metric.
  ft_ddp_small  per-step DDP at a LINK-SIZED scale — runs on TPU every
                round unconditionally: a ~0.72M-param S-2048 flash LM
                whose int8/bf16 gradient ship fits the measured link, with
                PipelinedDDP hiding the ring behind the next step's
                compute. The per-step product's number on this hardware.
  ft_ddp        flagship-scale per-step gradient allreduce (the reference
                train_ddp mode) against a same-batch raw baseline;
                blocking and PipelinedDDP both recorded. On a degraded
                device<->host link it is skipped (per-step shipping of the
                93 MB gradient is link-bound regardless of framework)
                unless BENCH_FORCE_DDP=1. On CPU, BOTH the reference-like
                small batch and the 4x-token batch land in the artifact
                (the ratio is an arithmetic-intensity story).
  big           the MXU-saturating model (111M params, d_model 1024, 8
                layers, seq 2048, bf16 compute + f32 master): raw vs
                AsyncDiLoCo with the window sized so the sync hides behind
                compute. Its FT/raw ratio is THE HEADLINE (printed last;
                the driver takes the last metric line) — FT cost at
                deployment-class arithmetic intensity, with MFU accounting
                against the v5e peak. Sub-results persist incrementally;
                BENCH_SKIP_BIG=1 skips.

The reference publishes no absolute numbers (BASELINE.md); the driver-set
north star is >= 90% of healthy-state throughput. The printed line reports
``vs_baseline = (ft_steps_per_sec / raw_steps_per_sec) / 0.90`` — 1.0
means exactly the 90% bar, > 1.0 beats it; the FINAL line (the one the
driver records) is the big phase's ratio when that phase completes, else
the provisional small-model ft_diloco ratio. Throughput *under churn* is
measured separately by bench_churn.py (CHURN_BENCH.json).

Prints ONE JSON line, e.g.:
{"metric": "steps_per_sec_ft", "value": 42.1, "unit": "steps/s", "vs_baseline": 1.01}
"""

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import timedelta

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SYNC_EVERY = 128  # AsyncDiLoCo window (inner steps per cross-group sync)
_T0 = time.monotonic()  # process start, for supervisor-budget guards


def _env_wire():
    """BENCH_WIRE as a compress dtype; the special value "ddp" is a
    force-DDP trigger, not a wire dtype, and must not leak into the
    diloco phases' compress selection."""
    w = os.environ.get("BENCH_WIRE")
    return None if w == "ddp" else w


def _model_setup(size: str = None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchft_tpu.models import TransformerConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    size = size or os.environ.get("BENCH_MODEL", "small")
    # The ring peer must build the SAME param tree as the main process
    # even though it runs on CPU: main exports its layer count, else the
    # `6 if on_tpu else 2` split below hands the TPU main a 6-layer tree
    # and the CPU peer a 2-layer one — a size-mismatched ring op that
    # (before the ring grew its header check) deadlocked silently with
    # the peer's recv queue full.
    forced_layers = os.environ.get("BENCH_FORCE_LAYERS")
    if size == "ddp_small":
        # Link-sized per-step DDP config (round-3 verdict #2): ~0.72M
        # params -> 0.73 MB int8 / 1.45 MB bf16 wire, but LOTS of compute
        # per param (S 2048 attention through the flash kernel), so the
        # per-step gradient ship can hide behind the next step's compute
        # (PipelinedDDP) even on a weak device<->host link. head_dim 64
        # keeps the kernel on its fast path. Batch is chosen per-link in
        # _bench_ddp_small.
        cfg = TransformerConfig(
            vocab_size=512,
            d_model=128,
            n_heads=2,
            n_layers=2,
            d_ff=512,
            max_seq_len=2048,
            use_flash=on_tpu,
        )
        batch_size = int(os.environ.get("BENCH_DDP_SMALL_BATCH", 64))
        seq_len = 2048
    elif size == "big":
        # MXU-saturating: d_model >= 1024 matmuls, seq 2048, bf16-sized
        # payloads. ~110M params at batch 16 x 2048 -> ~21.9 TFLOP/step.
        # Batch choice is MEASURED on v5e (fused train step, flash
        # (512,512) tiles): B16 70.0 param-TFLOP/s > B8 64.6 > B4 58.0;
        # XLA dense peaks at 47.5 (B8) and fails to compile at B16, so
        # the bench's dense-vs-flash selection (in _bench_big) lands on
        # the pallas kernel at this shape.
        cfg = TransformerConfig(
            vocab_size=8192,
            d_model=1024,
            n_heads=16,
            n_layers=8,
            d_ff=4096,
            max_seq_len=2048,
        )
        batch_size, seq_len = 16, 2048
    else:
        cfg = TransformerConfig(
            vocab_size=8192,
            d_model=512,
            n_heads=8,
            n_layers=int(forced_layers) if forced_layers
            else (6 if on_tpu else 2),
            d_ff=2048,
            max_seq_len=512,
        )
        batch_size = 16 if on_tpu else 4
        seq_len = 512 if on_tpu else 128
    rng = np.random.default_rng(0)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq_len), dtype=np.int32)
    )
    return cfg, batch, on_tpu


def _mark(msg: str) -> None:
    """Timestamped phase marker on stderr: which phase a wedged/slow run
    died in is the first thing a post-mortem needs."""
    print(
        f"[bench {time.strftime('%H:%M:%S')}] {msg}",
        file=sys.stderr,
        flush=True,
    )


def _barrier(tree) -> None:
    # Readback barrier: on the tunneled TPU, block_until_ready returns
    # before remote execution drains, so force a tiny device read.
    import jax
    import numpy as np

    jax.block_until_ready(tree)
    leaf = jax.tree_util.tree_leaves(tree)[0]
    np.asarray(leaf.ravel()[0:1])


def _time_raw_loop(step_fn, init_fn, tx, batch, warm: int, n: int) -> float:
    """The one warm+timed raw-loop discipline every phase shares (fresh
    state per call; _barrier drains before both clock edges; step_fn is
    the FUSED one-program train step, models.make_train_step — measured
    ~8% faster than split grad/apply programs on v5e, so it is the honest
    raw baseline). Keeping a single copy means a change to the
    timing/drain semantics cannot make phases silently measure
    differently."""
    params = init_fn()
    opt_state = tx.init(params)
    for _ in range(warm):
        params, opt_state, loss = step_fn(params, opt_state, batch)
    _barrier(params)
    t0 = time.perf_counter()
    for _ in range(n):
        params, opt_state, loss = step_fn(params, opt_state, batch)
    _barrier(params)
    return n / (time.perf_counter() - t0)


def peer() -> None:
    """CPU ring peer: a second replica group that paces the quorum and the
    ring (contributing zeros) so the main process's data plane is real."""
    from torchft_tpu.platform import apply_jax_platform_env

    apply_jax_platform_env()

    import jax
    import jax.numpy as jnp

    from torchft_tpu import HostCollectives, Manager
    from torchft_tpu.models import init_params

    cfg, _, _ = _model_setup()
    params = init_params(cfg, jax.random.PRNGKey(0))
    peer_dtype = os.environ.get("BENCH_PEER_DTYPE")
    if peer_dtype == "int8":
        # int8 windows travel as a managed (device-packed) ALLGATHER of
        # {q: int8 leaves, scale: f32 scalars} (AsyncDiLoCo/PipelinedDDP
        # compress="int8"); the peer's zero contribution is all-zero q
        # with zero scales.
        zeros = {
            "q": jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, jnp.int8), params
            ),
            "scale": jax.tree_util.tree_map(
                lambda l: jnp.zeros((), jnp.float32), params
            ),
        }
    elif peer_dtype == "q8":
        # quantized RING wire: param-shaped f32 zero tree; the ring
        # quantizes per chunk — same op header on both members.
        zeros = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, jnp.float32), params
        )
    else:
        wire_dtype = jnp.bfloat16 if peer_dtype == "bf16" else None
        zeros = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, wire_dtype or l.dtype), params
        )

    state = {"params": params}
    collectives = HostCollectives(timeout=timedelta(seconds=1800))
    manager = Manager(
        collectives=collectives,
        load_state_dict=state.update,
        state_dict=lambda: dict(state),
        min_replica_size=1,
        timeout=timedelta(seconds=1800),  # rides out main-side jit compiles
        quorum_timeout=timedelta(seconds=1800),
        rank=0,
        world_size=1,
        lighthouse_addr=os.environ["TORCHFT_LIGHTHOUSE"],
        replica_id="bench_peer",
    )
    # Signal readiness: heartbeats are flowing, so the main side's quorum
    # holds the door (join timeout) until our first quorum request lands.
    open(os.environ["BENCH_PEER_READY"], "w").close()
    # Hold until the main side joins: committing a solo quorum here would
    # advance our step and make the zero-contributing peer the recovery
    # primary for the main process. A quorum containing both sides can only
    # have formed from simultaneous requests, so the barrier's final quorum
    # IS the main side's round-0 quorum — reuse it (starting another here
    # would leave this peer one quorum ahead and deadlock the ring).
    # allow_heal=False throughout: the synthetic peer must never trigger
    # recovery transfers (a step-0 init sync would push the full state dict
    # through the device tunnel mid-compile on the main side).
    manager.start_quorum(allow_heal=False)
    manager.wait_quorum()
    while manager.num_participants() < 2:
        time.sleep(0.1)
        manager.start_quorum(allow_heal=False)
        manager.wait_quorum()
    print(f"peer: joined ring, participants={manager.num_participants()}",
          flush=True)
    # The peer never votes/commits: its step stays 0, so it can never
    # out-step a (transiently failing) main side and become its recovery
    # source, and it drops out of the max-step cohort after round 0 — the
    # main side's gradient divisor reflects real contributors only.
    rounds = int(os.environ["BENCH_PEER_ROUNDS"])
    for i in range(rounds):
        if i > 0:
            manager.start_quorum(allow_heal=False)
        if peer_dtype == "int8":
            manager.allgather(zeros).wait()  # paced by the main side
        elif peer_dtype == "q8":
            manager.allreduce(zeros, wire="q8").wait()  # paced by main
        else:
            manager.allreduce(zeros).wait()  # paced by the main side
        print(f"peer: round {i} done participants="
              f"{manager.num_participants()}", flush=True)
    manager.shutdown()
    collectives.shutdown()


def _spawn_peer(lighthouse_addr: str, rounds: int, dtype: str) -> subprocess.Popen:
    ready = os.path.join(REPO, f".bench_peer_ready_{os.getpid()}_{dtype}")
    if os.path.exists(ready):
        os.unlink(ready)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TORCHFT_LIGHTHOUSE": lighthouse_addr,
        "BENCH_PEER_ROUNDS": str(rounds),
        "BENCH_PEER_DTYPE": dtype,
        "BENCH_PEER_READY": ready,
        "TORCHFT_TPU_LOG": "info",
    }
    # CPU peers skip the sitecustomize TPU-backend preload (interpreter-
    # start PJRT init against the tunnel — seconds of dead weight).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    log = open(os.path.join(REPO, f".bench_peer_{dtype}.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--peer"],
        env=env,
        cwd=REPO,
        stdout=log,
        stderr=subprocess.STDOUT,
    )
    deadline = time.time() + 300
    while not os.path.exists(ready) and time.time() < deadline:
        time.sleep(0.2)
    os.unlink(ready)
    return proc


def _bench_big(save=lambda partial: None) -> dict:
    """Raw vs AsyncDiLoCo throughput on the MXU-saturating config, with the
    window sized so the (bf16, pipelined) sync can hide behind compute —
    the deployment-tuning rule DiLoCo practice prescribes (H in the
    hundreds). ``save`` receives partial result dicts as sub-phases land,
    so a supervisor kill mid-phase keeps everything measured so far
    (round-3 verdict #3: the driver's artifact lost the whole phase)."""
    import jax
    import numpy as np
    import optax
    from datetime import timedelta as td

    from torchft_tpu import AsyncDiLoCo, FTTrainState, HostCollectives, Manager
    from torchft_tpu.models import init_params

    import dataclasses

    cfg, batch, _ = _model_setup("big")
    tx = optax.adamw(1e-3)
    BF16_PARAMS = True  # f32 master + bf16 compute copy (measured +2.3%)

    # Attention-path selection is MEASURED per run, not assumed: time a
    # short raw loop with XLA dense attention and with the pallas flash
    # kernel (v5e-tuned tiles, ops/flash_attention.py), run the FT phase
    # on the winner, and record both timings (the round-2 verdict's ask).
    n_params = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(
            init_params(cfg, jax.random.PRNGKey(0))
        )
    )

    _fns_cache: dict = {}

    def step_fn_for(c):
        # Memoized per config: a fresh jit wrapper would retrace+recompile
        # the big model (minutes on the tunneled runtime) on every timing
        # helper call, burning the phase's time budget.
        if c not in _fns_cache:
            from torchft_tpu.models import make_train_step

            _fns_cache[c] = make_train_step(c, tx, bf16_params=BF16_PARAMS)
        return _fns_cache[c]

    def time_raw_variant(c, warm: int, raw_steps: int = 24):
        # 24 steps (not 8): the end-of-window drain costs a tunnel RTT;
        # a too-short window charges it against raw but not against the
        # long FT windows (same rationale as the headline raw window).
        """steps/s, or None when the variant fails (e.g. XLA dense at
        batch sizes whose S^2 score tensors break the compiler — observed
        at B16 on v5e; the selection then simply takes the survivor)."""
        try:
            return _time_raw_loop(
                step_fn_for(c),
                lambda: init_params(c, jax.random.PRNGKey(0)), tx, batch,
                warm, raw_steps,
            )
        except Exception as e:  # noqa: BLE001 - selection is best-effort
            _mark(f"big: variant failed: {type(e).__name__}: {str(e)[:120]}")
            return None

    _mark("big: attention-path selection (dense vs flash)")
    dense_cfg = dataclasses.replace(cfg, use_flash=False)
    flash_cfg = dataclasses.replace(cfg, use_flash=True)
    dense_sps = time_raw_variant(dense_cfg, 2)
    flash_sps = time_raw_variant(flash_cfg, 2)
    if dense_sps is None and flash_sps is None:
        raise RuntimeError("both attention variants failed to run")
    cfg = flash_cfg if (flash_sps or 0) >= (dense_sps or 0) else dense_cfg
    _mark(
        f"big: dense {dense_sps} vs flash {flash_sps} steps/s -> "
        f"{'flash' if cfg.use_flash else 'dense'}"
    )
    save({
        "params_M": round(n_params / 1e6, 1),
        "bf16_params": BF16_PARAMS,
        "attention": "flash" if cfg.use_flash else "dense",
        "attention_raw_steps_per_sec": {
            "dense": None if dense_sps is None else round(dense_sps, 3),
            "flash": None if flash_sps is None else round(flash_sps, 3),
        },
    })
    train_step = step_fn_for(cfg)

    def time_raw_big(warm: int) -> float:
        sps = time_raw_variant(cfg, warm)
        assert sps is not None, "selected variant stopped running"
        return sps

    raw_sps = max(s for s in (dense_sps, flash_sps) if s is not None)
    step_s = 1.0 / raw_sps

    # Window sizing: sync ships n_params bf16 bytes each way; size H so
    # the sync is a small fraction of window compute (capped to keep the
    # bench bounded — the cap is reported so a capped ratio is read as a
    # link artifact, not a framework cost).
    d2h_MBps = _measure_d2h_MBps()
    sync_s_est = 2 * (n_params * 2 / 1e6) / max(d2h_MBps, 0.1)
    sync_every = int(min(max(12 * sync_s_est / step_s, 64), 1536))
    windows = 2  # best-of, matching the headline phase
    # Supervisor-budget clamp (same rationale as the headline phase): at
    # batch 16 a 1536-step window can exceed the remaining attempt budget
    # outright; a clamped window is a worse sync amortization but a
    # RECORDED one.
    sync_every = min(
        sync_every, _budget_window_steps(windows, raw_sps, margin=240)
    )  # (the budget helper floors at 128 steps)

    os.environ["BENCH_MODEL"] = "big"
    lighthouse = peer_proc = manager = collectives = None
    try:
        lighthouse = _fresh_lighthouse()  # own instance: no ghost members
        wire = _env_wire() or ("bf16" if d2h_MBps >= 100 else "int8")
        peer_proc = _spawn_peer(lighthouse.address(), windows + 1, wire)
        state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
        collectives = HostCollectives(timeout=td(seconds=600))
        manager = Manager(
            collectives=collectives,
            load_state_dict=None,
            state_dict=None,
            min_replica_size=1,
            use_async_quorum=False,
            timeout=td(seconds=600),
            quorum_timeout=td(seconds=600),
            rank=0,
            world_size=1,
            lighthouse_addr=lighthouse.address(),
            replica_id="bench_big",
        )
        diloco = AsyncDiLoCo(
            manager, state, optax.sgd(0.7, momentum=0.9, nesterov=True),
            sync_every, compress=wire,
            overlap=d2h_MBps >= 100,  # serial sync on degraded links
        )
        manager._load_state_dict = diloco.load_state_dict
        manager._user_state_dict = diloco.state_dict

        # Short warmup: compile the inner step, then force ONE early
        # boundary sync (the peer's first of windows+1 rounds) instead of
        # crawling a full window to the boundary (see main()'s note).
        # Must stay BELOW sync_every (floor-clamped to 64): hitting the
        # auto-sync in the warm loop would spend a peer round and
        # desynchronize the 2-round accounting.
        for i in range(min(65, sync_every - 1)):
            state.params, state.opt_state, loss = train_step(
                state.params, state.opt_state, batch
            )
            diloco.step_applied()
            if i % 64 == 63:
                np.asarray(loss)  # real drain (see _barrier note)
        diloco.sync()
        diloco.flush()
        _barrier(state.params)
        # Best-of-N windows, same noise treatment as the headline phase:
        # a single tunnel stall must not masquerade as framework cost.
        window_sps = []
        skipped = False
        for w in range(windows):
            if w > 0 and time.monotonic() - _T0 > 800:
                skipped = True
                # The supervisor kills the run at BENCH_ATTEMPT_TIMEOUT_S
                # (default 1200); a second window on a badly degraded
                # tunnel could push past it and lose this whole section.
                _mark(f"big: skipping window {w} (time budget)")
                break
            _mark(f"big: timed window {w} (sync_every={sync_every})")
            t0 = time.perf_counter()
            for i in range(sync_every):
                state.params, state.opt_state, loss = train_step(
                    state.params, state.opt_state, batch
                )
                diloco.step_applied()
                if i % 512 == 511:
                    np.asarray(loss)  # real drain (see _barrier note)
            diloco.flush()
            _barrier(state.params)
            window_sps.append(sync_every / (time.perf_counter() - t0))
            _mark(f"big: window {w} done ({window_sps[-1]:.2f} steps/s)")
            save({
                "window_steps_per_sec": [round(s, 3) for s in window_sps],
                "sync_every": sync_every,
                "raw_steps_per_sec": round(raw_sps, 3),
            })
        ft_sps = max(window_sps)
        raw_remeasured = False
        if time.monotonic() - _T0 < 900:
            # symmetric noise treatment (same rule as the headline phase)
            _mark("big: raw re-measure")
            raw_sps = max(raw_sps, time_raw_big(1))
            raw_remeasured = True
        assert collectives.size() == 2, "big-bench peer did not join the ring"
        if not skipped:
            peer_proc.wait(timeout=600)
        # else: the peer still expects the skipped window's sync round;
        # the finally below kills it rather than deadlocking here
    finally:
        # main() swallows exceptions from this phase; never leak the peer
        # process, the op thread, the manager server, or the env override.
        os.environ.pop("BENCH_MODEL", None)
        if peer_proc is not None and peer_proc.poll() is None:
            peer_proc.kill()
        if manager is not None:
            manager.shutdown()
        if collectives is not None:
            collectives.shutdown()
        if lighthouse is not None:
            lighthouse.shutdown()
    # Symmetric comparison discipline: FT is best-of-N windows, so the raw
    # denominator must be best-of-N too. When the time budget skipped the
    # raw re-measure, compare FIRST window vs the single raw sample
    # (best-of-1 vs best-of-1) instead of biasing the ratio FT-ward.
    ft_for_ratio = ft_sps if raw_remeasured else window_sps[0]
    # MFU accounting (round-3 verdict 1d): param-FLOPs (6 N tokens) AND
    # total FLOPs including causal attention (fwd 4*B*S^2*d/2 per layer,
    # backward ~2.5x fwd -> x3.5), against the v5e bf16 paper peak.
    S_in = batch.shape[1] - 1  # LM slices the last token off
    attn_tflop = (
        cfg.n_layers * 3.5 * 4 * batch.shape[0] * S_in * S_in
        * cfg.d_model / 2 / 1e12
    )
    param_tflop = 6 * n_params * batch.size / 1e12
    result = {
        "params_M": round(n_params / 1e6, 1),
        "bf16_params": BF16_PARAMS,
        "tflop_per_step": round(param_tflop, 2),
        "attention": "flash" if cfg.use_flash else "dense",
        "attention_raw_steps_per_sec": {
            "dense": None if dense_sps is None else round(dense_sps, 3),
            "flash": None if flash_sps is None else round(flash_sps, 3),
        },
        "raw_steps_per_sec": round(raw_sps, 3),
        "raw_tflops": round(param_tflop * raw_sps, 1),
        "ft_diloco_steps_per_sec": round(ft_sps, 3),
        "window_steps_per_sec": [round(s, 3) for s in window_sps],
        "ratio_vs_raw": round(ft_for_ratio / raw_sps, 3),
        # "symmetric" = raw re-measured AND both FT windows ran; a
        # budget-skipped second window is best-of-1 FT vs best-of-2 raw
        # (conservative, but not symmetric — round-3 advisor finding)
        "ratio_symmetric": raw_remeasured and not skipped,
        "windows_measured": len(window_sps),
        "mfu": {
            "attn_tflop_per_step": round(attn_tflop, 2),
            "total_tflop_per_step": round(param_tflop + attn_tflop, 2),
            "raw_total_tflops": round(
                (param_tflop + attn_tflop) * raw_sps, 1
            ),
            "pct_of_v5e_bf16_peak": round(
                (param_tflop + attn_tflop) * raw_sps / 197.0 * 100, 1
            ),
            "note": "total = param matmuls + causal attention (x3.5 "
            "fwd+bwd); peak = 197 TFLOP/s v5e bf16; see ROOFLINE.md for "
            "the measured per-component ceilings on this tunneled chip",
        },
        "sync_every": sync_every,
        "window_capped": bool(sync_every >= 1536),
        "note": "MXU-saturating config; attention path chosen by "
        "measurement this run (both timings recorded); window sized so "
        "the sync stays a small fraction of compute, capped at 1536 to "
        "bound bench time"
        + (
            ""
            if raw_remeasured
            else "; raw re-measure skipped (time budget) so the ratio "
            "compares first-window FT vs the single raw sample"
        ),
    }
    save(result)
    return result


def _bench_ddp_small(d2h_MBps: float, h2d_MBps: float) -> dict:
    """Per-step fault-tolerant DDP at a LINK-SIZED scale, run on TPU every
    round unconditionally (round-3 verdict #2: the reference's product is
    per-step FT, and the flagship ft_ddp phase is link-bound on degraded
    tunnels — this phase sizes the MODEL to the link instead of skipping).

    ~0.72M params (0.73 MB int8 wire) with S-2048 flash attention: compute
    per step is large relative to the gradient ship, and PipelinedDDP
    overlaps step i's ring with step i+1's grads, so the achievable ratio
    is C/max(C, R) rather than C/(C+R). The batch is chosen so estimated
    compute ~= 1.2x the estimated ring time on the MEASURED link (bigger
    batches on worse links), capped at 512.
    """
    import jax
    import numpy as np
    import optax

    from torchft_tpu import (
        FTTrainState, HostCollectives, Manager, PipelinedDDP,
    )
    from torchft_tpu.models import init_params, loss_fn, make_train_step

    degraded = d2h_MBps < 100
    wire = "int8" if degraded else "bf16"
    os.environ["BENCH_MODEL"] = "ddp_small"
    try:
        cfg, batch, _ = _model_setup("ddp_small")
        tx = optax.adamw(1e-3)
        n_params = sum(
            int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(
                init_params(cfg, jax.random.PRNGKey(0))
            )
        )
        wire_mb = n_params * (1 if wire == "int8" else 2) / 1e6
        # ring time estimate: payload d2h + cohort payloads h2d + slack
        r_est = wire_mb / max(d2h_MBps, 0.1) + \
            2 * wire_mb / max(h2d_MBps, 0.1) + 0.15
        train_step = make_train_step(cfg, tx)
        _mark(f"ddp_small: raw probe (wire={wire}, est ring {r_est:.2f}s)")
        base_B = batch.shape[0]
        raw_sps = _time_raw_loop(
            train_step,
            lambda: init_params(cfg, jax.random.PRNGKey(0)), tx, batch,
            2, 12,
        )
        c_base = 1.0 / raw_sps
        # scale batch so compute ~= 1.2x ring estimate (compute ~linear
        # in B; pipelined ratio ~ C/max(C, R), so C >= ~1.1R is the 0.9
        # bar). Cap 512: ~1M tokens/step of the 0.72M-param model still
        # fits HBM comfortably.
        want_B = int(base_B * max(1.2 * r_est / c_base, 1.0))
        B = min(max(32, (want_B // 32) * 32), 512)
        if B != base_B:
            os.environ["BENCH_DDP_SMALL_BATCH"] = str(B)
            cfg, batch, _ = _model_setup("ddp_small")
            raw_sps = _time_raw_loop(
                train_step,
                lambda: init_params(cfg, jax.random.PRNGKey(0)), tx, batch,
                1, 8,
            )
        _mark(f"ddp_small: B={B} raw {raw_sps:.2f} steps/s")

        ddp_grad_fn = jax.jit(
            jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b))
        )
        steps = 4
        lh = peer_proc = manager = collectives = None
        try:
            lh = _fresh_lighthouse()
            peer_proc = _spawn_peer(lh.address(), 1 + steps, wire)
            state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
            collectives = HostCollectives(timeout=timedelta(seconds=1800))
            manager = Manager(
                collectives=collectives,
                load_state_dict=state.load_state_dict,
                state_dict=state.state_dict,
                min_replica_size=1,
                timeout=timedelta(seconds=600),
                quorum_timeout=timedelta(seconds=600),
                rank=0,
                world_size=1,
                lighthouse_addr=lh.address(),
                replica_id="bench_main_ddp_small",  # sorts before bench_peer
            )
            ddp = PipelinedDDP(
                manager, state, lambda p, b: ddp_grad_fn(p, b),
                compress=wire,
            )
            ddp.step(batch)  # warm: compile + peer round 0
            _barrier(state.params)
            t0 = time.perf_counter()
            for _ in range(steps):
                ddp.step(batch)
            t_end = time.perf_counter()
            ddp.flush()
            _barrier(state.params)
            ft_sps = steps / (t_end - t0)
            assert collectives.size() == 2, "peer did not join the ring"
            peer_proc.wait(timeout=600)
        finally:
            if peer_proc is not None and peer_proc.poll() is None:
                peer_proc.kill()
            if manager is not None:
                manager.shutdown()
            if collectives is not None:
                collectives.shutdown()
            if lh is not None:
                lh.shutdown()
        return {
            "steps_per_sec": round(ft_sps, 3),
            "raw_steps_per_sec": round(raw_sps, 3),
            "ratio_vs_raw": round(ft_sps / raw_sps, 3),
            "params_M": round(n_params / 1e6, 2),
            "wire": wire,
            "wire_MB": round(wire_mb, 2),
            "batch": B,
            "tokens_per_step": int(batch.size),
            "est_ring_s": round(r_est, 3),
            "note": "link-sized per-step DDP (PipelinedDDP, full quorum + "
            "commit vote every step) over a live 2-member ring; model "
            "sized so the gradient ship fits the measured link and the "
            "ring hides behind the next step's compute; raw baseline is "
            "the fused one-program step at the same batch",
        }
    finally:
        os.environ.pop("BENCH_MODEL", None)
        os.environ.pop("BENCH_DDP_SMALL_BATCH", None)


def _budget_window_steps(windows: int, steps_per_sec: float, margin: float) -> int:
    """Largest per-window step count (multiple of 128, floor 128) such
    that ``windows`` timed windows plus ``margin`` seconds (compiles,
    warm sync, re-measures) fit the supervisor's remaining attempt
    budget. A window the supervisor kills mid-flight measures nothing,
    so fitting beats the ideal sync-amortization size."""
    budget = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", 1200))
    remain = budget - (time.monotonic() - _T0) - margin
    per_window_s = max(remain / max(windows, 1), 10.0)
    return max(int(per_window_s * steps_per_sec) // 128 * 128, 128)


def _fresh_lighthouse():
    """One lighthouse PER bench phase. Phases reusing a lighthouse within
    the heartbeat window (~5 s) of the previous phase's members see their
    ghost heartbeats; the new step-0 manager can then elect a dead ghost
    as its recovery primary and wedge healing from it until timeout
    (observed on this harness; the ghost stays a quorum participant until
    its heartbeat ages out)."""
    from torchft_tpu import Lighthouse

    return Lighthouse(
        bind="[::]:0", min_replicas=1, join_timeout_ms=5000, quorum_tick_ms=50
    )


def _measure_d2h_MBps() -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    probe = jnp.ones((8 << 20,), jnp.float32) + 0  # 32 MB
    jax.block_until_ready(probe)
    t0 = time.perf_counter()
    np.asarray(probe)
    return 32 / (time.perf_counter() - t0)


def main() -> None:
    # Wedge watchdog: the tunneled device runtime can hang an in-flight
    # call forever; dump every thread's stack periodically so a killed
    # run's log names the exact blocking frame.
    import faulthandler

    faulthandler.dump_traceback_later(300, repeat=True, exit=False)
    parser = argparse.ArgumentParser()
    parser.add_argument("--peer", action="store_true")
    args = parser.parse_args()
    if args.peer:
        peer()
        return

    # Honor JAX_PLATFORMS when the caller sets it (CPU smoke tests); the
    # driver's TPU run leaves it unset and lands on the real chip.
    from torchft_tpu.platform import (
        apply_compilation_cache_env,
        apply_jax_platform_env,
    )

    apply_jax_platform_env()
    # Persistent jit cache (repo-local): the big-model compiles cost
    # minutes each through the tunneled remote-compile service, and a
    # prior run's cache spends the attempt budget on measurement instead.
    apply_compilation_cache_env(os.path.join(REPO, ".bench_jax_cache"))

    import jax
    import numpy as np
    import optax

    from torchft_tpu import (
        AsyncDiLoCo,
        FTTrainState,
        HostCollectives,
        Manager,
        OptimizerWrapper,
    )
    from torchft_tpu.models import init_params, loss_fn, make_train_step

    cfg, batch, on_tpu = _model_setup()
    # ring peers (spawned with inherited env) must pack identical trees
    os.environ["BENCH_FORCE_LAYERS"] = str(cfg.n_layers)
    # The raw window must amortize the drain the same way the FT windows
    # do: on the tunneled runtime the end-of-window readback costs a full
    # RTT (up to seconds), so a 30-step raw window under-measures raw by
    # tens of percent against a 4096-step FT window — the source of the
    # absurd >1 FT/raw ratios in earlier rounds.
    warmup, steps = 5, 512 if on_tpu else 15
    tx = optax.adamw(1e-3)
    # The fused one-program step (grad+apply, donated) is the raw baseline
    # AND the diloco inner step; per-step DDP necessarily splits the
    # programs (the ring needs the gradients on the host between them).
    train_step = make_train_step(cfg, tx)

    detail = {"host": {"cpus": os.cpu_count(), "platform": jax.devices()[0].platform}}

    # -- raw loop --
    def time_raw(warm: int) -> float:
        return _time_raw_loop(
            train_step,
            lambda: init_params(cfg, jax.random.PRNGKey(0)), tx, batch,
            warm, steps,
        )

    _mark("phase: raw (compile + timed loop)")
    raw_sps = time_raw(warmup)
    detail["raw"] = {"steps_per_sec": round(raw_sps, 3)}
    _mark(f"phase: transfer probe (raw={raw_sps:.1f} steps/s)")

    # Device<->host bandwidth of the gradient-sized payload: the number that
    # decides whether per-step DDP or windowed DiLoCo fits this host.
    import jax.numpy as jnp

    probe = jnp.ones((16 << 20,), jnp.float32) + 0  # 64 MB
    jax.block_until_ready(probe)
    t0 = time.perf_counter()
    host_probe = np.asarray(probe)
    d2h_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.block_until_ready(jnp.asarray(host_probe))
    h2d_s = time.perf_counter() - t0
    detail["transfer"] = {
        "d2h_MBps": round(64 / d2h_s, 1),
        "h2d_MBps": round(64 / h2d_s, 1),
    }
    del probe, host_probe

    n_params = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(init_params(cfg, jax.random.PRNGKey(0)))
    )
    grad_mb = n_params * 4 / 1e6
    d2h_MBps = detail["transfer"]["d2h_MBps"]
    h2d_MBps = detail["transfer"]["h2d_MBps"]
    force_ddp = os.environ.get("BENCH_FORCE_DDP") == "1" or (
        os.environ.get("BENCH_WIRE") == "ddp"
    )

    # -- ft_ddp (flagship-scale): per-step gradient allreduce over a real
    # 2-group ring -- run AFTER the headline lands (see phase order below).
    # The reference's product mode (per-step allreduce hidden behind
    # backward, reference ddp.py:47-71). Measured at REPRESENTATIVE
    # arithmetic intensity: the smoke config's 512 tokens/step against a
    # full gradient ship is a compute:comm balance no DDP deployment has
    # (measured breakdown on 1 CPU core: grad 546 ms vs ring 127 ms +
    # unpack 66 ms — fixed ring WORK that neither overlap nor bf16 can
    # remove on a single core). The DDP phase therefore scales the batch
    # and measures its OWN raw baseline at the same config; blocking and
    # pipelined (PipelinedDDP: step i's ring overlapped with step i+1's
    # grads — the torch bucket-hook overlap, restructured for JAX's
    # one-pytree gradients) are both recorded. On CPU BOTH batch points
    # land in the artifact (round-3 verdict #6): the reference-like small
    # batch where fixed ring work dominates, and the 4x-token batch where
    # compute amortizes it — the ratio is an arithmetic-intensity story,
    # and recording one point hides that.
    def run_ft_ddp_phase() -> dict:
        from torchft_tpu import PipelinedDDP

        degraded = on_tpu and d2h_MBps < 100
        # The DDP step MUST split grad and apply (the ring runs between
        # them); its raw baseline stays the FUSED step at the same batch,
        # so the ratio honestly charges the split to the transport.
        ddp_grad_fn = jax.jit(
            jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b))
        )
        ddp_steps = 2 if degraded else (4 if on_tpu else 5)

        def time_ddp_raw(ddp_batch, warm: int, n: int) -> float:
            return _time_raw_loop(
                train_step,
                lambda: init_params(cfg, jax.random.PRNGKey(0)), tx,
                ddp_batch, warm, n,
            )

        def run_ddp(mode: str, wire: str, ddp_batch) -> float:
            # Fresh lighthouse per session (_fresh_lighthouse) and every
            # resource constructed INSIDE the try: a constructor failure
            # must not leak a heartbeating "bench_peer" into later phases.
            lh = peer_proc = manager = collectives = None
            try:
                lh = _fresh_lighthouse()
                peer_proc = _spawn_peer(lh.address(), 1 + ddp_steps, wire)
                state = FTTrainState(
                    init_params(cfg, jax.random.PRNGKey(0)), tx
                )
                collectives = HostCollectives(timeout=timedelta(seconds=1800))
                manager = Manager(
                    collectives=collectives,
                    load_state_dict=state.load_state_dict,
                    state_dict=state.state_dict,
                    min_replica_size=1,
                    timeout=timedelta(seconds=600),  # 1st step rides a compile
                    quorum_timeout=timedelta(seconds=600),
                    rank=0,
                    world_size=1,
                    lighthouse_addr=lh.address(),
                    # sorts before "bench_peer": the step-0 primary is the
                    # first-sorted id and the peer never serves checkpoints
                    replica_id=f"bench_main_ddp_{mode}",
                )
                if mode == "blocking":
                    optimizer = OptimizerWrapper(manager, state)

                    def ft_step():
                        optimizer.zero_grad()
                        loss, grads = ddp_grad_fn(state.params, ddp_batch)
                        avg = manager.allreduce(grads).wait()
                        optimizer.step(avg)

                    ft_step()  # warm (peer round 0)
                    _barrier(state.params)
                    t0 = time.perf_counter()
                    for _ in range(ddp_steps):
                        ft_step()
                    _barrier(state.params)
                    t_end = time.perf_counter()
                else:
                    ddp = PipelinedDDP(
                        manager, state,
                        lambda p, b: ddp_grad_fn(p, b),
                        compress="bf16" if wire == "bf16" else None,
                    )
                    ddp.step(ddp_batch)  # warm dispatch (peer round 0)
                    _barrier(state.params)
                    # Steady-state rate: each timed step settles exactly
                    # one prior transaction and dispatches one ring (one
                    # in-flight at entry, one left at exit); the fully-
                    # exposed flush stays OUTSIDE the window so the
                    # blocking-vs-pipelined comparison is unbiased.
                    t0 = time.perf_counter()
                    for _ in range(ddp_steps):
                        ddp.step(ddp_batch)
                    t_end = time.perf_counter()
                    ddp.flush()
                    _barrier(state.params)
                sps = ddp_steps / (t_end - t0)
                # A real 2-member ring carried every byte (no world-size-1
                # identity shortcut).
                assert collectives.size() == 2, "peer did not join the ring"
                peer_proc.wait(timeout=600)
            finally:
                if peer_proc is not None and peer_proc.poll() is None:
                    peer_proc.kill()
                if manager is not None:
                    manager.shutdown()
                if collectives is not None:
                    collectives.shutdown()
                if lh is not None:
                    lh.shutdown()
            return sps

        wire = "bf16" if degraded else "f32"

        def measure_point(ddp_batch) -> dict:
            # Degraded-link forced mode runs only the pipelined+bf16
            # variant: the blocking variant's f32 tree would mismatch the
            # peer's bf16 zeros on the ring, and each extra step ships the
            # full gradient through the crippled tunnel.
            # On TPU ddp_batch == batch, so the long-window raw
            # measurement is the baseline (a short re-measure would
            # under-measure raw by the end-of-window drain RTT and
            # flatter the FT ratio). On CPU, best-of-2 short windows: a
            # single window on the loaded 1-core host under-measures raw
            # enough to produce nonsense FT/raw > 1.
            ddp_raw = raw_sps if on_tpu else max(
                time_ddp_raw(ddp_batch, 1, ddp_steps),
                time_ddp_raw(ddp_batch, 0, ddp_steps),
            )
            blocking = (
                None if degraded else run_ddp("blocking", wire, ddp_batch)
            )
            pipe = run_ddp("pipelined", wire, ddp_batch)
            best = max(s for s in (blocking, pipe) if s is not None)
            return {
                "steps_per_sec": round(best, 3),
                "ratio_vs_raw": round(best / ddp_raw, 3),
                "raw_steps_per_sec": round(ddp_raw, 3),
                "blocking_steps_per_sec": (
                    None if blocking is None else round(blocking, 3)
                ),
                "pipelined_steps_per_sec": round(pipe, 3),
                "tokens_per_step": int(ddp_batch.size),
            }

        big_batch = batch if on_tpu else jnp.concatenate([batch] * 4, axis=0)
        out = measure_point(big_batch)
        out["wire"] = wire
        out["note"] = (
            "per-step full-gradient shipping over a live 2-member ring; "
            "raw baseline measured at the same batch"
            + (
                "; FORCED run on a degraded device<->host link — the "
                "absolute rate is link-bound, not framework-bound"
                if degraded
                else ""
            )
        )
        if not on_tpu:
            # reference-like small batch: fixed ring work is ~30% of the
            # 1-core step there, so the ratio is structurally lower — the
            # amortization rule (compute >= 9x overhead for >= 0.9
            # blocking) made explicit by recording both points
            out["small_batch"] = measure_point(batch)
            out["note"] += (
                "; small_batch = the reference-like batch where ring "
                "work is not amortized (ratio >= 0.9 needs compute >= 9x "
                "overhead in blocking mode, ~1.1x in pipelined)"
            )
        return out

    def run_ft_ddp_skip_note() -> dict:
        return {
            "skipped": f"device<->host link degraded ({d2h_MBps} MB/s d2h); "
            f"per-step shipping of {grad_mb:.0f} MB grads is link-bound "
            f"(>= {grad_mb / d2h_MBps:.0f} s/step floor) regardless of "
            "framework — the link-sized phase (ft_ddp_small) carries the "
            "per-step story on this link; set BENCH_FORCE_DDP=1 to record "
            "the link-bound flagship number",
        }

    # -- ft_diloco: AsyncDiLoCo over the same real ring (headline) --
    # Tuned to the measured link, the H-tuning every DiLoCo deployment does
    # (H in the hundreds-to-thousands per the paper):
    #  - window sized so the bf16 sync stays ~<=10% of wall-clock;
    #  - on degraded links (tunneled device runtime) the sync runs
    #    serially at the boundary: an in-flight transfer starves under the
    #    async dispatch flood there, so overlap is strictly worse.
    _mark("phase: ft_diloco")
    overlap = d2h_MBps >= 100
    if not overlap:
        # Degraded device<->host link (tunneled runtime): the chunked
        # d2h/ring/h2d overlap pipeline can wedge the device session
        # outright (in-flight transfer starved under overlapping async
        # dispatch — observed reproducibly on this host). Serialize the
        # ring transfers on BOTH members (env flows to the peer).
        os.environ["TORCHFT_HC_PIPELINE_CHUNKS"] = "1"
    sync_mb = n_params * 2 / 1e6  # bf16-compressed pseudogradient
    sync_est_s = (
        2.5 * (sync_mb / max(d2h_MBps, 0.1) + sync_mb / max(h2d_MBps, 0.1))
        + 1.0  # ring + dispatch slack
    )
    # Cap 4096: this phase's ratio is the PROVISIONAL headline only (the
    # big phase's ratio is the real one), so it no longer buys precision
    # with giant windows — and the tunnel's throughput can degrade 5x+
    # MID-WINDOW, turning a 12288-step window sized at the healthy rate
    # into a supervisor-budget killer (observed: a ~164 s window crawling
    # past 40 min). A capped window under-amortizes the boundary sync on
    # degraded links; the big phase measures the honest ratio. The
    # supervisor budget then clamps further so both timed windows (plus
    # margin) fit the attempt: a killed window measures nothing.
    sync_every = int(
        min(max(12 * sync_est_s * raw_sps, SYNC_EVERY), 4096) // 128 * 128
    ) or SYNC_EVERY
    sync_every = min(sync_every, _budget_window_steps(2, raw_sps, margin=180))
    # Two timed windows, best-of reported: the tunneled device runtime has
    # minute-scale throughput swings (transient stalls halve a single
    # window's rate), and the best window is the steady-state capability
    # the metric is after. Both rates land in the detail file.
    diloco_windows = 2
    # int8+error-feedback on degraded links: the window sync is the cost
    # being measured there, and int8 ships 4x fewer bytes than f32 (2x
    # fewer than bf16); healthy links keep bf16 (sync hides behind
    # compute anyway, and allgather traffic grows with cohort size).
    wire = _env_wire() or ("bf16" if overlap else "int8")
    lighthouse = _fresh_lighthouse()  # own instance: no ghost members
    peer_proc = _spawn_peer(lighthouse.address(), diloco_windows + 1, wire)
    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
    collectives = HostCollectives(timeout=timedelta(seconds=1800))
    manager = Manager(
        collectives=collectives,
        load_state_dict=None,  # set below via diloco
        state_dict=None,
        min_replica_size=1,
        use_async_quorum=False,
        timeout=timedelta(seconds=1800),
        quorum_timeout=timedelta(seconds=1800),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse.address(),
        replica_id="bench_main_diloco",
    )
    diloco = AsyncDiLoCo(
        manager,
        state,
        optax.sgd(0.7, momentum=0.9, nesterov=True),
        sync_every,
        compress=wire,
        overlap=overlap,
    )
    manager._load_state_dict = diloco.load_state_dict
    manager._user_state_dict = diloco.state_dict

    # Warmup: compile the inner step, then force ONE early boundary sync
    # (compiles the quorum + both sync-side jits; in serial mode it runs
    # launch+finish end to end) — the measurement semantics don't need a
    # full sync_every-step crawl to the first boundary, and skipping it
    # cuts several minutes of warmup at sync_every in the thousands.
    # The periodic drain bounds the in-flight dispatch queue: on the
    # tunneled device runtime an unbounded multi-thousand-op queue can
    # wedge the session (observed reproducibly at 6k+ queued steps).
    _mark("diloco: warm inner steps")
    # min() guard: warm steps must stay below sync_every or the window
    # accounting auto-syncs here, consuming the peer's first of windows+1
    # rounds (same guard as _bench_big, whose floor is lower)
    for i in range(min(65, sync_every - 1)):
        state.params, state.opt_state, loss = train_step(
            state.params, state.opt_state, batch
        )
        diloco.step_applied()
        if i % 64 == 63:
            np.asarray(loss)  # real drain: block_until_ready returns
            # before remote execution finishes on this tunnel (_barrier)
    _mark("diloco: warm sync")
    diloco.sync()  # early warm sync = the peer's first of windows+1 rounds
    _mark("diloco: warm sync launched")
    if overlap:
        diloco.flush()  # pull the warm sync out of the timed region
    _barrier(state.params)
    window_sps = []
    for w in range(diloco_windows):
        _mark(f"diloco: timed window {w} (sync_every={sync_every})")
        t0 = time.perf_counter()
        for i in range(sync_every):
            state.params, state.opt_state, loss = train_step(
                state.params, state.opt_state, batch
            )
            diloco.step_applied()
            if i % 512 == 511:
                np.asarray(loss)  # real drain: bounded queue; sparse because each
                # drain costs a full tunnel RTT (seconds when degraded)
        diloco.flush()  # window boundary: sync complete before the clock stops
        _barrier(state.params)
        window_sps.append(sync_every / (time.perf_counter() - t0))
        _mark(f"diloco: window {w} done ({window_sps[-1]:.1f} steps/s)")
    ft_sps = max(window_sps)
    detail["ft_diloco"] = {
        "steps_per_sec": round(ft_sps, 3),
        "window_steps_per_sec": [round(s, 3) for s in window_sps],
        "ratio_vs_raw": round(ft_sps / raw_sps, 3),
        "sync_every": sync_every,
        "compress": wire,
        "overlap": overlap,
        "note": f"{wire} pseudogradient window sync (AsyncDiLoCo); best of "
        f"{diloco_windows} windows (the tunneled runtime has transient "
        "stalls; both rates recorded); overlapped with inner compute on "
        "healthy links, serial-at-boundary on degraded ones (see "
        "local_sgd.AsyncDiLoCo overlap flag)",
    }
    peer_proc.wait(timeout=300)
    manager.shutdown()
    collectives.shutdown()
    lighthouse.shutdown()

    # Headline line + detail land BEFORE any further device phases (the
    # raw re-measure, the big model) so a tunnel wedge there can never
    # lose the round's primary metric; the supervisor takes the LAST
    # metric line, so a refined headline can safely overwrite this one.
    # CPU smoke runs write a separate file so they can never clobber the
    # committed TPU artifact.
    detail_name = (
        "BENCH_DETAIL.json" if on_tpu else "BENCH_DETAIL_cpu.json"
    )

    def land_headline() -> None:
        with open(os.path.join(REPO, detail_name), "w") as f:
            json.dump(detail, f, indent=2)
        print(
            json.dumps(
                {
                    "metric": "steps_per_sec_ft",
                    "value": round(ft_sps, 3),
                    "unit": "steps/s",
                    "vs_baseline": round((ft_sps / raw_sps) / 0.90, 3),
                }
            ),
            flush=True,
        )

    land_headline()

    # Symmetric noise treatment: the numerator is best-of-2 windows, so
    # the denominator is best-of-2 raw measurements too (re-timed here,
    # minutes after the first — tunnel stalls are minute-scale). The
    # provisional headline above already landed in case this wedges.
    _mark("phase: raw re-measure")
    raw_again = time_raw(1)
    detail["raw"]["steps_per_sec_2nd"] = round(raw_again, 3)
    raw_sps = max(raw_sps, raw_again)
    detail["raw"]["best"] = round(raw_sps, 3)
    detail["ft_diloco"]["ratio_vs_raw"] = round(ft_sps / raw_sps, 3)
    # (ft_ddp's ratio is against its OWN same-batch raw baseline and is
    # not rewritten here.)
    land_headline()

    # -- per-step FT: the link-sized phase runs on TPU EVERY round (the
    # per-step product must have a number on this hardware); the
    # flagship-scale point runs when the link can carry it (or forced) --
    if on_tpu:
        _mark("phase: ft_ddp_small")
        try:
            detail["ft_ddp_small"] = _bench_ddp_small(d2h_MBps, h2d_MBps)
        except Exception as e:  # noqa: BLE001 - keep the headline
            detail["ft_ddp_small"] = {"error": f"{type(e).__name__}: {e}"}
        land_headline()
    _mark(f"phase: ft_ddp flagship (d2h={d2h_MBps:.1f} MB/s)")
    if not on_tpu or d2h_MBps >= 100 or force_ddp:
        try:
            detail["ft_ddp"] = run_ft_ddp_phase()
        except Exception as e:  # noqa: BLE001 - keep the headline
            detail["ft_ddp"] = {"error": f"{type(e).__name__}: {e}"}
    else:
        detail["ft_ddp"] = run_ft_ddp_skip_note()
    land_headline()

    # -- big: FT overhead at MXU-saturating arithmetic intensity; its
    # ratio is THE headline (round-3 verdict #3: the small-model window
    # dilutes FT cost — the big phase measures it at deployment-class
    # arithmetic intensity). Sub-results persist incrementally via
    # save_partial so a supervisor kill can never erase the phase. --
    if on_tpu and not os.environ.get("BENCH_SKIP_BIG"):

        def save_partial(partial: dict) -> None:
            cur = dict(detail.get("big") or {})
            cur.update(partial)
            detail["big"] = cur
            with open(os.path.join(REPO, detail_name), "w") as f:
                json.dump(detail, f, indent=2)

        try:
            _bench_big(save_partial)
        except Exception as e:  # noqa: BLE001 - best effort, keep headline
            save_partial({"error": f"{type(e).__name__}: {e}"})
        big = detail.get("big") or {}
        if big.get("ft_diloco_steps_per_sec") and big.get("ratio_vs_raw"):
            # Promote the big phase to the printed headline (the driver
            # takes the LAST metric line; the small-model line above stays
            # as the provisional fallback if this phase died).
            detail["headline"] = "big"
            with open(os.path.join(REPO, detail_name), "w") as f:
                json.dump(detail, f, indent=2)
            print(
                json.dumps(
                    {
                        "metric": "steps_per_sec_ft",
                        "value": big["ft_diloco_steps_per_sec"],
                        "unit": "steps/s",
                        "vs_baseline": round(big["ratio_vs_raw"] / 0.90, 3),
                    }
                ),
                flush=True,
            )


def _supervised() -> None:
    """Wedge-resilient outer layer: the measurement runs in a child with a
    deadline and ONE retry. The device runtime on this host (tunneled)
    occasionally wedges a session's in-flight call forever while fresh
    sessions keep working — an orchestrator that never touches the device
    can kill the stuck child and re-roll, instead of losing the round's
    metric. The child's final JSON line is re-printed verbatim."""
    deadline_s = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", 1200))
    env = dict(os.environ, BENCH_INNER="1")
    last_output = ""
    for attempt in range(2):
        proc = subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            last_output, _ = proc.communicate(timeout=deadline_s)
            if proc.returncode == 0:
                break
            note = f"failed rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            proc.kill()
            last_output, _ = proc.communicate()
            subprocess.run(["pkill", "-9", "-f", "bench.py --peer"],
                           check=False)
            note = f"wedged past {deadline_s}s"
        if any(l.startswith('{"metric"') for l in last_output.splitlines()):
            # The headline landed before the (best-effort) big phase died;
            # keep it rather than re-rolling a finished measurement.
            break
        print(
            f"bench attempt {attempt} {note}; "
            + ("retrying" if attempt == 0 else "giving up"),
            file=sys.stderr,
            flush=True,
        )
    metric_lines = [
        l for l in last_output.splitlines() if l.startswith('{"metric"')
    ]
    if metric_lines:
        print(metric_lines[-1])
    else:
        sys.stderr.write(last_output[-2000:])
        sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("BENCH_INNER") or "--peer" in sys.argv:
        main()
    else:
        _supervised()
