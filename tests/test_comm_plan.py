"""Persistent comm-plan tests.

The plan path's contract: ONE GIL-released native call per step, zero
Python-side staging allocation after warmup, and results BIT-IDENTICAL to
the legacy managed path for every wire — the plan executes the identical
per-group stripe partition through the same native ring bodies, so these
tests are the oracle that the shared-schedule claim stays true as either
path evolves.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu._native import Store
from torchft_tpu.collectives import (
    DummyCollectives,
    HostCollectives,
    ReduceOp,
)


@pytest.fixture
def store():
    s = Store()
    yield s
    s.shutdown()


def _make_ring(store, world_size, prefix, stripes=1,
               timeout=timedelta(seconds=15)):
    cols = [
        HostCollectives(timeout=timeout, stripes=stripes)
        for _ in range(world_size)
    ]
    addr = f"{store.address()}/{prefix}"
    with ThreadPoolExecutor(max_workers=world_size) as ex:
        for f in [
            ex.submit(cols[r].configure, addr, r, world_size)
            for r in range(world_size)
        ]:
            f.result()
    return cols


def _run_all(cols, fn):
    results = [None] * len(cols)
    errors = []

    def run(r):
        try:
            results[r] = fn(r, cols[r])
        except Exception as e:  # noqa: BLE001
            errors.append((r, e))

    threads = [
        threading.Thread(target=run, args=(r,)) for r in range(len(cols))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0][1]
    return results


def _np_quantize_ef(leaf, res):
    """Pure-numpy mirror of quantize.quantize_with_feedback (and of the
    native plan EF): the FMA-free reference both implementations are
    tested against. (The jitted jax version may differ from either at the
    last ulp of the residual — XLA contracts ``d - q*scale`` into an fma —
    which is exactly why the plan's native EF is the wire contract.)"""
    d = (leaf.astype(np.float32) + res).astype(np.float32)
    absmax = np.max(np.abs(d)) if d.size else np.float32(0)
    if not np.isfinite(absmax):
        nan = np.float32(np.nan)
        return np.full_like(d, nan), np.full_like(d, nan)
    scale = np.maximum(np.float32(absmax) / np.float32(127.0),
                       np.float32(1e-12))
    q = np.clip(np.round(d / scale), -127, 127).astype(np.float32)
    dq = (q * scale).astype(np.float32)
    return dq, (d - dq).astype(np.float32)


def _trees(world_size, rng_seed=7):
    """Mixed-dtype trees with uneven leaf sizes: the flat counts divide
    evenly by neither world size nor stripe count, so ring chunks AND
    stripe sub-ranges (= plan buckets) land on uneven tails."""
    import ml_dtypes

    rng = np.random.default_rng(rng_seed)
    base = {
        "w": rng.standard_normal(100003).astype(np.float32),
        "v": rng.standard_normal((13, 7)).astype(np.float64),
        "b": (rng.integers(-16, 16, 1001) * 0.125).astype(ml_dtypes.bfloat16),
        "n": rng.integers(-100, 100, 41).astype(np.int64),
    }
    return [
        {k: v * (r + 1) for k, v in base.items()} for r in range(world_size)
    ]


class TestPlanBitIdentity:
    @pytest.mark.parametrize("world_size", [2, 3, 5])
    @pytest.mark.parametrize("stripes", [1, 4])
    def test_native_wire_matches_legacy(self, store, world_size, stripes):
        cols = _make_ring(
            store, world_size, f"p_{world_size}_{stripes}", stripes
        )
        trees = _trees(world_size)
        div = float(world_size)
        legacy = _run_all(
            cols,
            lambda r, c: c.allreduce(trees[r], ReduceOp.SUM, divisor=div)
            .wait(),
        )
        plan = _run_all(
            cols,
            lambda r, c: c.plan_allreduce(
                trees[r], ReduceOp.SUM, divisor=div
            ).wait(),
        )
        for leg, pl in zip(legacy, plan):
            for k in leg:
                assert (
                    np.asarray(leg[k]).tobytes() == np.asarray(pl[k]).tobytes()
                ), f"leaf {k}: plan != legacy bitwise"
        # and across ranks (the determinism oracle, extended to the plan)
        for other in plan[1:]:
            for k in other:
                assert np.asarray(plan[0][k]).tobytes() == np.asarray(
                    other[k]
                ).tobytes()
        for c in cols:
            c.shutdown()

    @pytest.mark.parametrize("stripes", [1, 4])
    def test_q8_wire_matches_legacy(self, store, stripes):
        cols = _make_ring(store, 3, f"pq8_{stripes}", stripes)
        rng = np.random.default_rng(3)
        base = rng.standard_normal(100003).astype(np.float32)
        trees = [{"g": base * (r + 1)} for r in range(3)]
        legacy = _run_all(
            cols,
            lambda r, c: c.allreduce(
                trees[r], ReduceOp.SUM, divisor=3.0, wire="q8"
            ).wait(),
        )
        plan = _run_all(
            cols,
            lambda r, c: c.plan_allreduce(
                trees[r], ReduceOp.SUM, divisor=3.0, wire="q8"
            ).wait(),
        )
        for leg, pl in zip(legacy, plan):
            assert np.asarray(leg["g"]).tobytes() == np.asarray(
                pl["g"]
            ).tobytes()
        for c in cols:
            c.shutdown()

    def test_bf16_wire_matches_legacy_cast_composition(self, store):
        # wire="bf16"'s legacy equivalent is ddp's compress="bf16": cast
        # f32 leaves to bf16, ride the native bf16 ring, cast back.
        import ml_dtypes

        cols = _make_ring(store, 3, "pbf", stripes=2)
        rng = np.random.default_rng(5)
        base = rng.standard_normal(70001).astype(np.float32)
        trees = [{"g": base * (r + 1)} for r in range(3)]
        cast = [
            {"g": t["g"].astype(ml_dtypes.bfloat16)} for t in trees
        ]
        legacy = _run_all(
            cols,
            lambda r, c: c.allreduce(cast[r], ReduceOp.SUM, divisor=3.0)
            .wait(),
        )
        plan = _run_all(
            cols,
            lambda r, c: c.plan_allreduce(
                trees[r], ReduceOp.SUM, divisor=3.0, wire="bf16"
            ).wait(),
        )
        for leg, pl in zip(legacy, plan):
            got = np.asarray(pl["g"])
            assert got.dtype == np.float32  # decoded back to the leaf dtype
            want = np.asarray(leg["g"]).astype(np.float32)
            assert got.tobytes() == want.tobytes()
        for c in cols:
            c.shutdown()

    @pytest.mark.parametrize("world_size", [2, 3])
    def test_q8ef_matches_numpy_ef_plus_legacy_q8(self, store, world_size):
        # The error-feedback oracle, run over several steps so the carry
        # itself is proven bit-identical (a drifting residual would
        # surface as a diverging quantization within a few steps).
        cols = _make_ring(store, world_size, f"pef_{world_size}", stripes=4)
        rng = np.random.default_rng(11)
        N = 70001
        res = [
            {"w": np.zeros(N, np.float32), "b": np.zeros(33, np.float32)}
            for _ in range(world_size)
        ]
        div = float(world_size)
        for step in range(5):
            grads = [
                {
                    "w": rng.standard_normal(N).astype(np.float32),
                    "b": rng.standard_normal(33).astype(np.float32) * 7,
                }
                for _ in range(world_size)
            ]
            legacy_dq = []
            for r in range(world_size):
                dqt = {}
                for k in grads[r]:
                    dq, nr = _np_quantize_ef(grads[r][k], res[r][k])
                    dqt[k] = dq
                    res[r][k] = nr
                legacy_dq.append(dqt)
            leg = _run_all(
                cols,
                lambda r, c: c.allreduce(
                    legacy_dq[r], ReduceOp.SUM, divisor=div, wire="q8"
                ).wait(),
            )
            plan = _run_all(
                cols,
                lambda r, c: c.plan_allreduce(
                    grads[r], ReduceOp.SUM, divisor=div, wire="q8ef"
                ).wait(),
            )
            for k in ("w", "b"):
                assert np.asarray(leg[0][k]).tobytes() == np.asarray(
                    plan[0][k]
                ).tobytes(), f"step {step} leaf {k}: EF diverged"
        for c in cols:
            c.shutdown()

    def test_q8ef_reset_feedback_restarts_carry(self, store):
        cols = _make_ring(store, 2, "pefreset")
        rng = np.random.default_rng(2)
        grads = [
            {"w": rng.standard_normal(5001).astype(np.float32) * (r + 1)}
            for r in range(2)
        ]

        def sync(r, c):
            return c.plan_allreduce(
                grads[r], ReduceOp.SUM, divisor=2.0, wire="q8ef"
            ).wait()

        first = _run_all(cols, sync)
        _run_all(cols, sync)  # advances the carry
        _run_all(cols, lambda r, c: c.plan_reset_feedback())
        again = _run_all(cols, sync)  # carry zeroed -> same as the first
        assert np.asarray(first[0]["w"]).tobytes() == np.asarray(
            again[0]["w"]
        ).tobytes()
        for c in cols:
            c.shutdown()


class TestPlanLifecycle:
    def test_q8_nonfinite_poisons_all_members(self, store):
        # The fused q8 poisoning contract holds on the plan path too: a
        # NaN/Inf leaf must come out non-finite on EVERY member.
        cols = _make_ring(store, 3, "ppoison")
        rng = np.random.default_rng(17)
        base = rng.standard_normal(400).astype(np.float32)

        def op(r, c):
            arr = base * (r + 1)
            if r == 0:
                arr = arr.copy()
                arr[7] = np.nan
                arr[250] = np.inf
            return c.plan_allreduce(
                {"w": arr}, ReduceOp.SUM, wire="q8"
            ).wait()

        results = _run_all(cols, op)
        for out in results:
            got = np.asarray(out["w"])
            assert np.isnan(got[7])
            assert np.isnan(got[250])
        for other in results[1:]:
            assert np.asarray(results[0]["w"]).tobytes() == np.asarray(
                other["w"]
            ).tobytes()
        for c in cols:
            c.shutdown()

    def test_zero_python_staging_allocs_and_bucket_stats(self, store):
        cols = _make_ring(store, 2, "pstats", stripes=4)
        rng = np.random.default_rng(0)
        # > 4 * 64 KiB so the payload stripes into 4 buckets
        tree = {"g": rng.standard_normal(200003).astype(np.float32)}
        trees = [tree, {"g": tree["g"] * 2}]

        def sync(r, c):
            return c.plan_allreduce(
                trees[r], ReduceOp.SUM, divisor=2.0
            ).wait()

        _run_all(cols, sync)  # warmup (plan build)
        cols[0].pop_op_stats()
        _run_all(cols, sync)
        _run_all(cols, sync)
        stats = [
            s for s in cols[0].pop_op_stats() if s["op"] == "plan_allreduce"
        ]
        assert len(stats) == 2
        for st in stats:
            # the zero-allocation contract after warmup
            assert st["py_staging_allocs"] == 0
            assert st["bytes"] == tree["g"].nbytes
            assert st["buckets"], "plan stats must carry per-bucket phases"
            assert len(st["buckets"]) == 4  # 4 stripes -> 4 buckets
            for b in st["buckets"]:
                assert {"group", "stripe", "bytes", "pack_s", "ring_s",
                        "unpack_s"} <= set(b)
            assert sum(b["bytes"] for b in st["buckets"]) == tree["g"].nbytes
        for c in cols:
            c.shutdown()

    def test_plan_survives_repeat_and_reconfigure(self, store):
        # Same signature reuses the cached plan; a reconfigure (new
        # quorum) invalidates and transparently rebuilds it — and the
        # rebuilt plan is correct for the NEW membership.
        cols = _make_ring(store, 3, "precfg")
        tree = {"g": np.ones(10007, np.float32)}

        out = _run_all(
            cols,
            lambda r, c: c.plan_allreduce({"g": tree["g"] * (r + 1)}).wait(),
        )
        np.testing.assert_array_equal(
            np.asarray(out[0]["g"]), np.full(10007, 6.0)
        )
        assert len(cols[0]._plans) == 1

        survivors = cols[:2]
        addr = f"{store.address()}/precfg2"
        _run_all(survivors, lambda r, c: c.configure(addr, r, 2))
        assert cols[0]._plans == {}  # cache dropped with the old ring
        out = _run_all(
            survivors,
            lambda r, c: c.plan_allreduce({"g": tree["g"] * (r + 1)}).wait(),
        )
        np.testing.assert_array_equal(
            np.asarray(out[0]["g"]), np.full(10007, 3.0)
        )
        for c in cols:
            c.shutdown()

    def test_stale_native_plan_id_errors(self, store):
        # The native side must reject an id from before a reconfigure
        # (its layout baked in the old ring) instead of executing it.
        import ctypes

        from torchft_tpu._native import _lib

        cols = _make_ring(store, 2, "pstale")
        tree = {"g": np.ones(4096, np.float32)}
        _run_all(cols, lambda r, c: c.plan_allreduce(
            {"g": tree["g"] * (r + 1)}).wait())
        plan = next(iter(cols[0]._plans.values()))
        stale_id = plan.plan_id
        addr = f"{store.address()}/pstale2"
        _run_all(cols, lambda r, c: c.configure(addr, r, 2))
        out = ctypes.c_void_p()
        rc = _lib.tft_plan_stats_json(
            cols[0]._handle, stale_id, ctypes.byref(out)
        )
        assert rc != 0  # unknown/invalidated plan
        for c in cols:
            c.shutdown()

    def test_abort_during_plan_execute_wakes_all_stripes(self, store):
        # Peer death mid-plan-execute must wake EVERY stripe worker
        # promptly (one surfaced error, not one timeout per stripe), and
        # a fresh configure restores plan service.
        cols = [
            HostCollectives(timeout=timedelta(seconds=30), stripes=4)
            for _ in range(2)
        ]
        addr = f"{store.address()}/pabort"
        with ThreadPoolExecutor(max_workers=2) as ex:
            for f in [
                ex.submit(cols[r].configure, addr, r, 2) for r in range(2)
            ]:
                f.result()
        big = {"g": np.ones(1 << 20, np.float32)}  # 4 MB -> 4 stripes
        w = cols[0].plan_allreduce(big)
        threading.Timer(0.3, cols[1].shutdown).start()
        start = time.monotonic()
        with pytest.raises(RuntimeError):
            w.wait(timeout=timedelta(seconds=20))
        elapsed = time.monotonic() - start
        assert elapsed < 5.0, (
            f"plan abort took {elapsed:.1f}s — a stripe worker sat out "
            "its own timeout instead of being woken"
        )
        fresh = HostCollectives(timeout=timedelta(seconds=30), stripes=4)
        addr2 = f"{store.address()}/pabort2"
        with ThreadPoolExecutor(max_workers=2) as ex:
            for f in [
                ex.submit(cols[0].configure, addr2, 0, 2),
                ex.submit(fresh.configure, addr2, 1, 2),
            ]:
                f.result()
        pair = [cols[0], fresh]
        outs = _run_all(
            pair,
            lambda r, c: c.plan_allreduce(
                {"g": np.ones(1 << 18, np.float32)}
            ).wait(),
        )
        for o in outs:
            np.testing.assert_array_equal(o["g"], np.full(1 << 18, 2.0))
        for c in pair:
            c.shutdown()

    def test_unsupported_dtype_falls_back_to_legacy(self, store):
        # f16 is not a native ring dtype: the plan path must serve the
        # tree through the legacy path with identical semantics (and
        # remember the verdict instead of re-attempting the build).
        cols = _make_ring(store, 2, "pfall")
        trees = [
            {"h": np.ones(257, np.float16) * (r + 1)} for r in range(2)
        ]
        out = _run_all(
            cols, lambda r, c: c.plan_allreduce(trees[r]).wait()
        )
        np.testing.assert_array_equal(
            np.asarray(out[0]["h"], np.float32), np.full(257, 3.0)
        )
        key = next(iter(cols[0]._plans))
        assert cols[0]._plans[key] is None  # cached "unsupported" verdict
        with pytest.raises(ValueError, match="q8"):
            cols[0].plan_allreduce(trees[0], wire="q8").wait()
        for c in cols:
            c.shutdown()

    def test_world_size_one_identity_and_divisor(self):
        col = HostCollectives()
        col.configure("ignored:0/q", 0, 1)
        tree = {"g": np.arange(10, dtype=np.float32)}
        out = col.plan_allreduce(tree, ReduceOp.SUM, divisor=2.0).wait()
        np.testing.assert_array_equal(out["g"], tree["g"] / 2.0)
        # AVG + explicit divisor is ambiguous and must raise loudly (the
        # legacy path's contract) — never silently replace the caller's
        # participant divisor with world_size
        with pytest.raises(ValueError, match="divisor"):
            col.plan_allreduce(tree, ReduceOp.AVG, divisor=2.0)
        col.shutdown()

    def test_dummy_plan_allreduce(self):
        d = DummyCollectives(world_size=4)
        out = d.plan_allreduce({"g": np.full(3, 8.0)}, ReduceOp.AVG).wait()
        np.testing.assert_array_equal(out["g"], np.full(3, 2.0))


class TestManagedPlanDiscipline:
    """Manager.plan_allreduce's error contract: failure -> None + latch ->
    commit vote discards (the plan's persistent buffers mean there is no
    meaningful 'as contributed' tree to fall back to)."""

    def _manager(self, collectives):
        from torchft_tpu import Lighthouse
        from torchft_tpu.manager import Manager

        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = Store()
        manager = Manager(
            collectives=collectives,
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            timeout=timedelta(seconds=10),
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="plan_test",
        )
        return manager, store, lighthouse

    def test_happy_path_averages(self):
        manager, store, lighthouse = self._manager(
            DummyCollectives(world_size=1)
        )
        try:
            manager.start_quorum()
            out = manager.plan_allreduce({"g": np.full(4, 6.0)}).wait()
            np.testing.assert_array_equal(out["g"], np.full(4, 6.0))
            assert manager.should_commit()
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_failure_resolves_none_and_discards_step(self):
        class FailingPlans(DummyCollectives):
            def plan_allreduce(self, tree, op=ReduceOp.SUM, divisor=None,
                               wire=None):
                raise RuntimeError("ring down")

        manager, store, lighthouse = self._manager(FailingPlans(world_size=1))
        try:
            manager.start_quorum()
            out = manager.plan_allreduce({"g": np.ones(4)}).wait()
            assert out is None  # no 'as contributed' fallback exists
            assert manager.errored() is not None
            assert not manager.should_commit()
            # next step starts clean and can commit again
            manager.start_quorum()
            assert manager.errored() is None
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()
