"""HSDP composition under faults: intra-group dp x tp sharding composed with
the cross-group fault-tolerance layer, end to end.

The reference proves FSDP composes with the managed replicate dimension
(reference fsdp_test.py:38-74, device_mesh_test.py:25-85). The TPU-native
equivalent proven here: each replica group runs the flagship transformer's
jitted sharded train step on its OWN 4-device mesh (data:2 x model:2 — the
slice's ICI dimensions), while gradients are averaged across groups through
a REAL 2-member host TCP ring (the DCN/replicate dimension), with kill +
heal and the bit-identical-state oracle (reference
manager_integ_test.py:279-282).

Runs on the virtual 8-device CPU platform from conftest.py: group g owns
devices [4g, 4g+4), so both sharded steps execute concurrently in one
process exactly as two slices would. Harness shared with the pp/ep
variants: sharded_integ.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import HAS_SHARD_MAP, SHARD_MAP_SKIP

if not HAS_SHARD_MAP:
    # the flagship sharded train step routes attention through the
    # shard_map'd flash kernel
    pytest.skip(SHARD_MAP_SKIP, allow_module_level=True)

from torchft_tpu.models import (
    init_params,
    loss_fn,
    param_sharding_rules,
    tiny_config,
)
from torchft_tpu.parallel import build_grad_step, make_mesh

from sharded_integ import (
    DEVICES_PER_GROUP,
    GroupSetup,
    assert_bitwise_identical,
    run_kill_and_heal,
    run_sharded_groups,
)


def _setup(gid: int) -> GroupSetup:
    from jax.sharding import NamedSharding, PartitionSpec as P

    devices = jax.devices()[
        gid * DEVICES_PER_GROUP : (gid + 1) * DEVICES_PER_GROUP
    ]
    mesh = make_mesh({"data": 2, "model": 2}, devices=devices)
    cfg = tiny_config()
    rules = param_sharding_rules(cfg)

    def batch_fn(step: int):
        # Deterministic per-step batch, identical across groups, sharded
        # over the group's data axis.
        rng = np.random.default_rng(7000 + step)
        tokens = rng.integers(0, cfg.vocab_size, size=(4, 32), dtype=np.int32)
        return jax.device_put(
            jnp.asarray(tokens), NamedSharding(mesh, P("data"))
        )

    return GroupSetup(
        devices=devices,
        mesh=mesh,
        rules=rules,
        grad_step=build_grad_step(
            lambda p, b: loss_fn(cfg, p, b), mesh, rules
        ),
        fresh_params=lambda: init_params(cfg, jax.random.PRNGKey(42)),
        batch_fn=batch_fn,
    )


class TestHSDPUnderFaults:
    def test_sharded_groups_stay_identical(self):
        results = run_sharded_groups("hsdp", _setup, num_steps=4)
        for r in results:
            assert r["manager_state"]["step"] == 4
        assert_bitwise_identical(results)

    def test_sharded_group_kill_and_heal(self):
        run_kill_and_heal("hsdp", _setup)

    def test_zero_sharded_groups_stay_identical(self):
        # Per-step ZeRO engine: reduce-scattered grads (q8 wire), ~1/W
        # optimizer shard, bf16 param allgather — composed with the
        # intra-group dp x tp sharding.
        results = run_sharded_groups(
            "hsdp", _setup, num_steps=4, engine="zero"
        )
        for r in results:
            assert r["manager_state"]["step"] == 4
        assert_bitwise_identical(results)

    def test_zero_sharded_group_kill_and_heal(self):
        # The heal carries the optimizer shard (donor's shard + meta);
        # the rejoin's quorum bump forces the cohort-wide re-partition.
        run_kill_and_heal("hsdp", _setup, engine="zero")
