import os
import subprocess
import sys

# JAX on a virtual 8-device CPU mesh: multi-chip sharding paths are tested
# without TPU hardware (the driver's dryrun uses the same trick). Must be set
# before the first `import jax` anywhere in the test session.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

_LIB = os.path.join(REPO_ROOT, "torchft_tpu", "_libtorchft.so")
if not os.path.exists(_LIB):
    subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "native")], check=True)
