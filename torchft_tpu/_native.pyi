# Typed stub for the ctypes bridge over native/src/capi.cc — the stable
# public surface of the native control plane (reference role:
# torchft/torchft.pyi:1-61 for the pyo3 module). The implementation module
# carries full inline annotations too; this stub pins the API for type
# checkers without importing the shared library.
from datetime import timedelta
from typing import Any, List, Optional, Union

# Error mapping (no custom exception classes): native failures raise
# RuntimeError; deadline-class failures raise TimeoutError, mirroring the
# reference's DeadlineExceeded/Cancelled -> TimeoutError mapping
# (reference src/lib.rs:321-333).


class QuorumResult:
    quorum_id: int
    replica_rank: int
    replica_world_size: int
    recover_src_manager_address: str
    recover_src_rank: Optional[int]
    recover_dst_ranks: List[int]
    store_address: str
    max_step: int
    max_rank: Optional[int]
    max_world_size: int
    heal: bool
    replica_regions: List[str]
    replica_hosts: List[str]

    def __init__(
        self,
        quorum_id: int = ...,
        replica_rank: int = ...,
        replica_world_size: int = ...,
        recover_src_manager_address: str = ...,
        recover_src_rank: Optional[int] = ...,
        recover_dst_ranks: List[int] = ...,
        store_address: str = ...,
        max_step: int = ...,
        max_rank: Optional[int] = ...,
        max_world_size: int = ...,
        heal: bool = ...,
        replica_regions: List[str] = ...,
        replica_hosts: List[str] = ...,
    ) -> None: ...


class Lighthouse:
    def __init__(
        self,
        bind: str = ...,
        min_replicas: int = ...,
        join_timeout_ms: int = ...,
        quorum_tick_ms: int = ...,
        heartbeat_timeout_ms: int = ...,
    ) -> None: ...
    def address(self) -> str: ...
    def status_json(self) -> dict: ...
    def shutdown(self) -> None: ...
    def __enter__(self) -> "Lighthouse": ...
    def __exit__(self, *exc: object) -> None: ...


class RegionLighthouse:
    def __init__(
        self,
        root_addr: str,
        region_id: str,
        bind: str = ...,
        digest_interval_ms: int = ...,
        heartbeat_timeout_ms: int = ...,
        connect_timeout_ms: int = ...,
    ) -> None: ...
    def address(self) -> str: ...
    def status_json(self) -> dict: ...
    def quorum_json(self) -> dict: ...
    def shutdown(self) -> None: ...
    def __enter__(self) -> "RegionLighthouse": ...
    def __exit__(self, *exc: object) -> None: ...


class LeaseClient:
    def __init__(
        self, addr: str, connect_timeout: timedelta = ...
    ) -> None: ...
    def renew(
        self, entries: List[dict], timeout: timedelta = ...
    ) -> int: ...
    def heartbeat(
        self, replica_id: str, timeout: timedelta = ...
    ) -> None: ...
    def depart(
        self, replica_id: str, timeout: timedelta = ...
    ) -> None: ...


def lighthouse_heartbeat(
    lighthouse_addr: str,
    replica_id: str,
    timeout: Union[timedelta, float, int] = ...,
) -> None: ...


class Manager:
    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        hostname: str,
        bind: str,
        store_addr: str,
        world_size: int,
        heartbeat_interval: timedelta = ...,
        connect_timeout: timedelta = ...,
        root_addr: str = ...,
        lease_ttl: Optional[timedelta] = ...,
        region: str = ...,
        host: str = ...,
    ) -> None: ...
    def address(self) -> str: ...
    def using_root_fallback(self) -> bool: ...
    def set_status(self, status: dict) -> None: ...
    def shutdown(self) -> None: ...


class ManagerClient:
    def __init__(
        self, addr: str, connect_timeout: timedelta = ...
    ) -> None: ...
    def quorum(
        self,
        rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool = ...,
        force_reconfigure: bool = ...,
        timeout: timedelta = ...,
    ) -> QuorumResult: ...
    def checkpoint_metadata(
        self, rank: int, timeout: timedelta = ...
    ) -> str: ...
    def should_commit(
        self,
        rank: int,
        step: int,
        should_commit: bool,
        timeout: timedelta = ...,
    ) -> bool: ...
    def kill(self, msg: str = ...) -> None: ...


class Store:
    def __init__(self, bind: str = ...) -> None: ...
    def address(self) -> str: ...
    @property
    def port(self) -> int: ...
    def shutdown(self) -> None: ...


class StoreClient:
    def __init__(
        self,
        addr: str,
        prefix: str = ...,
        connect_timeout: timedelta = ...,
    ) -> None: ...
    def set(
        self, key: str, value: bytes, timeout: timedelta = ...
    ) -> None: ...
    def get(self, key: str, timeout: timedelta = ...) -> bytes: ...
    def add(
        self, key: str, delta: int, timeout: timedelta = ...
    ) -> int: ...


class _NativeLib:
    """The raw ctypes surface over native/src/capi.cc, one method per
    ``tft_*`` export — the checked contract between the three bridge
    layers. graftlint's ``capi_sync`` rule diffs this class against the C
    definitions and the ``_load_lib`` argtypes declarations (names AND
    parameter counts), so bridge drift fails CI instead of corrupting a
    call frame at 2am. ``Any`` stands for a ctypes pointer/buffer
    argument; handles are ``void*``. Wire codes for tft_plan_build: 0
    native dtypes, 1 bf16, 2 q8, 3 q8+EF; plans are invalidated by
    tft_hc_configure."""

    def tft_last_error(self) -> Any: ...
    def tft_string_free(self, s: Any) -> None: ...
    def tft_lighthouse_create(
        self,
        bind: bytes,
        min_replicas: int,
        join_timeout_ms: int,
        quorum_tick_ms: int,
        heartbeat_timeout_ms: int,
        wal_dir: bytes,
        snapshot_every: int,
        peers: bytes,
        standby: int,
        takeover_ms: int
    ) -> Any: ...
    def tft_lighthouse_address(self, handle: Any) -> Any: ...
    def tft_lighthouse_shutdown(self, handle: Any) -> None: ...
    def tft_lighthouse_destroy(self, handle: Any) -> None: ...
    def tft_lighthouse_active(self, handle: Any) -> int: ...
    def tft_lighthouse_root_epoch(self, handle: Any) -> int: ...
    def tft_lighthouse_heartbeat(
        self,
        addr: bytes,
        replica_id: bytes,
        timeout_ms: int
    ) -> int: ...
    def tft_lighthouse_status_json(self, handle: Any, out: Any) -> int: ...
    def tft_region_create(
        self,
        bind: bytes,
        root_addr: bytes,
        region_id: bytes,
        digest_interval_ms: int,
        heartbeat_timeout_ms: int,
        connect_timeout_ms: int
    ) -> Any: ...
    def tft_region_address(self, handle: Any) -> Any: ...
    def tft_region_shutdown(self, handle: Any) -> None: ...
    def tft_region_destroy(self, handle: Any) -> None: ...
    def tft_region_status_json(self, handle: Any, out: Any) -> int: ...
    def tft_region_quorum_json(self, handle: Any, out: Any) -> int: ...
    def tft_lease_client_create(
        self,
        addr: bytes,
        connect_timeout_ms: int
    ) -> Any: ...
    def tft_lease_client_destroy(self, handle: Any) -> None: ...
    def tft_lease_client_renew(
        self,
        handle: Any,
        entries_json: bytes,
        timeout_ms: int,
        quorum_id_out: Any
    ) -> int: ...
    def tft_lease_client_heartbeat(
        self,
        handle: Any,
        replica_id: bytes,
        timeout_ms: int
    ) -> int: ...
    def tft_lease_client_depart(
        self,
        handle: Any,
        replica_id: bytes,
        timeout_ms: int
    ) -> int: ...
    def tft_manager_create(
        self,
        replica_id: bytes,
        lighthouse_addr: bytes,
        hostname: bytes,
        bind: bytes,
        store_addr: bytes,
        world_size: int,
        heartbeat_interval_ms: int,
        connect_timeout_ms: int,
        root_addr: bytes,
        lease_ttl_ms: int,
        region: bytes,
        host: bytes,
        region_probe_max: int
    ) -> Any: ...
    def tft_manager_address(self, handle: Any) -> Any: ...
    def tft_manager_shutdown(self, handle: Any) -> None: ...
    def tft_manager_destroy(self, handle: Any) -> None: ...
    def tft_manager_using_root(self, handle: Any) -> int: ...
    def tft_manager_probe_given_up(self, handle: Any) -> int: ...
    def tft_manager_set_status(self, handle: Any, status_json: Any) -> int: ...
    def tft_wal_open(self, dir: bytes, snapshot_every: int) -> Any: ...
    def tft_wal_close(self, handle: Any) -> None: ...
    def tft_wal_log_lease(
        self,
        handle: Any,
        entries_json: bytes,
        unix_ms: int
    ) -> int: ...
    def tft_wal_log_depart(self, handle: Any, replica_id: bytes) -> int: ...
    def tft_wal_log_quorum(
        self,
        handle: Any,
        quorum_json: bytes,
        quorum_gen: int,
        root_epoch: int
    ) -> int: ...
    def tft_wal_log_epoch(self, handle: Any, epoch: int) -> int: ...
    def tft_wal_snapshot(
        self,
        handle: Any,
        state_json: bytes,
        quorum_gen: int,
        root_epoch: int,
        mono_now: int,
        unix_now: int
    ) -> int: ...
    def tft_wal_recover(
        self,
        dir: bytes,
        mono_now: int,
        unix_now: int,
        out: Any
    ) -> int: ...
    def tft_client_create(
        self,
        addr: bytes,
        connect_timeout_ms: int
    ) -> Any: ...
    def tft_client_destroy(self, handle: Any) -> None: ...
    def tft_client_quorum(
        self,
        handle: Any,
        rank: int,
        step: int,
        checkpoint_metadata: bytes,
        shrink_only: int,
        force_reconfigure: int,
        timeout_ms: int,
        result_json: Any
    ) -> int: ...
    def tft_client_checkpoint_metadata(
        self,
        handle: Any,
        rank: int,
        timeout_ms: int,
        metadata_out: Any
    ) -> int: ...
    def tft_client_should_commit(
        self,
        handle: Any,
        rank: int,
        step: int,
        should_commit: int,
        timeout_ms: int,
        result: Any
    ) -> int: ...
    def tft_client_kill(self, handle: Any, msg: bytes) -> int: ...
    def tft_store_create(self, bind: bytes) -> Any: ...
    def tft_store_address(self, handle: Any) -> Any: ...
    def tft_store_port(self, handle: Any) -> int: ...
    def tft_store_shutdown(self, handle: Any) -> None: ...
    def tft_store_destroy(self, handle: Any) -> None: ...
    def tft_store_client_create(
        self,
        addr: bytes,
        connect_timeout_ms: int
    ) -> Any: ...
    def tft_store_client_destroy(self, handle: Any) -> None: ...
    def tft_store_client_set(
        self,
        handle: Any,
        key: bytes,
        value: bytes,
        value_len: int,
        timeout_ms: int
    ) -> int: ...
    def tft_store_client_get(
        self,
        handle: Any,
        key: bytes,
        timeout_ms: int,
        value_out: Any,
        value_len_out: Any
    ) -> int: ...
    def tft_store_client_add(
        self,
        handle: Any,
        key: bytes,
        delta: int,
        timeout_ms: int,
        value_out: Any
    ) -> int: ...
    def tft_hc_create(self) -> Any: ...
    def tft_hc_destroy(self, handle: Any) -> None: ...
    def tft_hc_configure(
        self,
        handle: Any,
        store_addr: bytes,
        rank: int,
        world_size: int,
        timeout_ms: int,
        stripes: int
    ) -> int: ...
    def tft_hc_configure_hier(
        self,
        handle: Any,
        store_addr: bytes,
        rank: int,
        world_size: int,
        timeout_ms: int,
        stripes: int,
        stripes_inter: int,
        regions_json: bytes,
        hosts_json: bytes
    ) -> int: ...
    def tft_hc_hier_capable(self, handle: Any) -> int: ...
    def tft_hc_host_tier_transport(self, handle: Any) -> int: ...
    def tft_hc_release(self, handle: Any) -> int: ...
    def tft_hc_allreduce_hier(
        self,
        handle: Any,
        data: Any,
        count: int,
        dtype: int,
        op: int,
        wire: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_last_hier_json(self, handle: Any, out: Any) -> int: ...
    def tft_hc_allreduce(
        self,
        handle: Any,
        data: Any,
        count: int,
        dtype: int,
        op: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_allreduce_q8(
        self,
        handle: Any,
        data: Any,
        count: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_reduce_scatter(
        self,
        handle: Any,
        data: Any,
        count: int,
        dtype: int,
        op: int,
        shard_out: Any,
        layout_stripes: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_reduce_scatter_q8(
        self,
        handle: Any,
        data: Any,
        count: int,
        shard_out: Any,
        grid_shard: int,
        layout_stripes: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_allgather_into(
        self,
        handle: Any,
        shard: Any,
        data: Any,
        count: int,
        dtype: int,
        layout_stripes: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_shard_ranges(
        self,
        handle: Any,
        count: int,
        esize: int,
        rank: int,
        layout_stripes: int,
        out: Any,
        cap: int
    ) -> int: ...
    def tft_plan_build(
        self,
        handle: Any,
        counts: Any,
        dtypes: Any,
        n_leaves: int,
        wire: int
    ) -> int: ...
    def tft_plan_execute(
        self,
        handle: Any,
        plan_id: int,
        leaf_in: Any,
        leaf_out: Any,
        divisor: float,
        has_divisor: int,
        timeout_ms: int
    ) -> int: ...
    def tft_plan_build_pre(
        self,
        handle: Any,
        counts: Any,
        dtypes: Any,
        n_leaves: int,
        wire: int
    ) -> int: ...
    def tft_plan_build_hier(
        self,
        handle: Any,
        counts: Any,
        dtypes: Any,
        n_leaves: int,
        wire: int
    ) -> int: ...
    def tft_plan_execute_pre(
        self,
        handle: Any,
        plan_id: int,
        group_in: Any,
        group_aux: Any,
        leaf_out: Any,
        divisor: float,
        has_divisor: int,
        timeout_ms: int
    ) -> int: ...
    def tft_plan_build_sharded(
        self,
        handle: Any,
        counts: Any,
        dtypes: Any,
        n_leaves: int,
        rs_wire: int,
        ag_wire: int
    ) -> int: ...
    def tft_plan_execute_rs(
        self,
        handle: Any,
        plan_id: int,
        leaf_in: Any,
        shard_out: Any,
        divisor: float,
        has_divisor: int,
        timeout_ms: int
    ) -> int: ...
    def tft_plan_execute_ag(
        self,
        handle: Any,
        plan_id: int,
        shard_in: Any,
        leaf_out: Any,
        timeout_ms: int
    ) -> int: ...
    def tft_plan_sharded_meta(
        self,
        handle: Any,
        plan_id: int,
        out3: Any
    ) -> int: ...
    def tft_plan_free(self, handle: Any, plan_id: int) -> int: ...
    def tft_plan_reset_feedback(self, handle: Any, plan_id: int) -> int: ...
    def tft_plan_stats_json(
        self,
        handle: Any,
        plan_id: int,
        out: Any
    ) -> int: ...
    def tft_shm_create(self, name: bytes, nbytes: int) -> Any: ...
    def tft_shm_attach(self, name: bytes, nbytes: int) -> Any: ...
    def tft_shm_data(self, handle: Any) -> int: ...
    def tft_shm_size(self, handle: Any) -> int: ...
    def tft_shm_close(self, handle: Any) -> None: ...
    def tft_shm_unlink(self, name: bytes) -> int: ...
    def tft_shm_live_count(self) -> int: ...
    def tft_shm_layout_json(
        self,
        counts: Any,
        dtypes: Any,
        n_leaves: int,
        wire: int,
        out: Any
    ) -> int: ...
    def tft_hc_allgather(
        self,
        handle: Any,
        in_: Any,
        out: Any,
        nbytes: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_broadcast(
        self,
        handle: Any,
        data: Any,
        nbytes: int,
        root: int,
        timeout_ms: int
    ) -> int: ...
    def tft_hc_barrier(self, handle: Any, timeout_ms: int) -> int: ...
    def tft_hc_abort(self, handle: Any) -> None: ...
    def tft_hc_set_wire_crc(self, handle: Any, on: int) -> None: ...
    def tft_hc_wire_crc(self, handle: Any) -> int: ...
    def tft_fault_arm(self, plan_json: bytes) -> int: ...
    def tft_fault_disarm(self) -> None: ...
    def tft_fault_armed(self) -> int: ...
    def tft_fault_stats_json(self, out: Any) -> int: ...
    def tft_crc32c(self, data: bytes, len: int) -> int: ...
    def tft_crc32c_update(self, state: int, data: bytes, len: int) -> int: ...
    def tft_hc_world_size(self, handle: Any) -> int: ...
    def tft_hc_stripes(self, handle: Any) -> int: ...
    def tft_hc_last_stripe_ns(
        self,
        handle: Any,
        out: Any,
        cap: int
    ) -> int: ...
    def tft_quorum_compute(
        self,
        now: int,
        state_json: bytes,
        opt_json: bytes,
        result_json: Any
    ) -> int: ...
    def tft_compute_quorum_results(
        self,
        replica_id: bytes,
        rank: int,
        quorum_json: bytes,
        result_json: Any
    ) -> int: ...
    def tft_quorum_step(
        self,
        now: int,
        unix_now: int,
        state_json: bytes,
        opt_json: bytes,
        result_json: Any
    ) -> int: ...
    def tft_lease_apply(
        self,
        state_json: bytes,
        entries_json: bytes,
        now: int,
        result_json: Any
    ) -> int: ...
    def tft_depart_apply(
        self,
        state_json: bytes,
        replica_id: bytes,
        result_json: Any
    ) -> int: ...
    def tft_digest_make(
        self,
        state_json: bytes,
        now: int,
        opt_json: bytes,
        result_json: Any
    ) -> int: ...
    def tft_digest_apply(
        self,
        state_json: bytes,
        digest_json: bytes,
        now: int,
        result_json: Any
    ) -> int: ...
    def tft_backoff_ms(
        self,
        failures: int,
        base_ms: int,
        max_ms: int,
        seed: int
    ) -> int: ...
    def tft_jittered_interval_ms(
        self,
        interval_ms: int,
        seed: int,
        tick: int
    ) -> int: ...


def quorum_compute(now_ms: int, state: dict, opt: dict) -> dict: ...


def compute_quorum_results(
    replica_id: str, rank: int, quorum: dict
) -> QuorumResult: ...


def quorum_step(
    now_ms: int, unix_now_ms: int, state: dict, opt: dict
) -> dict: ...


def lease_apply(state: dict, entries: list, now_ms: int) -> dict: ...


def depart_apply(state: dict, replica_id: str) -> dict: ...


def digest_make(state: dict, now_ms: int, opt: dict) -> list: ...


def digest_apply(state: dict, digest: list, now_ms: int) -> dict: ...


def backoff_ms(failures: int, base_ms: int, max_ms: int, seed: int) -> int: ...


def jittered_interval_ms(interval_ms: int, seed: int, tick: int) -> int: ...


class ShmSegment:
    name: str

    def __init__(self, name: str, nbytes: int, create: bool) -> None: ...
    @classmethod
    def create(cls, name: str, nbytes: int) -> "ShmSegment": ...
    @classmethod
    def attach(cls, name: str, nbytes: int) -> "ShmSegment": ...
    def buffer(self) -> memoryview: ...
    @property
    def nbytes(self) -> int: ...
    def close(self) -> None: ...


def shm_unlink(name: str) -> None: ...


def shm_live_count() -> int: ...


def shm_layout(
    counts: List[int], dtype_codes: List[int], wire: int = 0
) -> dict: ...


class WireCorruption(RuntimeError):
    """A CRC-guarded wire frame (ring payload frame / heal stream range)
    failed its integrity check; rides the managed latch -> vote-discard
    machinery like any data-plane error, but typed so detections can be
    counted."""


def fault_arm(plan: dict) -> None: ...


def fault_disarm() -> None: ...


def fault_armed() -> bool: ...


def fault_stats() -> dict: ...


def crc32c(data: Union[bytes, bytearray, memoryview]) -> int: ...


def crc32c_update(
    state: int, data: Union[bytes, bytearray, memoryview]
) -> int: ...


def crc32c_combine(
    parts: List[Union[bytes, bytearray, memoryview]]
) -> int: ...
