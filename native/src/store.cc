#include "store.h"

#include <sys/socket.h>

#include <functional>

#include "wire.h"

namespace tft {

using torchft_tpu::ErrorResponse;

StoreServer::StoreServer(const std::string& bind_addr)
    : listener_(std::make_unique<Listener>(bind_addr)),
      hostname_(local_hostname()) {
  accept_thread_ = std::thread([this] { serve(); });
}

StoreServer::~StoreServer() { shutdown(); }

uint16_t StoreServer::port() const { return listener_->port(); }

std::string StoreServer::address() const {
  return hostname_ + ":" + std::to_string(listener_->port());
}

void StoreServer::shutdown() {
  {
    // Flag + notify under the cv's mutex so waiters can't miss the wakeup.
    MutexLock lock(mu_);
    if (shutting_down_.exchange(true)) return;
    cv_.notify_all();
  }
  listener_->close();
  if (accept_thread_.joinable()) accept_thread_.join();
  conns_.shutdown_all();
}

void StoreServer::serve() {
  while (true) {
    Socket sock = listener_->accept();
    if (!sock.valid()) return; // shut down
    conns_.spawn(std::move(sock), [this](Socket& s) { handle_conn(s); });
  }
}

void StoreServer::handle_conn(Socket& sock) {
  try {
    while (true) {
      auto [type, payload] = recv_frame(sock);
      switch (type) {
        case MsgType::kStoreSetReq: {
          torchft_tpu::StoreSetRequest req;
          req.ParseFromString(payload);
          {
            MutexLock lock(mu_);
            data_[req.key()] = req.value();
          }
          cv_.notify_all();
          send_msg(sock, MsgType::kStoreSetResp, torchft_tpu::StoreSetResponse());
          break;
        }
        case MsgType::kStoreGetReq: {
          torchft_tpu::StoreGetRequest req;
          req.ParseFromString(payload);
          int64_t deadline =
              req.timeout_ms() < 0 ? -1 : now_ms() + req.timeout_ms();
          UniqueMutexLock lock(mu_);
          bool timed_out = false;
          while (!data_.count(req.key()) && !shutting_down_) {
            if (deadline < 0) {
              cv_.wait(lock);
            } else {
              int64_t remain = deadline - now_ms();
              if (remain <= 0) {
                timed_out = true;
                break;
              }
              cv_.wait_for(lock, std::chrono::milliseconds(remain));
            }
          }
          if (!data_.count(req.key())) {
            bool cancelled = shutting_down_ && !timed_out;
            lock.unlock();
            if (cancelled) {
              send_error(sock, ErrorResponse::CANCELLED, "store shutting down");
            } else {
              send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                         "timed out waiting for key " + req.key());
            }
            break;
          }
          torchft_tpu::StoreGetResponse resp;
          resp.set_value(data_[req.key()]);
          lock.unlock();
          send_msg(sock, MsgType::kStoreGetResp, resp);
          break;
        }
        case MsgType::kStoreAddReq: {
          torchft_tpu::StoreAddRequest req;
          req.ParseFromString(payload);
          int64_t value;
          {
            UniqueMutexLock lock(mu_);
            std::string& cur = data_[req.key()];
            int64_t v = 0;
            if (!cur.empty()) {
              try {
                v = std::stoll(cur);
              } catch (const std::exception&) {
                lock.unlock();
                send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                           "add on non-numeric key " + req.key());
                break;
              }
            }
            v += req.delta();
            cur = std::to_string(v);
            value = v;
          }
          cv_.notify_all();
          torchft_tpu::StoreAddResponse resp;
          resp.set_value(value);
          send_msg(sock, MsgType::kStoreAddResp, resp);
          break;
        }
        default:
          send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad store request");
          return;
      }
    }
  } catch (const std::exception&) {
    // connection closed or reset; drop it
  }
}

StoreClient::StoreClient(const std::string& addr, int64_t connect_timeout_ms)
    : pool_(addr, connect_timeout_ms) {
  // Fail fast on an unreachable store, like the reference's TCPStore client.
  pool_.release(connect_with_retry(addr, connect_timeout_ms));
}

// One request/response on a pooled connection. A SocketError before the
// request was fully sent triggers one reconnect+resend (store ops are
// idempotent); a desynchronized connection — client-side timeout with the
// response still in flight, or a mid-response socket error — is dropped
// instead of returned to the pool.
template <typename Req, typename Resp>
Resp StoreClient::roundtrip(uint8_t req_type, const Req& req, uint8_t resp_type,
                            int64_t timeout_ms) {
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  Socket sock = pool_.acquire();
  try {
    try {
      send_msg(sock, static_cast<MsgType>(req_type), req, deadline);
    } catch (const SocketError&) {
      sock = connect_with_retry(pool_.addr(), pool_.connect_timeout_ms());
      send_msg(sock, static_cast<MsgType>(req_type), req, deadline);
    }
    Resp resp = recv_expect<Resp>(sock, static_cast<MsgType>(resp_type), deadline);
    pool_.release(std::move(sock));
    return resp;
  } catch (const RpcError&) {
    // Error frame fully consumed: the connection is still in sync.
    pool_.release(std::move(sock));
    throw;
  }
  // TimeoutError / SocketError: sock destructs here, dropping the connection.
}

void StoreClient::set(const std::string& key, const std::string& value,
                      int64_t timeout_ms) {
  torchft_tpu::StoreSetRequest req;
  req.set_key(key);
  req.set_value(value);
  roundtrip<torchft_tpu::StoreSetRequest, torchft_tpu::StoreSetResponse>(
      static_cast<uint8_t>(MsgType::kStoreSetReq), req,
      static_cast<uint8_t>(MsgType::kStoreSetResp), timeout_ms);
}

std::string StoreClient::get(const std::string& key, int64_t timeout_ms) {
  torchft_tpu::StoreGetRequest req;
  req.set_key(key);
  req.set_timeout_ms(timeout_ms);
  return roundtrip<torchft_tpu::StoreGetRequest, torchft_tpu::StoreGetResponse>(
             static_cast<uint8_t>(MsgType::kStoreGetReq), req,
             static_cast<uint8_t>(MsgType::kStoreGetResp), timeout_ms)
      .value();
}

int64_t StoreClient::add(const std::string& key, int64_t delta, int64_t timeout_ms) {
  torchft_tpu::StoreAddRequest req;
  req.set_key(key);
  req.set_delta(delta);
  return roundtrip<torchft_tpu::StoreAddRequest, torchft_tpu::StoreAddResponse>(
             static_cast<uint8_t>(MsgType::kStoreAddReq), req,
             static_cast<uint8_t>(MsgType::kStoreAddResp), timeout_ms)
      .value();
}

} // namespace tft
