# graftlint fixture: ctypes declarations drifted from bad_capi.cc.
import ctypes


def _load_lib(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.tft_fix_ok.restype = ctypes.c_int
    lib.tft_fix_ok.argtypes = [ctypes.c_void_p, ctypes.c_int64]

    # Wrong length: C side takes 3 parameters.
    lib.tft_fix_argcount.restype = ctypes.c_int
    lib.tft_fix_argcount.argtypes = [ctypes.c_void_p] * 2

    # Missing restype for an int64_t return.
    lib.tft_fix_ret64.argtypes = [ctypes.c_void_p]

    # tft_fix_undeclared: intentionally absent.

    lib.tft_fix_unstubbed.restype = ctypes.c_int
    lib.tft_fix_unstubbed.argtypes = [ctypes.c_void_p]

    # Stale: not exported by bad_capi.cc.
    # shm drift: void* return declared without restype (pointer mangled).
    lib.tft_shm_fix_noresty.argtypes = [ctypes.c_char_p, ctypes.c_int64]

    lib.tft_fix_stale.restype = ctypes.c_int
    lib.tft_fix_stale.argtypes = [ctypes.c_void_p]
    return lib
