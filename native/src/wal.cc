#include "wal.h"

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <fstream>
#include <sstream>

#include "fault.h"
#include "log.h"

namespace tft {

namespace {

constexpr uint8_t kRecEpoch = 1;
constexpr uint8_t kRecLease = 2;
constexpr uint8_t kRecDepart = 3;
constexpr uint8_t kRecQuorum = 4;
constexpr int64_t kDefaultSnapshotEvery = 512;
// A record bigger than this is not a record — it is a corrupt length
// word, and trusting it would make recovery read garbage as payload.
constexpr uint32_t kMaxRecordBytes = 16u << 20;

std::string wal_path(const std::string& dir) { return dir + "/wal.log"; }
std::string snap_path(const std::string& dir) { return dir + "/snapshot.json"; }

void put_u32(std::string& out, uint32_t v) {
  out.push_back(static_cast<char>((v >> 24) & 0xff));
  out.push_back(static_cast<char>((v >> 16) & 0xff));
  out.push_back(static_cast<char>((v >> 8) & 0xff));
  out.push_back(static_cast<char>(v & 0xff));
}

uint32_t get_u32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

void mkdirs(const std::string& dir) {
  std::string partial;
  for (size_t i = 0; i <= dir.size(); i++) {
    if (i == dir.size() || dir[i] == '/') {
      if (!partial.empty() && partial != "/") {
        if (mkdir(partial.c_str(), 0777) != 0 && errno != EEXIST) {
          throw std::runtime_error("mkdir " + partial + ": " +
                                   std::strerror(errno));
        }
      }
    }
    if (i < dir.size()) partial.push_back(dir[i]);
  }
}

// unix -> this process's monotonic clock. Can go negative for times
// before process start; every consumer compares differences, so that is
// fine.
int64_t rebase(int64_t unix_when, int64_t mono_now, int64_t unix_now) {
  return mono_now - (unix_now - unix_when);
}

// Durably journals a directory's entry table (the rename/create itself,
// not just file contents): without this, a power loss can surface the
// OLD directory state with NEW file contents — e.g. the pre-compaction
// snapshot next to an already-truncated log, which would regress the
// watermark the WAL exists to protect. Best-effort where the filesystem
// refuses (fsync on a directory fd is EINVAL on some sandboxes).
void fsync_dir(const std::string& dir) {
  int dfd = ::open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd < 0) return;
  ::fsync(dfd);
  ::close(dfd);
}

} // namespace

std::vector<WalLeaseEntry> wal_entries_from_state(
    const LighthouseState& state, const std::vector<std::string>& ids,
    int64_t mono_now) {
  std::vector<WalLeaseEntry> out;
  out.reserve(ids.size());
  for (const auto& id : ids) {
    auto hb = state.heartbeats.find(id);
    if (hb == state.heartbeats.end()) continue;  // departed mid-batch
    WalLeaseEntry e;
    e.replica_id = id;
    e.age_ms = mono_now - hb->second;
    auto ttl = state.lease_ttls.find(id);
    e.ttl_ms = ttl == state.lease_ttls.end() ? 0 : ttl->second;
    auto p = state.participants.find(id);
    if (p != state.participants.end()) {
      e.participating = true;
      e.joined_age_ms = mono_now - p->second.joined_ms;
      e.member = p->second.member;
    }
    out.push_back(std::move(e));
  }
  return out;
}

Json wal_lease_entries_to_json(const std::vector<WalLeaseEntry>& entries) {
  JsonArray arr;
  for (const auto& e : entries) {
    JsonObject o;
    o["replica_id"] = e.replica_id;
    o["age_ms"] = e.age_ms;
    o["ttl_ms"] = e.ttl_ms;
    o["participating"] = e.participating;
    if (e.participating) {
      o["joined_age_ms"] = e.joined_age_ms;
      o["member"] = member_to_json(e.member);
    }
    arr.push_back(Json(std::move(o)));
  }
  return Json(std::move(arr));
}

std::vector<WalLeaseEntry> wal_lease_entries_from_json(const Json& j) {
  std::vector<WalLeaseEntry> out;
  for (const auto& ej : j.as_array()) {
    WalLeaseEntry e;
    e.replica_id = ej.get_string("replica_id", "");
    e.age_ms = ej.get_int("age_ms", 0);
    e.ttl_ms = ej.get_int("ttl_ms", 0);
    e.participating = ej.get_bool("participating", false);
    e.joined_age_ms = ej.get_int("joined_age_ms", 0);
    const Json& m = ej.at("member");
    if (!m.is_null()) e.member = member_from_json(m);
    out.push_back(std::move(e));
  }
  return out;
}

DurableLog::DurableLog(const std::string& dir, int64_t snapshot_every)
    : dir_(dir),
      snapshot_every_(snapshot_every > 0 ? snapshot_every
                                         : kDefaultSnapshotEvery) {
  mkdirs(dir_);
  MutexLock lock(mu_);
  fd_ = ::open(wal_path(dir_).c_str(), O_CREAT | O_WRONLY | O_APPEND, 0666);
  if (fd_ < 0) {
    throw std::runtime_error("open " + wal_path(dir_) + ": " +
                             std::strerror(errno));
  }
  // The log FILE's existence must survive a power loss too.
  fsync_dir(dir_);
}

DurableLog::~DurableLog() {
  MutexLock lock(mu_);
  if (fd_ >= 0) ::close(fd_);
  fd_ = -1;
}

bool DurableLog::dead() {
  MutexLock lock(mu_);
  return dead_;
}

int64_t DurableLog::records_appended() {
  MutexLock lock(mu_);
  return records_;
}

int64_t DurableLog::snapshots_written() {
  MutexLock lock(mu_);
  return snapshots_;
}

void DurableLog::append_locked(uint8_t type, const std::string& payload,
                               bool sync) {
  if (dead_) throw WalTornError("log dead after a previous torn write");
  if (fd_ < 0) throw WalTornError("log closed");
  std::string frame;
  frame.reserve(payload.size() + 9);
  put_u32(frame, static_cast<uint32_t>(payload.size() + 1));
  std::string body;
  body.reserve(payload.size() + 1);
  body.push_back(static_cast<char>(type));
  body += payload;
  put_u32(frame, fault::crc32c(body.data(), body.size()));
  frame += body;

  fault::Decision fd = TFT_FAULT_CHECK(fault::kSeamWalWrite, -1, op_seq_++);
  if (fd.kind == fault::kDelay) {
    struct timespec ts;
    int64_t ms = fd.param > 0 ? fd.param : 50;
    ts.tv_sec = ms / 1000;
    ts.tv_nsec = (ms % 1000) * 1000000;
    nanosleep(&ts, nullptr);
  } else if (fd.kind == fault::kTruncate || fd.kind == fault::kDrop) {
    // The crash-mid-append faults: `truncate` leaves half a record on
    // disk (torn tail, dropped at recovery), `drop` crashes before any
    // byte lands. Either way the log is DEAD — the process would be too.
    if (fd.kind == fault::kTruncate) {
      size_t half = frame.size() / 2;
      ssize_t ignored = ::write(fd_, frame.data(), half);
      (void)ignored;
      ::fsync(fd_);
    }
    dead_ = true;
    throw WalTornError("injected crash mid-append (wal_write seam)");
  }

  const char* p = frame.data();
  size_t left = frame.size();
  while (left > 0) {
    ssize_t n = ::write(fd_, p, left);
    if (n < 0) {
      if (errno == EINTR) continue;
      dead_ = true;
      throw WalTornError(std::string("write: ") + std::strerror(errno));
    }
    p += n;
    left -= static_cast<size_t>(n);
  }
  if (sync) ::fsync(fd_);
  records_ += 1;
  since_snapshot_ += 1;
}

void DurableLog::log_epoch(int64_t epoch) {
  JsonObject o;
  o["epoch"] = epoch;
  MutexLock lock(mu_);
  append_locked(kRecEpoch, Json(std::move(o)).dump(), /*sync=*/true);
}

void DurableLog::log_lease(const std::vector<WalLeaseEntry>& entries,
                           int64_t unix_now) {
  if (entries.empty()) return;
  JsonObject o;
  o["unix_ms"] = unix_now;
  o["entries"] = wal_lease_entries_to_json(entries);
  MutexLock lock(mu_);
  append_locked(kRecLease, Json(std::move(o)).dump(), /*sync=*/false);
}

void DurableLog::log_depart(const std::string& replica_id) {
  JsonObject o;
  o["replica_id"] = replica_id;
  MutexLock lock(mu_);
  append_locked(kRecDepart, Json(std::move(o)).dump(), /*sync=*/true);
}

void DurableLog::log_quorum(const torchft_tpu::Quorum& quorum,
                            int64_t quorum_gen, int64_t root_epoch) {
  JsonObject o;
  o["gen"] = quorum_gen;
  o["epoch"] = root_epoch;
  o["quorum"] = quorum_to_json(quorum);
  MutexLock lock(mu_);
  append_locked(kRecQuorum, Json(std::move(o)).dump(), /*sync=*/true);
}

void DurableLog::snapshot(const LighthouseState& state, int64_t quorum_gen,
                          int64_t root_epoch, int64_t mono_now,
                          int64_t unix_now) {
  JsonObject o;
  o["unix_ms"] = unix_now;
  o["quorum_gen"] = quorum_gen;
  o["root_epoch"] = root_epoch;
  o["quorum_id"] = state.quorum_id;
  JsonObject hb;
  for (const auto& [id, last] : state.heartbeats)
    hb[id] = unix_now - (mono_now - last);
  o["heartbeats_unix"] = Json(std::move(hb));
  JsonObject ttls;
  for (const auto& [id, ttl] : state.lease_ttls) ttls[id] = ttl;
  o["lease_ttls"] = Json(std::move(ttls));
  JsonObject parts;
  for (const auto& [id, d] : state.participants) {
    JsonObject pj;
    pj["joined_unix"] = unix_now - (mono_now - d.joined_ms);
    pj["member"] = member_to_json(d.member);
    parts[id] = Json(std::move(pj));
  }
  o["participants"] = Json(std::move(parts));
  if (state.prev_quorum.has_value()) {
    o["prev_quorum"] = quorum_to_json(*state.prev_quorum);
  } else {
    o["prev_quorum"] = Json();
  }
  std::string body = Json(std::move(o)).dump();

  MutexLock lock(mu_);
  if (dead_) throw WalTornError("log dead after a previous torn write");
  std::string tmp = snap_path(dir_) + ".tmp";
  int sfd = ::open(tmp.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0666);
  if (sfd < 0)
    throw std::runtime_error("open " + tmp + ": " + std::strerror(errno));
  const char* p = body.data();
  size_t left = body.size();
  bool ok = true;
  while (left > 0) {
    ssize_t n = ::write(sfd, p, left);
    if (n < 0) {
      if (errno == EINTR) continue;
      ok = false;
      break;
    }
    p += n;
    left -= static_cast<size_t>(n);
  }
  ::fsync(sfd);
  ::close(sfd);
  if (!ok || ::rename(tmp.c_str(), snap_path(dir_).c_str()) != 0) {
    throw std::runtime_error("snapshot write failed: " +
                             std::string(std::strerror(errno)));
  }
  // The rename must be ON DISK before the log shrinks: a power loss
  // that persisted the truncate but not the directory entry would pair
  // the OLD snapshot with an EMPTY log — a regressed watermark. (A
  // process crash can't reorder these; only the storage stack can.)
  fsync_dir(dir_);
  // Truncate AFTER the rename: a crash between the two replays the
  // pre-snapshot records over the snapshot, which every record's
  // idempotent/monotone application absorbs.
  if (::ftruncate(fd_, 0) != 0) {
    throw std::runtime_error("wal truncate failed: " +
                             std::string(std::strerror(errno)));
  }
  since_snapshot_ = 0;
  snapshots_ += 1;
}

void DurableLog::maybe_snapshot(const LighthouseState& state,
                                int64_t quorum_gen, int64_t root_epoch,
                                int64_t mono_now, int64_t unix_now) {
  {
    MutexLock lock(mu_);
    if (since_snapshot_ < snapshot_every_) return;
  }
  snapshot(state, quorum_gen, root_epoch, mono_now, unix_now);
}

WalRecovery DurableLog::recover(const std::string& dir, int64_t mono_now,
                                int64_t unix_now) {
  WalRecovery out;

  // 1. Snapshot (if present and parseable; a half-written .tmp never
  //    carries the canonical name, so a parse failure here means real
  //    corruption — start from the log alone rather than refuse).
  {
    std::ifstream f(snap_path(dir), std::ios::binary);
    if (f) {
      std::stringstream ss;
      ss << f.rdbuf();
      try {
        Json j = Json::parse(ss.str());
        out.quorum_gen = j.get_int("quorum_gen", 0);
        out.root_epoch = j.get_int("root_epoch", 0);
        out.state.quorum_id = j.get_int("quorum_id", 0);
        const Json& hb = j.at("heartbeats_unix");
        if (!hb.is_null()) {
          for (const auto& [id, u] : hb.as_object())
            out.state.heartbeats[id] = rebase(u.as_int(), mono_now, unix_now);
        }
        const Json& ttls = j.at("lease_ttls");
        if (!ttls.is_null()) {
          for (const auto& [id, ttl] : ttls.as_object())
            out.state.lease_ttls[id] = ttl.as_int();
        }
        const Json& parts = j.at("participants");
        if (!parts.is_null()) {
          for (const auto& [id, pj] : parts.as_object()) {
            ParticipantDetails d;
            d.joined_ms =
                rebase(pj.get_int("joined_unix", unix_now), mono_now, unix_now);
            d.member = member_from_json(pj.at("member"));
            out.state.participants[id] = std::move(d);
          }
        }
        const Json& prev = j.at("prev_quorum");
        if (!prev.is_null()) out.state.prev_quorum = quorum_from_json(prev);
        out.replayed = true;
      } catch (const std::exception& e) {
        LOG_WARN("wal snapshot unreadable (" << e.what()
                                             << "); recovering from log only");
      }
    }
  }

  // 2. Log records, stopping at the first torn/corrupt frame.
  std::ifstream f(wal_path(dir), std::ios::binary);
  if (!f) return out;
  std::stringstream ss;
  ss << f.rdbuf();
  std::string raw = ss.str();
  size_t pos = 0;
  while (pos + 8 <= raw.size()) {
    const unsigned char* base =
        reinterpret_cast<const unsigned char*>(raw.data()) + pos;
    uint32_t len = get_u32(base);
    uint32_t crc = get_u32(base + 4);
    if (len == 0 || len > kMaxRecordBytes || pos + 8 + len > raw.size()) break;
    if (fault::crc32c(raw.data() + pos + 8, len) != crc) break;
    uint8_t type = static_cast<uint8_t>(raw[pos + 8]);
    std::string payload = raw.substr(pos + 9, len - 1);
    try {
      Json j = Json::parse(payload);
      switch (type) {
        case kRecEpoch:
          out.root_epoch = std::max(out.root_epoch, j.get_int("epoch", 0));
          break;
        case kRecLease: {
          int64_t rec_unix = j.get_int("unix_ms", unix_now);
          for (const auto& e : wal_lease_entries_from_json(j.at("entries"))) {
            if (e.replica_id.empty()) continue;
            int64_t hb = rebase(rec_unix - e.age_ms, mono_now, unix_now);
            auto it = out.state.heartbeats.find(e.replica_id);
            // Monotone merge: liveness only ever moves forward, so a
            // pre-snapshot record replayed over the snapshot (the
            // crash-between-rename-and-truncate window) is a no-op.
            if (it == out.state.heartbeats.end() || it->second < hb)
              out.state.heartbeats[e.replica_id] = hb;
            if (e.ttl_ms > 0) {
              out.state.lease_ttls[e.replica_id] = e.ttl_ms;
            } else {
              out.state.lease_ttls.erase(e.replica_id);
            }
            if (e.participating) {
              out.state.participants[e.replica_id] = ParticipantDetails{
                  rebase(rec_unix - e.joined_age_ms, mono_now, unix_now),
                  e.member};
            }
          }
          break;
        }
        case kRecDepart:
          apply_depart(out.state, j.get_string("replica_id", ""));
          break;
        case kRecQuorum: {
          torchft_tpu::Quorum q = quorum_from_json(j.at("quorum"));
          if (q.quorum_id() >= out.state.quorum_id) {
            out.state.quorum_id = q.quorum_id();
            out.state.prev_quorum = q;
            // Mirror quorum_step's formation protocol: registrations were
            // consumed by this quorum; later lease records re-register.
            out.state.participants.clear();
          }
          out.quorum_gen = std::max(out.quorum_gen, j.get_int("gen", 0));
          out.root_epoch = std::max(out.root_epoch, j.get_int("epoch", 0));
          break;
        }
        default:
          break;  // future record type: skip (CRC already vouched for it)
      }
      out.records_replayed += 1;
      out.replayed = true;
    } catch (const std::exception& e) {
      // CRC passed but the payload didn't parse: treat as corruption at
      // this point and stop, same as a torn tail.
      LOG_WARN("wal record " << out.records_replayed
                             << " unparseable: " << e.what());
      break;
    }
    pos += 8 + len;
  }
  out.dropped_tail_bytes = static_cast<int64_t>(raw.size() - pos);
  if (out.dropped_tail_bytes > 0) {
    LOG_WARN("wal: dropped " << out.dropped_tail_bytes
                             << " torn tail byte(s) at offset " << pos);
  }
  return out;
}

} // namespace tft
