"""Fault-tolerant LocalSGD and DiLoCo: communication-efficient data
parallelism across replica groups.

Reference: torchft/local_sgd.py. Inner steps run purely locally (no
cross-group traffic); every ``sync_every`` steps the groups synchronize
through the manager — a quorum + fault-tolerant allreduce + commit vote. On
a failed commit the whole window is discarded and parameters reset to the
last synchronized state, preserving exactly-``sync_every`` semantics
(reference local_sgd.py:35-46).

JAX shape: the reference hooks ``optimizer.step``; here the train loop calls
``local_sgd.step(grads)`` explicitly (optax has no hooks), which applies the
inner update and triggers ``sync()`` on the window boundary. The backup copy
lives on HOST (the reference's CPU backup, local_sgd.py:81-91) — one
device→host snapshot per window, not per step.

DiLoCo (https://arxiv.org/pdf/2311.08105): inner optimizer steps locally;
at the window boundary the *pseudogradient* Δ = θ_global_old − θ_local_new
is averaged across groups and fed to an outer optimizer (typically SGD with
Nesterov momentum) on the restored global params. Note the sign: this
follows the paper; the reference snapshot computes ``p.data - backup``
(local_sgd.py:214), the negation (fixed upstream later).
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from .collectives import ReduceOp
from .manager import Manager
from .train_state import FTTrainState, _to_device_tree

logger: logging.Logger = logging.getLogger(__name__)


def _to_host_copy(tree: Any) -> Any:
    """Detached host (numpy) copy of every array leaf."""
    import jax

    return jax.tree_util.tree_map(lambda l: np.array(np.asarray(l)), tree)


class LocalSGD:
    """Periodic parameter averaging (https://arxiv.org/pdf/1805.09767),
    fault-tolerant. Reference local_sgd.py:26-174.

    Usage::

        local = LocalSGD(manager, state, sync_every=32)
        for batch in data:
            grads = grad_fn(state.params, batch)
            local.step(grads)           # inner update; syncs every 32 steps

    Wire the manager's state callbacks to :meth:`state_dict` /
    :meth:`load_state_dict` (NOT the bare train state) so recovering
    replicas receive the backup copy and sync bookkeeping too.
    """

    def __init__(self, manager: Manager, state: FTTrainState, sync_every: int) -> None:
        assert sync_every >= 1, "sync_every must be >= 1"
        self._manager = manager
        self._state = state
        self._sync_every = sync_every
        self._local_step = 0
        # Host backup of the last synchronized params (reference :81-95).
        self._backup_params: Any = _to_host_copy(state.params)

    # -- train-loop surface --

    def step(self, grads: Any) -> None:
        """One inner optimizer step; synchronizes on the window boundary
        (the reference's optimizer post-hook, local_sgd.py:133-141)."""
        self._state.apply_gradients(grads)
        self._local_step += 1
        if self._local_step >= self._sync_every:
            self.sync()

    def sync(self) -> None:
        """Synchronizes across replica groups. Reference local_sgd.py:143-149."""
        self._manager.start_quorum()
        self._perform_sync()
        self._local_step = 0

    # -- checkpoint plumbing (manager state callbacks) --

    def state_dict(self) -> Dict[str, Any]:
        return {
            "state": self._state.state_dict(),
            "backup_params": self._backup_params,
            "local_step": self._local_step,
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._state.load_state_dict(sd["state"])
        self._backup_params = sd["backup_params"]
        self._local_step = sd["local_step"]

    # -- internals --

    def _save_parameters(self) -> None:
        self._backup_params = _to_host_copy(self._state.params)

    def _restore_parameters(self) -> None:
        self._state.params = _to_device_tree(self._backup_params)

    def _perform_sync(self) -> None:
        """Average params; commit -> new backup, abort -> roll the whole
        window back (reference local_sgd.py:151-162)."""
        averaged = self._manager.allreduce(
            self._state.params, op=ReduceOp.AVG
        ).wait()
        if self._manager.should_commit():
            self._state.params = averaged
            self._save_parameters()
        else:
            self._restore_parameters()


class DiLoCo(LocalSGD):
    """Distributed Low-Communication training. Reference local_sgd.py:177-239.

    Requires sync quorum (``use_async_quorum=False``) so a recovering
    replica restores the checkpoint before its first inner step (reference
    :195-199)."""

    def __init__(
        self,
        manager: Manager,
        state: FTTrainState,
        outer_tx: Any,
        sync_every: int,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        super().__init__(manager, state, sync_every)
        self._outer_tx = outer_tx
        self._outer_state = outer_tx.init(state.params)

    def state_dict(self) -> Dict[str, Any]:
        sd = super().state_dict()
        sd["outer_state"] = self._outer_state
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        super().load_state_dict(sd)
        self._outer_state = _to_device_tree(sd["outer_state"])

    def _perform_sync(self) -> None:
        """Average pseudogradients, outer-step from the restored global
        params on commit (reference local_sgd.py:205-225)."""
        import jax
        import optax

        old_global = _to_device_tree(self._backup_params)
        # Paper sign: Δ = θ_global_old − θ_local_new, so the outer optimizer
        # descends toward the inner-trained weights.
        pseudo_grads = jax.tree_util.tree_map(
            lambda old, new: old - new, old_global, self._state.params
        )
        averaged = self._manager.allreduce(pseudo_grads, op=ReduceOp.AVG).wait()

        # Restore to the last global state before applying the outer step.
        self._state.params = old_global

        if self._manager.should_commit():
            updates, self._outer_state = self._outer_tx.update(
                averaged, self._outer_state, self._state.params
            )
            self._state.params = optax.apply_updates(
                self._state.params, updates
            )
            self._save_parameters()
