"""Fault-tolerant data-parallel training demo (the reference train_ddp.py,
TPU-native).

Each replica group (in production: one TPU slice; here: one process) trains
the same model; gradients are averaged across groups through the manager's
fault-tolerant collectives, and every step ends in a distributed commit
vote. Kill any process: the others keep training, and the restarted process
heals from a live peer.

Run (2 groups on one machine, CPU JAX)::

    python -m torchft_tpu.lighthouse --min_replicas 1 &   # or any lighthouse
    TORCHFT_LIGHTHOUSE=http://localhost:29510 REPLICA_GROUP_ID=0 \
        JAX_PLATFORMS=cpu python examples/train_ddp.py &
    TORCHFT_LIGHTHOUSE=http://localhost:29510 REPLICA_GROUP_ID=1 \
        JAX_PLATFORMS=cpu python examples/train_ddp.py

Reference: train_ddp.py:34-152.
"""

import logging
import os
import sys
from datetime import timedelta

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from torchft_tpu.platform import (  # noqa: E402
    apply_compilation_cache_env,
    apply_jax_platform_env,
    standby_gate,
)

apply_jax_platform_env()
apply_compilation_cache_env()  # restarted groups skip the re-jit (heal path)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from torchft_tpu import (  # noqa: E402
    DistributedSampler,
    FTTrainState,
    HostCollectives,
    Manager,
    OptimizerWrapper,
    StatefulDataLoader,
)

logging.basicConfig(level=logging.INFO)
logger = logging.getLogger("train_ddp")


def make_synthetic_dataset(n: int = 4096, dim: int = 32, classes: int = 10):
    """CIFAR-stand-in: gaussian blobs, deterministic."""
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((classes, dim)).astype(np.float32) * 2
    labels = rng.integers(0, classes, size=n)
    x = centers[labels] + rng.standard_normal((n, dim)).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.int32)


def make_image_dataset():
    """Real-image datasets for MODEL=cnn (reference train_ddp.py:40-61
    trains CIFAR-10; this environment has no network, so the bundled real
    dataset is the default and CIFAR-10 loads from local files):

    - ``DATA=digits``: scikit-learn's bundled handwritten-digit images
      (1797 real 8x8 grayscale scans, 10 classes) — always available.
    - ``DATA=cifar10``: the standard ``cifar-10-batches-py`` pickle
      batches from ``CIFAR_DIR`` (default ``~/.cache/cifar-10-batches-py``
      — place an already-downloaded copy there; 32x32x3, 10 classes).

    Returns (images NHWC f32 in [0, 1]-ish, labels i32, (H, C, classes)).
    """
    data = os.environ.get("DATA", "synthetic")
    if data == "digits":
        from sklearn.datasets import load_digits

        d = load_digits()
        x = (d.images.astype(np.float32) / 16.0)[..., None]  # (N, 8, 8, 1)
        return x, d.target.astype(np.int32), (8, 1, 10)
    if data == "cifar10":
        import pickle

        cifar_dir = os.environ.get(
            "CIFAR_DIR",
            os.path.expanduser("~/.cache/cifar-10-batches-py"),
        )
        xs, ys = [], []
        for i in range(1, 6):
            path = os.path.join(cifar_dir, f"data_batch_{i}")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"{path} not found — DATA=cifar10 needs the standard "
                    "cifar-10-batches-py files in CIFAR_DIR (no network "
                    "in this environment; use DATA=digits for the bundled "
                    "real dataset)"
                )
            with open(path, "rb") as f:
                b = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(b[b"data"], np.uint8))
            ys.append(np.asarray(b[b"labels"], np.int64))
        x = (
            np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            .astype(np.float32) / 255.0
        )
        return x, np.concatenate(ys).astype(np.int32), (32, 3, 10)
    return None  # synthetic (the caller generates)


def init_params(dim: int = 32, hidden: int = 128, classes: int = 10):
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    scale = 1.0 / np.sqrt(dim)
    return {
        "w1": jax.random.normal(k1, (dim, hidden), jnp.float32) * scale,
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (hidden, classes), jnp.float32) * 0.1,
        "b2": jnp.zeros((classes,), jnp.float32),
    }


def loss_fn(params, x, y):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def build_model():
    """MODEL=mlp (default, synthetic blobs), MODEL=cnn (images through
    models.cnn — the reference demo's model family, reference
    train_ddp.py:64-72; pick the dataset with DATA=digits|cifar10|synthetic,
    see make_image_dataset), MODEL=lm (the flagship decoder-only
    transformer, tiny config), or MODEL=moe (tiny mixture-of-experts LM
    on synthetic tokens)."""
    model = os.environ.get("MODEL", "mlp")
    if model == "lm":
        # the flagship decoder-only transformer family (tiny config for
        # the CPU demo; the TPU-scale configs live in bench.py)
        from torchft_tpu.models import (
            TransformerConfig,
            init_params as lm_init,
            loss_fn as lm_loss,
        )

        cfg = TransformerConfig(
            vocab_size=512, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64,
        )
        rng = np.random.default_rng(0)
        n, seq = 2048, 33
        x = rng.integers(0, cfg.vocab_size, (n, seq)).astype(np.int32)
        y = np.zeros((n,), np.int32)  # unused: LM loss reads the tokens
        params = lm_init(cfg, jax.random.PRNGKey(0))

        def loss(params, xb, yb):
            return lm_loss(cfg, params, xb)

        return params, loss, x, y
    if model == "moe":
        from torchft_tpu.models import moe, tiny_moe_config

        cfg = tiny_moe_config()
        rng = np.random.default_rng(0)
        n, seq = 2048, 33
        x = rng.integers(
            0, cfg.vocab_size, (n, seq), dtype=np.int64
        ).astype(np.int32)
        y = np.zeros((n,), np.int32)  # unused: LM loss reads the tokens
        params = moe.init_params(cfg, jax.random.PRNGKey(0))

        def loss(params, xb, yb):
            return moe.loss_fn(cfg, params, xb)

        return params, loss, x, y
    if model == "cnn":
        from torchft_tpu.models import cnn, tiny_cnn_config

        real = make_image_dataset()
        if real is not None:
            x, y, (size, channels, classes) = real
            cfg = cnn.CNNConfig(
                image_size=size,
                channels=channels,
                classes=classes,
                widths=(16, 32) if size <= 8 else (32, 64, 128),
                groups=4,
                dense_width=64,
            )
        else:
            cfg = tiny_cnn_config()
            rng = np.random.default_rng(0)
            n = 2048
            x = rng.standard_normal(
                (n, cfg.image_size, cfg.image_size, cfg.channels)
            ).astype(np.float32)
            y = rng.integers(0, cfg.classes, n).astype(np.int32)
        params = cnn.init_params(cfg, jax.random.PRNGKey(0))

        def loss(params, xb, yb):
            return cnn.loss_fn(cfg, params, (xb, yb))

        return params, loss, x, y
    x, y = make_synthetic_dataset()
    return init_params(), loss_fn, x, y


def main() -> None:
    replica_group = int(os.environ.get("REPLICA_GROUP_ID", 0))
    num_replica_groups = int(os.environ.get("NUM_REPLICA_GROUPS", 2))
    num_steps = int(os.environ.get("NUM_STEPS", 200))
    batch_size = 64

    params0, model_loss_fn, x, y = build_model()
    sampler = DistributedSampler(
        dataset_len=len(x),
        replica_group=replica_group,
        num_replica_groups=num_replica_groups,
        shuffle=True,
    )

    # Dataloader position is part of the recovery state: a healed replica
    # resumes its shard mid-epoch instead of re-deriving an offset from the
    # step count (reference train_ddp.py:57-61,141-148 via StatefulDataLoader).
    loader = StatefulDataLoader(sampler, batch_size)

    state = FTTrainState(params0, optax.adamw(1e-3))

    # Checkpoints (recovery or durable) must pair step-N weights with the
    # loader position AS OF the last commit — not the live position, which
    # is already past the in-flight, possibly-never-committed batch.
    ckpt_box = {"loader": loader.state_dict(), "healed": False}

    def full_state_dict():
        return {"train": state.state_dict(), "loader": ckpt_box["loader"]}

    def load_full_state_dict(sd):
        state.load_state_dict(sd["train"])
        loader.load_state_dict(sd["loader"])
        ckpt_box["loader"] = dict(sd["loader"])
        ckpt_box["healed"] = True

    grad_fn = jax.jit(jax.value_and_grad(model_loss_fn))
    # Warm the jit, then park if we are a hot-spare standby (launcher
    # --hot-spare): a promoted standby joins the quorum in milliseconds
    # instead of paying interpreter+import+compile.
    warm_idx = next(iter(StatefulDataLoader(sampler, batch_size)))
    jax.block_until_ready(
        grad_fn(state.params, jnp.asarray(x[warm_idx]), jnp.asarray(y[warm_idx]))
    )
    standby_gate()

    collectives = HostCollectives(timeout=timedelta(seconds=30))
    manager = Manager(
        collectives=collectives,
        load_state_dict=load_full_state_dict,
        state_dict=full_state_dict,
        min_replica_size=1,
        replica_id=f"train_ddp_{replica_group}",
    )
    optimizer = OptimizerWrapper(manager, state)

    # Durable tier (CKPT_DIR set): periodic whole-job checkpoints pairing
    # the user state with the manager's {step, batches_committed} AND the
    # loader position; restore BEFORE the first quorum so the replica
    # rejoins at its step instead of 0 (reference train_ddp.py:141-148 +
    # the manager state_dict contract, reference manager.py:83-85).
    ckpt = None
    if os.environ.get("CKPT_DIR"):
        from torchft_tpu import DurableCheckpointer

        class _UserState:
            state_dict = staticmethod(full_state_dict)
            load_state_dict = staticmethod(load_full_state_dict)

        ckpt = DurableCheckpointer(
            os.environ["CKPT_DIR"],
            manager,
            _UserState(),
            every=int(os.environ.get("CKPT_EVERY", 50)),
        )
        restored = ckpt.restore_latest()
        if restored is not None:
            logger.info(
                f"[group {replica_group}] restored durable ckpt at "
                f"step {restored}"
            )

    while manager.current_step() < num_steps:
        step = manager.current_step()
        ckpt_box["healed"] = False
        loader_ckpt = loader.state_dict()
        batch_idx = next(loader)
        bx, by = jnp.asarray(x[batch_idx]), jnp.asarray(y[batch_idx])

        optimizer.zero_grad()  # async quorum, overlapped with fwd/bwd
        loss, grads = grad_fn(state.params, bx, by)
        avg_grads = manager.allreduce(grads).wait()
        committed = optimizer.step(avg_grads)
        if committed:
            if ckpt_box["healed"]:
                # The heal restored the source's position as of ITS last
                # commit; this step's commit adds one more. Skip one batch
                # (zero-contributed while healing, lossy by design —
                # reference data.py:33-36) so position stays aligned with
                # the committed-step count and epoch boundaries stay
                # synchronized across replica groups.
                next(loader)
            ckpt_box["loader"] = loader.state_dict()
            if ckpt is not None:
                ckpt.maybe_save()
        elif not ckpt_box["healed"]:
            # Replay the same batch on the retry: an uncommitted step must
            # not advance the durable data position, or the stream drifts
            # from the committed-step count and the batch is lost. (A heal
            # applied this step already reset the loader to the peer's
            # committed position — rolling back would clobber it.)
            loader.load_state_dict(loader_ckpt)

        if step % 10 == 0:
            logger.info(
                f"[group {replica_group}] step={step} loss={float(loss):.4f} "
                f"participants={manager.num_participants()} "
                f"committed={committed}"
            )
    logger.info(
        f"[group {replica_group}] done: step={manager.current_step()} "
        f"batches_committed={manager.batches_committed()}"
    )
    if ckpt is not None:
        # drain the async writer: the last snapshot's manifest commit
        # must land before the process exits
        ckpt.flush()
        ckpt.close()
    manager.shutdown()
    collectives.shutdown()


if __name__ == "__main__":
    main()
