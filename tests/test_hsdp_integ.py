"""HSDP composition under faults: intra-group dp x tp sharding composed with
the cross-group fault-tolerance layer, end to end.

The reference proves FSDP composes with the managed replicate dimension
(reference fsdp_test.py:38-74, device_mesh_test.py:25-85). The TPU-native
equivalent proven here: each replica group runs the flagship transformer's
jitted sharded train step on its OWN 4-device mesh (data:2 x model:2 — the
slice's ICI dimensions), while gradients are averaged across groups through
a REAL 2-member host TCP ring (the DCN/replicate dimension), with kill +
heal and the bit-identical-state oracle (reference
manager_integ_test.py:279-282).

Runs on the virtual 8-device CPU platform from conftest.py: group g owns
devices [4g, 4g+4), so both sharded steps execute concurrently in one
process exactly as two slices would.
"""

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import (
    FTTrainState,
    HostCollectives,
    Lighthouse,
    Manager,
    OptimizerWrapper,
)
from torchft_tpu.models import (
    init_params,
    loss_fn,
    param_sharding_rules,
    tiny_config,
)
from torchft_tpu.parallel import build_grad_step, make_mesh, shard_pytree

from test_manager_integ import FailureInjector, InjectedFailure

logger = logging.getLogger(__name__)

DEVICES_PER_GROUP = 4


class ShardedFTTrainState(FTTrainState):
    """FTTrainState whose heal path re-shards onto the group's mesh.

    Checkpoint leaves arrive as host numpy; the base class rebuilds them on
    the default device, which would leave a healed replica's params off its
    mesh. Re-placing through the sharding rules keeps the jitted step's
    in_shardings contract intact. Uses a stateless optimizer (plain SGD) so
    opt_state needs no sharding rules of its own.
    """

    def __init__(self, params: Any, tx: Any, mesh: Any, rules: Any) -> None:
        super().__init__(shard_pytree(params, rules, mesh), tx)
        self._mesh = mesh
        self._rules = rules

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self.params = shard_pytree(state_dict["params"], self._rules, self._mesh)
        self.opt_state = self.tx.init(self.params)


def _batch(cfg, step: int, mesh) -> jax.Array:
    """Deterministic per-step token batch, identical across groups, sharded
    over the group's data axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(7000 + step)
    tokens = rng.integers(0, cfg.vocab_size, size=(4, 32), dtype=np.int32)
    return jax.device_put(jnp.asarray(tokens), NamedSharding(mesh, P("data")))


class ShardedRunner:
    """One replica group: a thread owning 4 devices, running the sharded
    step, healing through the real ring on restart."""

    def __init__(
        self,
        replica_id: int,
        lighthouse_address: str,
        injector: FailureInjector,
        num_steps: int,
        attempts: int = 3,
        gate_step: Optional[int] = None,
        gate_event: Optional[threading.Event] = None,
        announce_restart: Optional[threading.Event] = None,
    ) -> None:
        self.replica_id = replica_id
        self.lighthouse_address = lighthouse_address
        self.injector = injector
        self.num_steps = num_steps
        self.attempts = attempts
        # Same deterministic-overlap gate as test_manager_integ.Runner:
        # the survivor holds at gate_step until the victim's restart is
        # live, so the heal really overlaps (and the survivor's manager is
        # still up to serve the checkpoint).
        self.gate_step = gate_step
        self.gate_event = gate_event
        self.announce_restart = announce_restart

    def run(self) -> Dict[str, Any]:
        for attempt in range(self.attempts):
            try:
                return self._main(attempt)
            except InjectedFailure:
                logger.info(f"group {self.replica_id} died; restarting")
                continue
        raise RuntimeError(f"group {self.replica_id} exhausted attempts")

    # One compiled sharded step per group, shared across restart attempts:
    # a restart re-jitting from scratch on this 1-CPU host can take >100 s
    # under suite load, starving the survivor's gate (a real deployment
    # has XLA's persistent compilation cache for the same reason).
    _setup_cache: Dict[int, Any] = {}

    def _group_setup(self, gid: int):
        cached = self._setup_cache.get(gid)
        if cached is None:
            devices = jax.devices()[
                gid * DEVICES_PER_GROUP : (gid + 1) * DEVICES_PER_GROUP
            ]
            mesh = make_mesh({"data": 2, "model": 2}, devices=devices)
            cfg = tiny_config()
            rules = param_sharding_rules(cfg)
            grad_step = build_grad_step(
                lambda p, b: loss_fn(cfg, p, b), mesh, rules
            )
            cached = self._setup_cache[gid] = (
                devices, mesh, cfg, rules, grad_step
            )
        return cached

    def _main(self, attempt: int) -> Dict[str, Any]:
        gid = self.replica_id
        devices, mesh, cfg, rules, grad_step = self._group_setup(gid)
        state = ShardedFTTrainState(
            init_params(cfg, jax.random.PRNGKey(42)), optax.sgd(0.05), mesh, rules
        )
        # Pre-warm the sharded compile BEFORE joining the control plane: a
        # long jit under CPU load inside the quorum window would time out
        # the peer's long-poll.
        jax.block_until_ready(grad_step(state.params, _batch(cfg, 0, mesh)))

        collectives = HostCollectives(timeout=timedelta(seconds=60))
        manager = Manager(
            collectives=collectives,
            load_state_dict=state.load_state_dict,
            state_dict=state.state_dict,
            min_replica_size=1,
            timeout=timedelta(seconds=60),
            quorum_timeout=timedelta(seconds=60),
            connect_timeout=timedelta(seconds=60),
            lighthouse_addr=self.lighthouse_address,
            replica_id=f"hsdp_{gid}",
        )
        optimizer = OptimizerWrapper(manager, state)
        if attempt > 0 and self.announce_restart is not None:
            self.announce_restart.set()
        try:
            while manager.current_step() < self.num_steps:
                if (
                    self.gate_event is not None
                    and manager.current_step() == self.gate_step
                ):
                    assert self.gate_event.wait(timeout=300)
                self.injector.check(0, manager.current_step())
                optimizer.zero_grad()  # async quorum
                batch = _batch(cfg, manager.current_step(), mesh)
                loss, grads = grad_step(state.params, batch)
                # Cross-group (DCN) average through the real ring; the ring
                # returns unsharded leaves — re-place on the group mesh so
                # the donated apply keeps its sharded layout.
                avg = manager.allreduce(grads).wait()
                avg = shard_pytree(avg, rules, mesh)
                optimizer.step(avg)
            # Every param leaf must still live on this group's mesh with
            # its declared sharding (the composition claim).
            for leaf in jax.tree_util.tree_leaves(state.params):
                assert set(leaf.sharding.device_set) <= set(devices)
            return {
                "replica_id": gid,
                "state_dict": jax.tree_util.tree_map(
                    np.asarray, state.state_dict()
                ),
                "manager_state": manager.state_dict(),
                "metrics": manager.metrics().snapshot(),
            }
        finally:
            manager.shutdown()
            collectives.shutdown()


def _run_groups(
    num_steps: int,
    injectors: Optional[List[FailureInjector]] = None,
    gates: Optional[Dict[int, Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    assert len(jax.devices()) >= 2 * DEVICES_PER_GROUP
    lighthouse = Lighthouse(
        bind="[::]:0",
        min_replicas=1,
        join_timeout_ms=200,
        quorum_tick_ms=50,
        heartbeat_timeout_ms=2500,
    )
    injectors = injectors or [FailureInjector() for _ in range(2)]
    try:
        with ThreadPoolExecutor(max_workers=2) as ex:
            futures = [
                ex.submit(
                    ShardedRunner(
                        replica_id=i,
                        lighthouse_address=lighthouse.address(),
                        injector=injectors[i],
                        num_steps=num_steps,
                        **(gates or {}).get(i, {}),
                    ).run
                )
                for i in range(2)
            ]
            return [f.result(timeout=240) for f in futures]
    finally:
        lighthouse.shutdown()


def _assert_bitwise_identical(results: List[Dict[str, Any]]) -> None:
    a, ta = jax.tree_util.tree_flatten(results[0]["state_dict"]["params"])
    b, tb = jax.tree_util.tree_flatten(results[1]["state_dict"]["params"])
    assert ta == tb
    for x, y in zip(a, b):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), (
            "sharded states diverged across replica groups"
        )


class TestHSDPUnderFaults:
    def test_sharded_groups_stay_identical(self):
        results = _run_groups(num_steps=4)
        for r in results:
            assert r["manager_state"]["step"] == 4
        _assert_bitwise_identical(results)

    def test_sharded_group_kill_and_heal(self):
        injectors = [FailureInjector(), FailureInjector().fail_at(0, 2)]
        # Group 0 holds at step 4 until group 1's restart is live, so the
        # heal deterministically overlaps (group 1 really fetches group
        # 0's sharded state through the ring-side transport rather than
        # re-deriving it solo).
        rejoined = threading.Event()
        results = _run_groups(
            num_steps=6,
            injectors=injectors,
            gates={
                0: {"gate_step": 4, "gate_event": rejoined},
                1: {"announce_restart": rejoined},
            },
        )
        assert injectors[1].count == 1
        for r in results:
            assert r["manager_state"]["step"] == 6
        healed = next(r for r in results if r["replica_id"] == 1)
        assert healed["metrics"]["counters"]["heals"] >= 1
        _assert_bitwise_identical(results)
