#!/bin/bash
# E2E verify: lighthouse CLI + dashboard + 2-group train_ddp with a
# mid-run SIGKILL and live heal. CPU JAX.
set -ex
cd "$(dirname "$0")"
# The demo MLP runs ~hundreds of steps/s: the step count must be large
# enough that the kill, the ~8 s restart (interpreter + jax import), and
# the heal all land while the survivor is still training.
export JAX_PLATFORMS=cpu NUM_STEPS=20000 NUM_REPLICA_GROUPS=2
export TORCHFT_COMPILE_CACHE=/tmp/verify_jax_cache

pkill -f '[t]orchft_tpu.lighthouse' || true
python -m torchft_tpu.lighthouse --bind '[::]:29511' --min_replicas 1 \
    --join_timeout_ms 2000 --quorum_tick_ms 50 --heartbeat_timeout_ms 1000 \
    > /tmp/verify_lh.log 2>&1 &
LH_PID=$!
sleep 2
export TORCHFT_LIGHTHOUSE=http://localhost:29511

curl -sf http://localhost:29511/ | grep -qi torchft
curl -sf http://localhost:29511/status > /tmp/verify_status0.html

REPLICA_GROUP_ID=0 python examples/train_ddp.py > /tmp/verify_g0.log 2>&1 &
G0=$!
REPLICA_GROUP_ID=1 python examples/train_ddp.py > /tmp/verify_g1.log 2>&1 &
G1=$!

# wait until group 1 is actually training, then SIGKILL it and restart
for i in $(seq 1 120); do
    grep -q "step=200" /tmp/verify_g1.log && break
    sleep 1
done
grep -q "step=200" /tmp/verify_g1.log
kill -9 $G1 || true
REPLICA_GROUP_ID=1 python examples/train_ddp.py > /tmp/verify_g1b.log 2>&1 &
G1B=$!

wait $G0; RC0=$?
wait $G1B; RC1=$?
kill $LH_PID || true

test $RC0 -eq 0
test $RC1 -eq 0
grep -q "done: step=20000" /tmp/verify_g0.log
grep -q "done: step=20000" /tmp/verify_g1b.log
# the restarted group healed live from the surviving peer
grep -qi "healing required, fetching checkpoint" /tmp/verify_g1b.log
echo "E2E VERIFY OK"
