"""Hierarchical lighthouse tier: flat-vs-hierarchical equivalence + failover.

Two layers of proof:

1. **Scripted-history property suite** (pure): membership histories — joins,
   renewals, silent deaths, explicit departs, region deaths (incl.
   simultaneous region death + group join), demotion to direct-root
   registration — are interpreted twice through the SAME C++ pure functions
   the live servers run (``lease_apply``/``depart_apply``/``digest_make``/
   ``digest_apply``/``quorum_step``): once flat (events applied directly to
   one state) and once hierarchically (events buffered in per-region states,
   forwarded as age-relative digests each tick). The formed-quorum sequences
   must be BIT-IDENTICAL, including ``quorum_id`` monotonicity.

2. **Live e2e**: root + two region lighthouses + native managers with root
   fallback; a region kill demotes its manager to direct-root registration
   (quorums keep forming), the revived region wins it back, and quorum_id
   stays monotonic throughout.
"""

import threading
import time
from datetime import timedelta

import pytest

from torchft_tpu import _native
from torchft_tpu._native import (
    Lighthouse,
    Manager,
    ManagerClient,
    RegionLighthouse,
    Store,
    depart_apply,
    digest_apply,
    digest_make,
    lease_apply,
    quorum_step,
)
from torchft_tpu.lighthouse import fetch_quorum, fetch_status

TIMEOUT = timedelta(seconds=20)


def member(replica_id, step=1, force_reconfigure=False):
    return {
        "replica_id": replica_id,
        "address": f"addr_{replica_id}",
        "store_address": f"store_{replica_id}",
        "step": step,
        "world_size": 1,
        "shrink_only": False,
        "force_reconfigure": force_reconfigure,
    }


def entry(replica_id, ttl_ms=200, participating=True, **kw):
    return {
        "replica_id": replica_id,
        "ttl_ms": ttl_ms,
        "participating": participating,
        "member": member(replica_id, **kw),
    }


EMPTY = {
    "participants": {},
    "heartbeats": {},
    "lease_ttls": {},
    "prev_quorum": None,
    "quorum_id": 0,
}

OPT = {
    "min_replicas": 1,
    "join_timeout_ms": 0,
    "quorum_tick_ms": 10,
    "heartbeat_timeout_ms": 200,
}


# ---- scripted-history interpreters -------------------------------------
#
# A history is a list of (t, op, *args), ops:
#   ("lease", region, [entries])   renewal batch via `region` ("direct" =
#                                  straight to the root, the demoted path)
#   ("depart", region, replica_id)
#   ("region_die", region)         region stops digesting; its state is lost
#   ("region_revive", region)      region returns with a FRESH state
#
# Both interpreters tick every TICK ms over the horizon and record every
# formed quorum as its full JSON (id, membership, created_ms).

TICK = 10


def run_flat(history, horizon, opt=OPT):
    state = dict(EMPTY)
    formed = []
    by_time = sorted(history, key=lambda e: e[0])
    i = 0
    for t in range(0, horizon + TICK, TICK):
        while i < len(by_time) and by_time[i][0] <= t:
            ev = by_time[i]
            if ev[1] == "lease":
                state = lease_apply(state, ev[3], t)
            elif ev[1] == "depart":
                state = depart_apply(state, ev[3])
            # region_die / region_revive: routing-only events; the flat
            # service sees nothing (the history itself reroutes renewals)
            i += 1
        res = quorum_step(t, t, state, opt)
        state = res["state"]
        if res["quorum"] is not None:
            formed.append((t, res["quorum"]))
    return formed


def run_hierarchical(history, horizon, regions, opt=OPT, wal_dir=None):
    """``wal_dir`` (optional) runs the root DURABLY: every mutation is
    logged through the native DurableLog exactly the way the live root
    logs it (post-apply member slices, departs, quorum commits), and a
    ``(t, "root_restart")`` event DROPS the in-memory root state and
    recovers it from the WAL — the mid-history crash. The quorum output
    must stay bit-identical to flat either way."""
    root = dict(EMPTY)
    region_states = {r: dict(EMPTY) for r in regions}
    alive = {r: True for r in regions}
    formed = []
    wal = _native.WalLog(wal_dir) if wal_dir else None
    quorum_gen = 0
    epoch = 1
    if wal is not None:
        wal.log_epoch(epoch)

    def wal_log_members(ids, t):
        # The live root's wal_entries_from_state: POST-APPLY slices.
        if wal is None:
            return
        entries = []
        for rid in ids:
            if rid not in root["heartbeats"]:
                continue
            e = {
                "replica_id": rid,
                "age_ms": t - root["heartbeats"][rid],
                "ttl_ms": root["lease_ttls"].get(rid, 0),
                "participating": rid in root["participants"],
            }
            if e["participating"]:
                p = root["participants"][rid]
                e["joined_age_ms"] = t - p["joined_ms"]
                e["member"] = p["member"]
            entries.append(e)
        if entries:
            wal.log_lease(entries, t)

    by_time = sorted(history, key=lambda e: e[0])
    i = 0
    for t in range(0, horizon + TICK, TICK):
        departed = {r: [] for r in regions}
        direct_departs = []
        while i < len(by_time) and by_time[i][0] <= t:
            ev = by_time[i]
            if ev[1] == "lease":
                if ev[2] == "direct":
                    root = lease_apply(root, ev[3], t)
                    wal_log_members([e["replica_id"] for e in ev[3]], t)
                else:
                    assert alive[ev[2]], f"lease via dead region {ev[2]}"
                    region_states[ev[2]] = lease_apply(region_states[ev[2]], ev[3], t)
            elif ev[1] == "depart":
                if ev[2] == "direct":
                    direct_departs.append(ev[3])
                else:
                    region_states[ev[2]] = depart_apply(region_states[ev[2]], ev[3])
                    departed[ev[2]].append(ev[3])
            elif ev[1] == "region_die":
                alive[ev[2]] = False
                region_states[ev[2]] = dict(EMPTY)  # process state is lost
            elif ev[1] == "region_revive":
                alive[ev[2]] = True
            elif ev[1] == "root_restart":
                # The root crashes and comes back: in-memory state is
                # LOST; the WAL is the only thing it remembers. Scripted
                # clocks make the rebase an identity, so a correct replay
                # reconstructs the exact pre-crash state.
                assert wal is not None, "root_restart needs wal_dir"
                rec = _native.wal_recover(wal_dir, t, t)
                root = rec["state"]
                quorum_gen = rec["quorum_gen"]
                epoch = rec["root_epoch"] + 1
                wal.log_epoch(epoch)
            i += 1
        # live regions push their digests (ages on the region clock, applied
        # on the root clock — same t here, which is exactly the live
        # behavior up to transport latency). Departs apply BEFORE entries,
        # mirroring the root handler (a re-queued stale depart must not
        # evict a rejoin carried in the same digest's entries).
        for r in regions:
            if alive[r]:
                for d in departed[r]:
                    root = depart_apply(root, d)
                    if wal is not None:
                        wal.log_depart(d)
                digest = digest_make(region_states[r], t, opt)
                root = digest_apply(root, digest, t)
                wal_log_members([e["replica_id"] for e in digest], t)
        for d in direct_departs:
            root = depart_apply(root, d)
            if wal is not None:
                wal.log_depart(d)
        res = quorum_step(t, t, root, opt)
        root = res["state"]
        if res["quorum"] is not None:
            quorum_gen += 1
            if wal is not None:
                wal.log_quorum(res["quorum"], quorum_gen, epoch)
            formed.append((t, res["quorum"]))
            # regions observe the new quorum and mirror the root's
            # participant clear (the poll_loop contract)
            for r in regions:
                if alive[r]:
                    region_states[r]["participants"] = {}
    if wal is not None:
        wal.close()
    return formed


def renew_all(groups, t0, t1, every, via):
    """Renewal events for `groups` every `every` ms in [t0, t1)."""
    out = []
    for t in range(t0, t1, every):
        for region, ids in via.items():
            ids = [g for g in ids if g in groups]
            if ids:
                out.append((t, "lease", region, [entry(g) for g in ids]))
    return out


def assert_equivalent(history, horizon, regions, wal_dir=None):
    flat = run_flat(history, horizon)
    hier = run_hierarchical(history, horizon, regions, wal_dir=wal_dir)
    assert len(flat) == len(hier), (len(flat), len(hier))
    for (tf, qf), (th, qh) in zip(flat, hier):
        assert tf == th
        assert qf == qh, f"divergence at t={tf}:\nflat={qf}\nhier={qh}"
    ids = [q["quorum_id"] for _, q in flat]
    assert ids == sorted(ids), f"quorum_id not monotonic: {ids}"
    return flat


class TestEquivalenceSuite:
    def test_steady_state_and_expiry(self):
        # 6 groups across 2 regions; g3 silently dies at 800 (lease runs
        # out); rejoins at 1400. Membership sequence: 6 -> 5 -> 6.
        via = {"A": ["g0", "g1", "g2"], "B": ["g3", "g4", "g5"]}
        groups = set(sum(via.values(), []))
        hist = renew_all(groups, 0, 800, 50, via)
        hist += renew_all(groups - {"g3"}, 800, 1400, 50, via)
        hist += renew_all(groups, 1400, 2000, 50, via)
        formed = assert_equivalent(hist, 2000, ["A", "B"])
        sizes = [len(q["participants"]) for _, q in formed]
        assert 6 in sizes and 5 in sizes
        assert len({q["quorum_id"] for _, q in formed}) >= 3

    def test_simultaneous_region_death_and_join(self):
        # At t=500 region B dies EXACTLY as a new group joins via region A.
        # B's groups demote to direct-root renewal from t=550 (their leases
        # at the root are still warm, so membership never flaps).
        via = {"A": ["g0", "g1"], "B": ["g2", "g3"]}
        groups = set(sum(via.values(), []))
        hist = renew_all(groups, 0, 500, 50, via)
        hist.append((500, "region_die", "B"))
        hist.append((500, "lease", "A", [entry("g_new")]))
        hist += renew_all(
            groups | {"g_new"},
            550,
            1500,
            50,
            {"A": ["g0", "g1", "g_new"], "direct": ["g2", "g3"]},
        )
        formed = assert_equivalent(hist, 1500, ["A", "B"])
        # all five present in the final quorum; no shrink below 4 (the
        # demotion was seamless)
        assert len(formed[-1][1]["participants"]) == 5
        assert min(len(q["participants"]) for _, q in formed) >= 4

    def test_region_failover_and_return(self):
        # Region B dies, its groups demote, B revives, groups drift back.
        via = {"A": ["g0", "g1"], "B": ["g2", "g3"]}
        groups = set(sum(via.values(), []))
        hist = renew_all(groups, 0, 600, 50, via)
        hist.append((600, "region_die", "B"))
        hist += renew_all(
            groups, 650, 1200, 50, {"A": ["g0", "g1"], "direct": ["g2", "g3"]}
        )
        hist.append((1200, "region_revive", "B"))
        hist += renew_all(groups, 1250, 1800, 50, via)
        formed = assert_equivalent(hist, 1800, ["A", "B"])
        # membership never changed -> quorum_id never bumps after the first
        ids = {q["quorum_id"] for _, q in formed}
        assert ids == {1}, ids

    def test_departs_and_force_reconfigure(self):
        via = {"A": ["g0", "g1"], "B": ["g2"]}
        groups = set(sum(via.values(), []))
        hist = renew_all(groups, 0, 1000, 50, via)
        hist.append((400, "depart", "B", "g2"))
        hist = [
            e for e in hist
            if not (e[1] == "lease" and e[2] == "B" and e[0] > 400)
        ]
        # force_reconfigure pulse from g0 at 700: same membership, id bump
        hist.append(
            (700, "lease", "A", [entry("g0", force_reconfigure=True)])
        )
        formed = assert_equivalent(hist, 1000, ["A", "B"])
        sizes = [len(q["participants"]) for _, q in formed]
        assert sizes[0] == 3 and sizes[-1] == 2
        ids = [q["quorum_id"] for _, q in formed]
        assert len(set(ids)) == 3  # join(1) -> depart(2) -> force(3)


class TestRootRestartEquivalence:
    """Durable-control-plane extension of the property suite: the SAME
    scripted histories, but the hierarchical root runs on a WAL and is
    crash-restarted mid-history — the quorum sequence must stay
    bit-identical to the never-restarted flat service, including
    quorum_id monotonicity across the restart."""

    def test_restart_mid_history_bit_identical(self, tmp_path):
        via = {"A": ["g0", "g1", "g2"], "B": ["g3", "g4", "g5"]}
        groups = set(sum(via.values(), []))
        hist = renew_all(groups, 0, 800, 50, via)
        # membership churn before the crash: g3 silently dies at 800
        hist += renew_all(groups - {"g3"}, 800, 1400, 50, via)
        hist.append((1100, "root_restart"))
        hist += renew_all(groups, 1400, 2000, 50, via)
        formed = assert_equivalent(
            hist, 2000, ["A", "B"], wal_dir=str(tmp_path / "wal")
        )
        sizes = [len(q["participants"]) for _, q in formed]
        assert 6 in sizes and 5 in sizes

    def test_restart_at_every_window(self, tmp_path):
        # The kill-at-every-point sweep at history granularity: one
        # restart per run, swept across the whole horizon — every
        # placement must keep the hierarchical output bit-identical.
        via = {"A": ["g0", "g1"], "B": ["g2"]}
        groups = set(sum(via.values(), []))
        base = renew_all(groups, 0, 600, 50, via)
        base.append((300, "depart", "B", "g2"))
        base = [
            e for e in base
            if not (e[1] == "lease" and e[2] == "B" and e[0] > 300)
        ]
        for k, restart_t in enumerate(range(50, 600, 100)):
            hist = list(base) + [(restart_t, "root_restart")]
            assert_equivalent(
                hist, 600, ["A", "B"],
                wal_dir=str(tmp_path / f"wal_{k}"),
            )

    def test_restart_with_simultaneous_region_death(self, tmp_path):
        # The outage window compounds: the root restarts at the SAME tick
        # a region dies, and the dead region's groups demote to
        # direct-root renewals — exactly the correlated-failure case
        # (whole rack/zone loss) the durability tier exists for.
        via = {"A": ["g0", "g1"], "B": ["g2", "g3"]}
        groups = set(sum(via.values(), []))
        hist = renew_all(groups, 0, 500, 50, via)
        hist.append((500, "root_restart"))
        hist.append((500, "region_die", "B"))
        hist.append((500, "lease", "A", [entry("g_new")]))
        hist += renew_all(
            groups | {"g_new"},
            550,
            1500,
            50,
            {"A": ["g0", "g1", "g_new"], "direct": ["g2", "g3"]},
        )
        formed = assert_equivalent(
            hist, 1500, ["A", "B"], wal_dir=str(tmp_path / "wal")
        )
        assert len(formed[-1][1]["participants"]) == 5
        # the demotion + restart was seamless: no shrink below 4
        assert min(len(q["participants"]) for _, q in formed) >= 4

    def test_wal_disabled_matches_wal_enabled_without_restart(self, tmp_path):
        # Logging itself must be output-invariant: the durable root and
        # the in-memory root produce identical histories when no crash
        # happens.
        via = {"A": ["g0", "g1"], "B": ["g2"]}
        groups = set(sum(via.values(), []))
        hist = renew_all(groups, 0, 1000, 50, via)
        hist.append((400, "depart", "B", "g2"))
        hist = [
            e for e in hist
            if not (e[1] == "lease" and e[2] == "B" and e[0] > 400)
        ]
        plain = run_hierarchical(hist, 1000, ["A", "B"])
        durable = run_hierarchical(
            hist, 1000, ["A", "B"], wal_dir=str(tmp_path / "wal")
        )
        assert plain == durable


class TestDigestFreshnessGate:
    def test_stale_digest_cannot_clobber_direct_lease(self):
        # Region failover: the member renews DIRECTLY at the root while a
        # region (that still remembers it) keeps digesting its pre-demotion
        # state. The stale digest entry must not overwrite the fresh lease
        # — it would count a live, renewing member as dead.
        root = lease_apply(EMPTY, [entry("g0", ttl_ms=1000)], now_ms=5000)
        stale = [
            {
                "replica_id": "g0",
                "lease_age_ms": 4000,  # region last saw g0 at t=1400
                "ttl_ms": 1000,
                "participating": False,
                "joined_age_ms": 0,
                "member": member("g0"),
            }
        ]
        after = digest_apply(root, stale, now_ms=5400)
        assert after["heartbeats"]["g0"] == 5000  # fresh direct lease kept
        # ... while an up-to-date digest still applies
        fresh = [dict(stale[0], lease_age_ms=100)]
        after = digest_apply(after, fresh, now_ms=5600)
        assert after["heartbeats"]["g0"] == 5500


class TestLiveHierarchy:
    def _quorum(self, client, name, step, results, errors):
        try:
            results[name] = client.quorum(0, step, f"ck-{name}", timeout=TIMEOUT)
        except Exception as e:  # noqa: BLE001
            errors[name] = e

    def _both_quorum(self, cA, cB, step):
        results, errors = {}, {}
        ts = [
            threading.Thread(
                target=self._quorum, args=(c, n, step, results, errors), daemon=True
            )
            for n, c in (("A", cA), ("B", cB))
        ]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        assert not errors, errors
        return results

    def _wait_fresh_leases(self, root, margin_ms, replica_ids, deadline_s=10):
        # Readiness probe: a manager flips using_root_fallback() after two
        # FAILED region renewals, i.e. BEFORE any successful direct renewal
        # has reached the root — by then its root lease (last fed by the dead
        # region's digest) may already be expired. A quorum issued in that
        # gap forms without the demoted member, and its lone late intent then
        # parks behind the split-brain guard (1 participant <= half of 2
        # healthy workers) for the full client timeout. Gate on the root
        # actually holding a fresh lease for every member first.
        deadline = time.monotonic() + deadline_s
        while True:
            lease = {
                m["replica_id"]: m["lease_remaining_ms"]
                for m in root.status_json()["members"]
            }
            if all(lease.get(rid, -1) >= margin_ms for rid in replica_ids):
                return
            assert time.monotonic() < deadline, lease
            time.sleep(0.02)

    def test_managers_through_regions_with_failover(self):
        root = Lighthouse(min_replicas=1, join_timeout_ms=200)
        ra = RegionLighthouse(root.address(), "ra", digest_interval_ms=50)
        rb = RegionLighthouse(root.address(), "rb", digest_interval_ms=50)
        store = Store()
        # lease_ttl must outlive the demotion gap: a region death costs two
        # failed renewals (500 ms connect timeout each) before the manager
        # falls back to direct root registration, and under full-suite CPU
        # contention that gap stretches past 1.5 s — a 500 ms TTL then
        # expires ONCE PER FAILOVER WINDOW (demotion + return = two quorum
        # bumps), which is legitimate behavior but not what this test is
        # probing for.
        mA = Manager(
            "repA", ra.address(), "localhost", "[::]:0", store.address(), 1,
            heartbeat_interval=timedelta(milliseconds=50),
            root_addr=root.address(),
            lease_ttl=timedelta(milliseconds=2500),
        )
        mB = Manager(
            "repB", rb.address(), "localhost", "[::]:0", store.address(), 1,
            heartbeat_interval=timedelta(milliseconds=50),
            root_addr=root.address(),
            lease_ttl=timedelta(milliseconds=2500),
        )
        cA, cB = ManagerClient(mA.address()), ManagerClient(mB.address())
        quorum_ids = []
        try:
            # wait until both members' liveness has propagated region->root
            # (a quorum requested before that would form without the other
            # member and park it behind the split-brain guard)
            deadline = time.monotonic() + 10
            while True:
                ids = {m["replica_id"] for m in root.status_json()["members"]}
                if {"repA", "repB"} <= ids:
                    break
                assert time.monotonic() < deadline, ids
                time.sleep(0.05)

            # 1. both groups quorum through their regions
            r = self._both_quorum(cA, cB, step=1)
            assert r["A"].replica_world_size == 2
            assert r["A"].quorum_id == r["B"].quorum_id
            quorum_ids.append(r["A"].quorum_id)
            assert not mA.using_root_fallback()

            # root status shows both regions digesting
            st = root.status_json()
            assert st["role"] == "root"
            assert sorted(x["region_id"] for x in st["regions"]) == ["ra", "rb"]

            # 2. region A dies -> manager A demotes to direct root
            ra_port = int(ra.address().rsplit(":", 1)[1])
            ra.shutdown()
            deadline = time.monotonic() + 10
            while not mA.using_root_fallback():
                assert time.monotonic() < deadline, "manager A never demoted"
                time.sleep(0.05)
            self._wait_fresh_leases(root, 250, ("repA", "repB"))

            r = self._both_quorum(cA, cB, step=2)
            assert r["A"].replica_world_size == 2
            quorum_ids.append(r["A"].quorum_id)

            # 3. region A returns on the SAME port -> manager drifts back
            ra = RegionLighthouse(
                root.address(), "ra", bind=f"[::]:{ra_port}", digest_interval_ms=50
            )
            deadline = time.monotonic() + 10
            while mA.using_root_fallback():
                assert time.monotonic() < deadline, "manager A never returned"
                time.sleep(0.05)
            self._wait_fresh_leases(root, 250, ("repA", "repB"))

            r = self._both_quorum(cA, cB, step=3)
            assert r["A"].replica_world_size == 2
            quorum_ids.append(r["A"].quorum_id)

            # membership never changed across the failover: monotonic ids,
            # and no spurious reconfigure (ids identical unless a lease
            # expired during the demotion window)
            assert quorum_ids == sorted(quorum_ids)
            assert quorum_ids[-1] - quorum_ids[0] <= 1
        finally:
            mA.shutdown()
            mB.shutdown()
            ra.shutdown()
            rb.shutdown()
            root.shutdown()
            store.shutdown()

    def test_region_survives_root_restart(self):
        # The root's broadcast generation belongs to an incarnation: after a
        # root restart (counter back to 0) the region must reset its poll
        # cursor, or every poll parks forever and the region goes quorumless.
        root = Lighthouse(min_replicas=1, join_timeout_ms=100)
        root_port = int(root.address().rsplit(":", 1)[1])
        ra = RegionLighthouse(root.address(), "ra", digest_interval_ms=50)
        try:
            c = _native.LeaseClient(ra.address())
            c.renew([entry("g0", ttl_ms=60000)])
            deadline = time.monotonic() + 10
            while ra.status_json()["quorum_gen"] < 1:
                assert time.monotonic() < deadline, "no quorum before restart"
                time.sleep(0.05)

            root.shutdown()
            root = Lighthouse(
                bind=f"[::]:{root_port}", min_replicas=1, join_timeout_ms=100
            )
            # a new membership round against the RESTARTED root must still
            # reach waiters through the region's poll loop (both members
            # re-declare intent — a lone g1 would rightly sit behind the
            # split-brain guard while g0 is healthy but silent)
            deadline = time.monotonic() + 15
            while True:
                st = ra.status_json()
                q = st.get("quorum") or {}
                ids = [m["replica_id"] for m in q.get("participants", [])]
                if "g1" in ids:
                    break
                assert time.monotonic() < deadline, st
                c.renew(
                    [entry("g0", ttl_ms=60000), entry("g1", ttl_ms=60000)]
                )
                time.sleep(0.1)
        finally:
            ra.shutdown()
            root.shutdown()

    def test_region_status_json_over_http(self):
        root = Lighthouse(min_replicas=1, join_timeout_ms=100)
        ra = RegionLighthouse(root.address(), "ra", digest_interval_ms=50)
        try:
            _native.LeaseClient(ra.address()).renew(
                [entry("g0", ttl_ms=2000, participating=False)]
            )
            deadline = time.monotonic() + 5
            while True:
                st = fetch_status(ra.address())
                if st["members"] and st["root_connected"]:
                    break
                assert time.monotonic() < deadline
                time.sleep(0.05)
            assert st["role"] == "region"
            assert st["region_id"] == "ra"
            assert st["members"][0]["replica_id"] == "g0"
            # and the root lists the region
            deadline = time.monotonic() + 5
            while True:
                rst = fetch_status(root.address())
                if rst["regions"]:
                    break
                assert time.monotonic() < deadline
                time.sleep(0.05)
            assert rst["regions"][0]["region_id"] == "ra"
            assert rst["role"] == "root"
        finally:
            ra.shutdown()
            root.shutdown()


class TestRegionQuorumCache:
    """The region-side quorum cache (ROADMAP item 2 carry-over): read-mostly
    consumers get the last GLOBAL quorum from the region's standing root
    poll instead of long-polling the root per request — and the staleness
    of that cache is bounded and visible (`age_ms`)."""

    def test_cache_serves_locally_with_bounded_staleness(self):
        root = Lighthouse(min_replicas=1, join_timeout_ms=100)
        ra = RegionLighthouse(root.address(), "ra", digest_interval_ms=50)
        try:
            c = _native.LeaseClient(ra.address())
            # Before any root quorum: the cache is explicit about having
            # nothing (age null), not fake-fresh.
            q = ra.quorum_json()
            assert q["cached"] is True
            assert q["age_ms"] is None and q["quorum"] is None

            c.renew([entry("g0", ttl_ms=60000)])
            deadline = time.monotonic() + 10
            while True:
                q = ra.quorum_json()
                if q["quorum_id"] >= 1 and q["quorum"] is not None:
                    break
                assert time.monotonic() < deadline, q
                time.sleep(0.05)
            ids = [m["replica_id"] for m in q["quorum"]["participants"]]
            assert ids == ["g0"]

            # Staleness bound: a freshly-caught quorum's cache age is within
            # one poll round trip (the push path is the standing long-poll,
            # not this read), far under the 10 s poll window.
            assert q["age_ms"] is not None and q["age_ms"] < 3000

            # A NEW root quorum (g0+g1) must land in the cache within the
            # same bound — the cache tracks the root, it doesn't snapshot
            # once.
            deadline = time.monotonic() + 15
            while True:
                c.renew([entry("g0", ttl_ms=60000), entry("g1", ttl_ms=60000)])
                q = fetch_quorum(ra.address())  # the HTTP read-mostly path
                got = q["quorum"] or {}
                ids = [m["replica_id"] for m in got.get("participants", [])]
                if "g1" in ids:
                    break
                assert time.monotonic() < deadline, q
                time.sleep(0.1)
            assert q["cached"] is True
            assert q["age_ms"] < 3000
            assert q["region_id"] == "ra"
            qid_before_outage = q["quorum_id"]

            # Root down: the cache KEEPS serving the last global quorum
            # locally (that is what makes it a cache, not a proxy), with a
            # growing age — readers can bound their own staleness.
            root.shutdown()
            time.sleep(0.3)
            q1 = fetch_quorum(ra.address())
            assert q1["quorum_id"] == qid_before_outage
            time.sleep(0.3)
            q2 = fetch_quorum(ra.address())
            assert q2["quorum_id"] == qid_before_outage
            assert q2["age_ms"] > q1["age_ms"]
            # status.json mirrors the cache age for dashboards
            st = ra.status_json()
            assert st["quorum_age_ms"] is not None
        finally:
            ra.shutdown()
            root.shutdown()


class TestStatusDigestForwarding:
    """Member-health status digests ride lease renewals into the REGION and
    are forwarded region->root inside membership digests — the root's
    /status.json stays the fleet's single pane of glass under the
    hierarchical tier."""

    def test_status_reaches_root_through_digest(self):
        root = Lighthouse(min_replicas=1, join_timeout_ms=100)
        ra = RegionLighthouse(root.address(), "ra", digest_interval_ms=50)
        try:
            e = entry("gst", ttl_ms=60000, participating=False)
            e["status_json"] = '{"wire_eff_MBps": 7.5, "step": 3}'
            _native.LeaseClient(ra.address()).renew([e])
            deadline = time.monotonic() + 10
            got = None
            while time.monotonic() < deadline:
                members = root.status_json()["members"]
                got = next(
                    (m for m in members if m["replica_id"] == "gst"), None
                )
                if got is not None and "status" in got:
                    break
                time.sleep(0.05)
            assert got is not None and "status" in got, got
            assert got["status"]["wire_eff_MBps"] == 7.5
            # and the region's own view carries it too
            rm = next(
                m for m in ra.status_json()["members"]
                if m["replica_id"] == "gst"
            )
            assert rm["status"]["step"] == 3
        finally:
            ra.shutdown()
            root.shutdown()
