"""Churn benchmark: throughput under replica-group kills (the north star).

Measures the driver-set target from BASELINE.md: steps/sec with one
replica-group kill every ``--kill-every`` steps must stay >= 90% of
healthy-state steps/sec. The reference makes this claim qualitatively
("avoid stop the world training on errors", reference README.md:46-47) and
exercises the recovery flow in tests (reference torchft/manager.py:470-526);
this benchmark puts a number on it.

Topology: N replica groups as local processes (CPU JAX), one real
HostCollectives TCP ring between them, one lighthouse. Two phases with the
same model/config:

  healthy: all groups train ``--steps`` steps, no faults.
  churn:   a supervisor SIGKILLs one (rotating, never group 0) group each
           time group 0 commits ``--kill-every`` more steps, then restarts
           it; the restarted process heals from a live peer over HTTP.

Reported (CHURN_BENCH.json + one JSON line on stdout):
  steps_per_sec_healthy / steps_per_sec_churn  (group 0's committed steps)
  ratio  = churn / healthy       (north star: >= 0.90)
  heal_p50_s = median time from SIGKILL to the restarted group's first
               committed step (includes process restart + jit recompile —
               on real multi-host deployments each group has its own host,
               so single-host numbers are pessimistic: the restarting
               process competes for this machine's CPUs).

Usage::

    python bench_churn.py --groups 4 --steps 300 --kill-every 100
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


# --------------------------------------------------------------------------
# worker: one replica group
# --------------------------------------------------------------------------


def worker() -> None:
    """Trains the flagship transformer (small config) with the full FT path,
    appending one JSONL record per attempted step (plus one "boot" record
    timestamping the restart->rejoin phases for the heal breakdown)."""
    t_enter = time.time()
    from torchft_tpu.platform import (
        apply_compilation_cache_env,
        apply_jax_platform_env,
        standby_gate,
    )

    apply_jax_platform_env()
    apply_compilation_cache_env()  # restarted workers reload jit executables

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from datetime import timedelta

    from torchft_tpu import (
        FTTrainState,
        HostCollectives,
        Manager,
        OptimizerWrapper,
    )
    from torchft_tpu.models import TransformerConfig, init_params, loss_fn

    group = int(os.environ["REPLICA_GROUP_ID"])
    num_steps = int(os.environ["NUM_STEPS"])
    log_path = os.environ["BENCH_LOG"]

    cfg = TransformerConfig(
        vocab_size=2048, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=64,
    )
    batch_size, seq_len = 4, 64
    rng = np.random.default_rng(group)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq_len), dtype=np.int32)
    )

    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), optax.adamw(1e-3))
    grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b)))
    t_setup = time.time()

    # Compile BEFORE joining the quorum, then hold at the start line until
    # every group is ready (parent touches the go file). Without this the
    # first group up forms a solo quorum and races at world-size-1 speed
    # while peers are still importing/compiling, polluting the measured
    # window. Restarted workers find the go file already present and rejoin
    # immediately through the normal heal path.
    jax.block_until_ready(grad_fn(state.params, batch))
    t_compiled = time.time()
    # (t_setup was stamped after the import block: spawn->enter is the
    # interpreter + sitecustomize-preloaded jax; enter->setup is the
    # remaining library imports + model init; setup->compiled is the jit.)
    # Hot-spare standbys park HERE, fully warmed, until promoted; for
    # them activated_t is the promotion instant, for cold starts it
    # coincides with compile completion.
    standby_gate()
    t_activated = time.time()

    # Manager BEFORE the start line: heartbeats flow while the groups
    # gather at the go-gate, so the first quorum's join gate sees every
    # group as healthy and holds the door for all of them — otherwise the
    # first group to request forms an instant solo quorum (it is the only
    # HEARTBEATING replica at that moment) and membership flaps from
    # there.
    collectives = HostCollectives(timeout=timedelta(seconds=30))
    manager = Manager(
        collectives=collectives,
        load_state_dict=state.load_state_dict,
        state_dict=state.state_dict,
        min_replica_size=1,
        heartbeat_interval=timedelta(milliseconds=50),
        replica_id=f"bench_{group}",
    )
    optimizer = OptimizerWrapper(manager, state)

    go_path = os.environ["BENCH_GO"]
    open(log_path + ".ready", "w").close()
    while not os.path.exists(go_path):
        time.sleep(0.05)

    with open(log_path, "a", buffering=1) as log:
        # Boot record first: the parent joins it with its kill/spawn
        # timestamps to break heal latency into respawn / import / setup /
        # compile / join phases.
        log.write(
            json.dumps(
                {
                    "boot": {
                        "spawn_t": float(os.environ.get("BENCH_SPAWN_T", 0)),
                        "enter_t": t_enter,
                        "setup_t": t_setup,
                        "compiled_t": t_compiled,
                        "activated_t": t_activated,
                        "manager_t": time.time(),
                    }
                }
            )
            + "\n"
        )
        while manager.current_step() < num_steps:
            t0 = time.perf_counter()
            optimizer.zero_grad()
            t1 = time.perf_counter()
            loss, grads = grad_fn(state.params, batch)
            jax.block_until_ready(grads)
            t2 = time.perf_counter()
            avg = manager.allreduce(grads).wait()
            t3 = time.perf_counter()
            committed = optimizer.step(avg)
            t4 = time.perf_counter()
            log.write(
                json.dumps(
                    {
                        "t": time.time(),
                        "step": manager.current_step(),
                        "committed": bool(committed),
                        "participants": manager.num_participants(),
                        "ms": {
                            "quorum_start": round((t1 - t0) * 1e3, 1),
                            "grad": round((t2 - t1) * 1e3, 1),
                            "allreduce": round((t3 - t2) * 1e3, 1),
                            "commit": round((t4 - t3) * 1e3, 1),
                        },
                    }
                )
                + "\n"
            )
    manager.shutdown()
    collectives.shutdown()


# --------------------------------------------------------------------------
# parent: orchestration + measurement
# --------------------------------------------------------------------------


class _Group:
    def __init__(
        self, gid: int, log_path: str, env: Dict[str, str],
        hot_spare: bool = False,
    ) -> None:
        self.gid = gid
        self.log_path = log_path
        self.env = env
        self.hot_spare = hot_spare
        self.proc: Optional[subprocess.Popen] = None
        self.standby: Optional[subprocess.Popen] = None
        self.standby_file: Optional[str] = None

    def _popen(
        self, extra_env: Dict[str, str], idle: bool = False
    ) -> subprocess.Popen:
        env = {**os.environ, "BENCH_SPAWN_T": str(time.time()), **extra_env}
        # In the GROUP SPEC only, an empty value means "unset" (e.g.
        # JAX_PLATFORMS="" lets the host's default accelerator platform
        # win for the TPU group); inherited empty-string env vars pass
        # through untouched — empty and unset differ for some vars.
        for k, v in self.env.items():
            if v == "":
                env.pop(k, None)
            else:
                env[k] = v
        preexec = None
        if idle:

            def preexec() -> None:
                try:
                    os.nice(19)
                except OSError:
                    pass

        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env,
            cwd=REPO,
            preexec_fn=preexec,
        )

    def spawn(self) -> None:
        self.proc = self._popen({})
        if self.hot_spare:
            self.arm_standby()

    def arm_standby(self) -> None:
        # Idle priority (launcher.py discipline): standby warm-up
        # (imports + jit) must not steal cycles from live training — the
        # round-3 hot-spare phase measured ratio 0.742 BECAUSE re-arming
        # contended with every group on the single shared CPU.
        self.standby_file = self.log_path + f".standby_{time.time():.3f}"
        self.standby = self._popen(
            {"TORCHFT_STANDBY_FILE": self.standby_file}, idle=True
        )

    def restart(self) -> None:
        """Cold respawn, or sub-second promotion of the warm standby
        (the launcher's --hot-spare policy, torchft_tpu.launcher)."""
        if self.standby is not None and self.standby.poll() is None:
            open(self.standby_file, "w").close()
            self.proc = self.standby
            self.standby = None
            try:  # lift the idle priority on promotion (root/CAP_SYS_NICE)
                os.setpriority(os.PRIO_PROCESS, self.proc.pid, 0)
            except (OSError, AttributeError):
                pass
            self.arm_standby()
        else:
            self.proc = self._popen({})
            if self.hot_spare:
                self.arm_standby()

    def reap(self) -> None:
        if self.standby is not None and self.standby.poll() is None:
            self.standby.kill()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def _read_log(path: str) -> List[dict]:
    records = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn write
    except FileNotFoundError:
        pass
    return records


def _committed(records: List[dict]) -> List[dict]:
    return [r for r in records if r.get("committed")]


def _steps_per_sec(records: List[dict], skip: int = 5) -> float:
    """Committed steps/sec, excluding the first ``skip`` commits (compile +
    ramp)."""
    done = _committed(records)[skip:]
    if len(done) < 2:
        return 0.0
    return (len(done) - 1) / (done[-1]["t"] - done[0]["t"])


def _run_phase(
    name: str,
    groups: int,
    steps: int,
    kill_every: int,
    out_dir: str,
    lighthouse_addr: str,
    tpu_group0: bool = False,
    hot_spare: bool = False,
) -> dict:
    go_path = os.path.join(out_dir, f"{name}.go")
    gs: List[_Group] = []
    for g in range(groups):
        log_path = os.path.join(out_dir, f"{name}_g{g}.jsonl")
        gs.append(
            _Group(
                g,
                log_path,
                {
                    # --tpu-group0: the measurement group runs on the real
                    # chip (the platform the host pins by default); its CPU
                    # peers are the churn. Kills only ever hit CPU groups
                    # (victim rotates over 1..N-1), so this shows the
                    # TPU-RESIDENT process's throughput under cross-group
                    # churn — the axis virtual-device dryruns can't show.
                    "JAX_PLATFORMS": ""
                    if (tpu_group0 and g == 0)
                    else "cpu",
                    # CPU workers skip the sitecustomize TPU-backend
                    # preload (axon.register + PJRT init at INTERPRETER
                    # START — it can round-trip the device tunnel): pure
                    # dead weight on the cold-restart heal path, where
                    # the import bucket dominated round 3's 15.2 s p50.
                    # (empty value = "unset" per _popen's group-spec rule)
                    **(
                        {}
                        if (tpu_group0 and g == 0)
                        else {"PALLAS_AXON_POOL_IPS": ""}
                    ),
                    "TORCHFT_LIGHTHOUSE": lighthouse_addr,
                    "REPLICA_GROUP_ID": str(g),
                    "NUM_REPLICA_GROUPS": str(groups),
                    "NUM_STEPS": str(steps),
                    "BENCH_LOG": log_path,
                    "BENCH_GO": go_path,
                    # Shared persistent jit cache: restarted workers reload
                    # executables instead of recompiling (the dominant heal
                    # cost in round 2's 31 s p50).
                    "TORCHFT_COMPILE_CACHE": os.path.join(out_dir, "jax_cache"),
                },
                # Standbys only for killable groups: kills rotate over
                # 1..N-1, so a group-0 standby would be pure import+compile
                # contention against the measurement group (and on
                # --tpu-group0 it could not warm the primary-owned chip
                # anyway).
                hot_spare=hot_spare and g != 0,
            )
        )
    for g in gs:
        g.spawn()

    # Start line: release every group at once, after all have compiled.
    ready_deadline = time.time() + 300
    while time.time() < ready_deadline:
        if all(os.path.exists(g.log_path + ".ready") for g in gs):
            break
        time.sleep(0.25)
    open(go_path, "w").close()

    kills: List[dict] = []
    next_kill = kill_every if kill_every > 0 else None
    victim = 1  # rotate over groups 1..N-1; group 0 is the measurement group
    # Deadline scales with the step target (the default was raised to 1200
    # steps for kill-count power; a fixed 1200 s cap would silently
    # truncate slow runs back to the under-powered measurement). Truncation
    # is detected and reported either way.
    deadline = time.time() + max(1200, steps * 4)
    timed_out = False
    try:
        while any(g.alive() for g in gs):
            if time.time() >= deadline:
                timed_out = True
                break
            time.sleep(0.25)
            # Restart any dead group (supervisor role, launcher semantics;
            # promotes the warm standby under --hot-spare).
            for g in gs:
                if g.proc is not None and g.proc.poll() not in (None, 0):
                    g.restart()
            if next_kill is not None:
                lead = len(_committed(_read_log(gs[0].log_path)))
                if lead >= next_kill and lead < steps - 5:
                    v = gs[victim]
                    if v.alive():
                        v.proc.send_signal(signal.SIGKILL)
                        kills.append(
                            {"t": time.time(), "gid": v.gid, "at_step": lead}
                        )
                        victim = victim % (groups - 1) + 1
                    next_kill += kill_every
    finally:
        for g in gs:
            g.reap()  # parked standbys never exit on their own
            if g.alive():
                g.proc.terminate()
        for g in gs:
            if g.proc is not None:
                try:
                    g.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    g.proc.kill()

    # Heal latency: kill -> first commit recorded by the restarted process,
    # broken into phases via the worker's boot record (respawn = supervisor
    # poll; import = interpreter + sitecustomize-preloaded jax; setup =
    # remaining library imports + model init; compile = jit, ~zero with
    # the shared cache warm; join = go-gate + manager/quorum bring-up;
    # first_commit = rejoin through the heal protocol to a committed step).
    heal_s = []
    breakdowns = []
    for k in kills:
        # Bound each kill's window at the SAME group's next kill: if the
        # victim is killed again before its restart commits, the first
        # commit/boot after the later kill must not be attributed to this
        # one (it would silently fold an extra kill cycle into the
        # breakdown medians).
        next_kill_t = min(
            (
                k2["t"]
                for k2 in kills
                if k2["gid"] == k["gid"] and k2["t"] > k["t"]
            ),
            default=float("inf"),
        )
        log = _read_log(gs[k["gid"]].log_path)
        after = [
            r["t"]
            for r in _committed(log)
            if k["t"] < r["t"] < next_kill_t
        ]
        if after:
            heal_s.append(after[0] - k["t"])
        # Match boots by ACTIVATION time: a promoted hot-spare standby was
        # spawned (and imported/compiled) long before the kill, so only
        # its activation falls in this kill's window.
        boots = [
            r["boot"]
            for r in log
            if "boot" in r
            and k["t"] < r["boot"].get("activated_t", r["boot"]["spawn_t"])
            < next_kill_t
        ]
        if boots and after:
            b = boots[0]
            entry = {
                # kill -> warmed process past its gate (cold: respawn +
                # import + setup + compile; promoted standby: just the
                # supervisor poll + gate poll)
                "activation": b["activated_t"] - k["t"],
                "join": b["manager_t"] - b["activated_t"],
                "first_commit": after[0] - b["manager_t"],
            }
            if b["spawn_t"] > k["t"]:
                # Cold restart: the process-boot phases belong to this kill.
                entry.update(
                    {
                        "respawn": b["spawn_t"] - k["t"],
                        "import": b["enter_t"] - b["spawn_t"],
                        "setup": b["setup_t"] - b["enter_t"],
                        "compile": b["compiled_t"] - b["setup_t"],
                    }
                )
            breakdowns.append(entry)
    heal_s.sort()

    def _phase_median(name: str) -> Optional[float]:
        vals = sorted(b[name] for b in breakdowns if name in b)
        return round(vals[len(vals) // 2], 2) if vals else None

    # Throughput spread: group 0's committed-step rate over time quarters —
    # the noise floor a churn ratio must be read against.
    g0 = _committed(_read_log(gs[0].log_path))[5:]
    quarter_sps = []
    for i in range(4):
        seg = g0[i * len(g0) // 4 : (i + 1) * len(g0) // 4]
        if len(seg) >= 2:
            quarter_sps.append(
                round((len(seg) - 1) / (seg[-1]["t"] - seg[0]["t"]), 3)
            )

    committed_g0 = len(_committed(_read_log(gs[0].log_path)))
    return {
        "steps_per_sec": round(_steps_per_sec(_read_log(gs[0].log_path)), 3),
        "steps_per_sec_quarters": quarter_sps,
        # Deadline truncation (the phase was cut off mid-run, so the
        # measurement is under-powered). A near-target committed count
        # without a timeout is normal: the first group to finish exits,
        # which can abort one in-flight step on the others.
        "truncated": bool(timed_out),
        "committed_vs_target": f"{committed_g0}/{steps}",
        "kills": len(kills),
        "heal_s": [round(h, 2) for h in heal_s],
        "heal_p50_s": round(heal_s[len(heal_s) // 2], 2) if heal_s else None,
        "heal_breakdown_median_s": {
            name: _phase_median(name)
            for name in (
                "activation", "respawn", "import", "setup", "compile",
                "join", "first_commit"
            )
        }
        if breakdowns
        else None,
        "committed_steps_g0": len(_committed(_read_log(gs[0].log_path))),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--groups", type=int, default=4)
    # >= 10 kills over >= 1000 steps: 2 kills over 300 steps (round 2)
    # left the effect smaller than the noise (ratio measured > 1).
    parser.add_argument("--steps", type=int, default=1200)
    parser.add_argument("--kill-every", type=int, default=100)
    parser.add_argument(
        "--tpu-group0",
        action="store_true",
        help="run group 0 on the host's default (TPU) platform; kills "
        "still only hit the CPU peer groups",
    )
    parser.add_argument(
        "--hot-spare",
        action="store_true",
        help="also run a churn phase where restarts promote a pre-warmed "
        "standby (the launcher's --hot-spare policy) instead of cold-"
        "restarting",
    )
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    if args.out is None:
        args.out = os.path.join(
            REPO,
            "CHURN_BENCH_tpu.json" if args.tpu_group0 else "CHURN_BENCH.json",
        )

    if args.worker:
        worker()
        return

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from torchft_tpu import Lighthouse

    out_dir = os.path.join(REPO, ".bench_churn_logs")
    os.makedirs(out_dir, exist_ok=True)
    for f in os.listdir(out_dir):
        path = os.path.join(out_dir, f)
        if os.path.isdir(path):
            # Keep the persistent jit cache WARM across runs: restarted
            # workers (and whole re-runs) skip the compile.
            continue
        os.unlink(path)

    # Failure detection speed comes from heartbeat_timeout (a dead member
    # leaves the healthy set after 500 ms and the join gate does not apply
    # to it). join_timeout must exceed a STEP TIME: the gate holds quorum
    # formation for healthy-but-not-yet-requesting members, and members
    # re-request once per step — a 200 ms gate under >200 ms steps lets
    # sub-quorums form between paced requests, flapping membership and
    # starving a joiner (observed: the TPU group excluded for 43 s while
    # two CPU groups fast-quorumed as a stable pair).
    lighthouse = Lighthouse(
        bind="[::]:0",
        min_replicas=1,
        join_timeout_ms=2000,
        quorum_tick_ms=50,
        heartbeat_timeout_ms=500,
    )

    healthy = _run_phase(
        "healthy", args.groups, args.steps, 0, out_dir, lighthouse.address(),
        tpu_group0=args.tpu_group0,
    )
    churn = _run_phase(
        "churn", args.groups, args.steps, args.kill_every, out_dir,
        lighthouse.address(), tpu_group0=args.tpu_group0,
    )
    churn_hot = None
    if args.hot_spare:
        # Third phase: same kill schedule, restarts by standby PROMOTION
        # (launcher --hot-spare). The cold phase above stays in the
        # artifact so both restart policies' heal latencies are on record.
        churn_hot = _run_phase(
            "churn_hot", args.groups, args.steps, args.kill_every, out_dir,
            lighthouse.address(), tpu_group0=args.tpu_group0, hot_spare=True,
        )
    lighthouse.shutdown()

    ratio = (
        round(churn["steps_per_sec"] / healthy["steps_per_sec"], 3)
        if healthy["steps_per_sec"]
        else 0.0
    )
    # Noise gate: churn measuring FASTER than healthy by > 5% means the
    # run-to-run noise exceeds the effect under measurement — record the
    # run as too noisy instead of claiming an absurd ratio (a fault-
    # tolerance layer cannot beat the fault-free loop).
    quarters = healthy.get("steps_per_sec_quarters") or []
    spread = (
        round((max(quarters) - min(quarters)) / max(quarters), 3)
        if quarters
        else None
    )
    result = {
        "config": {
            "groups": args.groups,
            "steps": args.steps,
            "kill_every": args.kill_every,
            "host_cpus": os.cpu_count(),
            "tpu_group0": args.tpu_group0,
        },
        "healthy": healthy,
        "churn": churn,
        "churn_hot_spare": churn_hot,
        "ratio": ratio,
        "ratio_hot_spare": (
            round(churn_hot["steps_per_sec"] / healthy["steps_per_sec"], 3)
            if churn_hot and healthy["steps_per_sec"]
            else None
        ),
        "healthy_quarter_spread": spread,
        "measurement_ok": bool(
            ratio <= 1.05
            and not healthy.get("truncated")
            and not churn.get("truncated")
        ),
        "target": 0.90,
        "note": "all groups share ONE host CPU, so the two hot-spare "
        "metrics trade off in a way the target deployment (one host per "
        "group) does not: standbys re-arm at IDLE priority (launcher "
        "discipline) so warm-up never steals training cycles — "
        "ratio_hot_spare is deployment-meaningful — but on a saturated "
        "core an idle-priority re-arm may not finish before the same "
        "group is killed again, so REPEAT kills promote a half-warmed "
        "spare and heal_p50_hot_spare regresses toward a cold restart "
        "(first-kill promotions are sub-second, see round-3 artifact's "
        "1.38 s p50 measured with normal-priority re-arm, which instead "
        "cost ratio 0.742). Per-group hosts get both numbers at once: "
        "warm-up contends only with the group it will replace. Cold-heal "
        "breakdown: jax import dominates (~14 s UNDER 4-way load; ~3-5 s "
        "unloaded) — the interpreter-start TPU-backend preload is now "
        "skipped for CPU workers, moving that cost out of spawn->enter.",
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(
        json.dumps(
            {
                "metric": "steps_per_sec_churn_ratio",
                "value": ratio,
                "unit": "ratio",
                "vs_baseline": round(ratio / 0.90, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
