"""pop_op_stats accounting tests.

The per-op phase breakdown (pack / d2h / ring / h2d, bytes, per-bucket
and per-stripe detail) is the ONLY signal that tells a slow transfer from
a slow wire on a degraded link — per-step DDP diagnosis depends on it —
yet until this file nothing asserted its accounting. Covers the
device-packed bulk path, the chunk-pipelined op schedule, the q8 wire,
the plan path's per-bucket stats, and — since the accounting contract
went cross-backend (OpStatsMixin) — the XLA and isolated-XLA backends'
parity keys (``op`` / ``bytes`` / ``d2h_bytes`` on every path), so
AdaptiveDDP probe comparisons and diagnosis tooling read one schema no
matter which data plane served the op.
"""

import os
import subprocess
import sys
import textwrap
import threading
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from conftest import CPU_MULTIPROCESS_SKIP, HAS_CPU_MULTIPROCESS

from torchft_tpu._native import Store
from torchft_tpu.collectives import HostCollectives, ReduceOp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def store():
    s = Store()
    yield s
    s.shutdown()


def _ring(store, prefix, world_size=2, **kwargs):
    cols = [
        HostCollectives(timeout=timedelta(seconds=15), **kwargs)
        for _ in range(world_size)
    ]
    addr = f"{store.address()}/{prefix}"
    with ThreadPoolExecutor(max_workers=world_size) as ex:
        for f in [
            ex.submit(cols[r].configure, addr, r, world_size)
            for r in range(world_size)
        ]:
            f.result()
    return cols


def _run_all(cols, fn):
    results = [None] * len(cols)
    errors = []

    def run(r):
        try:
            results[r] = fn(r, cols[r])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=run, args=(r,)) for r in range(len(cols))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


class TestDevicePackedStats:
    def test_allreduce_phases_bytes_and_buckets(self, store):
        import jax.numpy as jnp

        cols = _ring(store, "st0", pipeline_chunks=1)
        tree = {
            "w": jnp.ones(5003, jnp.float32),
            "n": jnp.ones(777, jnp.int32),
        }
        _run_all(cols, lambda r, c: c.allreduce(tree).wait())
        stats = [
            s for s in cols[0].pop_op_stats() if s["op"] == "allreduce"
        ]
        assert len(stats) == 1
        st = stats[0]
        # every phase of the d2h -> ring -> h2d pipeline is accounted
        for key in ("pack", "d2h", "ring", "h2d"):
            assert key in st and st[key] >= 0.0
        assert st["bytes"] == 5003 * 4 + 777 * 4
        # d2h_bytes is its own key on every path: native dtypes cross
        # the device link at full width here
        assert st["d2h_bytes"] == st["bytes"]
        assert set(st["buckets"]) == {"float32", "int32"}
        for name, b in st["buckets"].items():
            assert b["bytes"] > 0
            assert "stripe_s" in b and "stripe_wall" in b
        # drained: a second pop is empty
        assert cols[0].pop_op_stats() == []
        for c in cols:
            c.shutdown()

    def test_chunk_pipelined_chunk_count_and_bytes(self, store):
        import jax.numpy as jnp

        cols = _ring(store, "st1", pipeline_chunks=4, pipeline_min_bytes=0)
        tree = {
            "w": jnp.ones(10007, jnp.float32),
            "n": jnp.ones(501, jnp.int32),
        }
        _run_all(cols, lambda r, c: c.allreduce(tree).wait())
        st = [
            s for s in cols[0].pop_op_stats() if s["op"] == "allreduce"
        ][-1]
        assert st["chunks"] == 2 * 4  # both dtype buckets chunked 4-way
        # chunking must not double-count bytes: bucket sums == totals
        assert st["bytes"] == 10007 * 4 + 501 * 4
        assert st["d2h_bytes"] == st["bytes"]  # chunk-pipelined path too
        assert (
            sum(b["bytes"] for b in st["buckets"].values()) == st["bytes"]
        )
        # phase sums over buckets equal the op-level phase totals
        for phase in ("d2h", "ring", "h2d"):
            assert st[phase] == pytest.approx(
                sum(b[phase] for b in st["buckets"].values())
            )
        for c in cols:
            c.shutdown()

    def test_q8_wire_bytes_quarter_of_device_bytes(self, store):
        import jax.numpy as jnp

        from torchft_tpu.collectives import (
            _effective_stripes,
            _q8_wire_overhead,
        )

        cols = _ring(store, "st2")
        tree = {"w": jnp.ones(8192, jnp.float32)}
        _run_all(
            cols, lambda r, c: c.allreduce(tree, wire="q8").wait()
        )
        st = [
            s for s in cols[0].pop_op_stats() if s["op"] == "allreduce_q8"
        ][-1]
        assert st["bytes"] == 8192 * 4  # f32 crosses the device link
        assert st["d2h_bytes"] == 8192 * 4  # host pack: f32 d2h leg
        # ~1 byte/elem rides TCP PLUS the honest overhead: one f32 scale
        # per (stripe, ring chunk) per quantized phase + the op header
        eff = _effective_stripes(8192, cols[0]._stripes)
        assert st["wire_bytes"] == 8192 + _q8_wire_overhead(eff, 2)
        assert st["wire_bytes"] > 8192  # the sidecar is not free
        for c in cols:
            c.shutdown()

    def test_stats_window_is_bounded_at_256(self, store):
        cols = _ring(store, "st3")
        for _ in range(300):
            cols[0]._record_op_stats({"op": "x"})
        assert len(cols[0].pop_op_stats()) == 256
        for c in cols:
            c.shutdown()


class TestShardedStats:
    def test_reduce_scatter_and_allgather_into_stats(self, store):
        cols = _ring(store, "st4", world_size=2, stripes=2)
        tree = {"g": np.ones(50021, np.float32)}

        def sync(r, c):
            sh = c.reduce_scatter(tree, ReduceOp.SUM).wait()
            return c.allgather_into(sh).wait()

        _run_all(cols, sync)
        stats = cols[0].pop_op_stats()
        rs = [s for s in stats if s["op"] == "reduce_scatter"][-1]
        ag = [s for s in stats if s["op"] == "allgather_into"][-1]
        assert rs["bytes"] == 50021 * 4
        # the shard leg scales with 1/world: strictly smaller than full
        assert 0 < rs["shard_bytes"] < rs["bytes"]
        assert rs["wire_bytes"] == rs["bytes"]  # f32 wire
        # numpy input: nothing crossed a device link on either op
        assert rs["d2h_bytes"] == 0
        assert ag["d2h_bytes"] == 0
        assert ag["bytes"] == 50021 * 4
        for st in (rs, ag):
            assert "ring" in st and "stripe_s" in st
        for c in cols:
            c.shutdown()

    def test_sharded_d2h_bytes_with_jax_leaves(self, store):
        import jax.numpy as jnp

        from torchft_tpu.collectives import _q8_wire_overhead

        cols = _ring(store, "st4j", world_size=2, stripes=2)
        tree = {"g": jnp.ones(50021, jnp.float32)}

        def sync(r, c):
            sh = c.reduce_scatter(tree, ReduceOp.SUM, wire="q8").wait()
            return c.allgather_into(sh).wait()

        _run_all(cols, sync)
        stats = cols[0].pop_op_stats()
        rs = [s for s in stats if s["op"] == "reduce_scatter"][-1]
        ag = [s for s in stats if s["op"] == "allgather_into"][-1]
        # the full tree crosses down once; only the owned shard returns
        assert rs["d2h_bytes"] == 50021 * 4
        assert 0 < ag["d2h_bytes"] == rs["shard_bytes"]
        # q8 reduce-scatter runs ONE quantized phase: sidecar + header
        from torchft_tpu.collectives import _effective_stripes

        eff = _effective_stripes(50021, 2)  # q8: 1 byte/element
        assert rs["wire_bytes"] == 50021 + _q8_wire_overhead(
            eff, 2, phases=1
        )
        for c in cols:
            c.shutdown()


class TestIsolatedBackendStats:
    def test_iso_entries_carry_the_parity_keys(self, store):
        # The isolated backend drains through the SAME pop_op_stats
        # contract as the host ring: op / bytes / d2h_bytes on every
        # entry, plus its child-side wall and measured reduction path.
        import jax.numpy as jnp

        from torchft_tpu.isolated_xla import IsolatedXLACollectives

        cols = [
            IsolatedXLACollectives(timeout=timedelta(seconds=20))
            for _ in range(2)
        ]
        addr = f"{store.address()}/isostats"
        try:
            with ThreadPoolExecutor(max_workers=2) as ex:
                list(ex.map(
                    lambda r: cols[r].configure(addr, r, 2), range(2)
                ))
                list(ex.map(
                    lambda r: cols[r].allreduce(
                        {"w": jnp.ones(2048, jnp.float32)}, ReduceOp.AVG
                    ).wait(),
                    range(2),
                ))
            stats = cols[0].pop_op_stats()
            ar = [s for s in stats if s["op"] == "allreduce"][-1]
            assert ar["backend"] == "iso"
            assert ar["bytes"] >= 2048 * 4
            assert ar["d2h_bytes"] == 2048 * 4  # the jax leaf's d2h leg
            assert ar["path"] in ("psum", "store")
            for key in ("pack", "d2h", "ring", "h2d", "child_s"):
                assert key in ar and ar[key] >= 0.0
            cfg = [s for s in stats if s["op"] == "configure"][-1]
            assert {"spawn_s", "child_init_s", "rendezvous_s"} <= set(cfg)
        finally:
            for c in cols:
                c.shutdown()


_XLA_STATS_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    sys.path.insert(0, {repo!r})
    import jax, numpy as np, jax.numpy as jnp
    jax.config.update("jax_platforms", "cpu")
    from datetime import timedelta
    from torchft_tpu import XLACollectives
    from torchft_tpu.collectives import ReduceOp

    rank = int(sys.argv[1]); store_addr = sys.argv[2]
    xc = XLACollectives(timeout=timedelta(seconds=60),
                        connect_timeout=timedelta(seconds=60))
    xc.configure(store_addr + "/q0", rank, 2)
    xc.allreduce({{"w": jnp.ones(1024, jnp.float32)}}, ReduceOp.SUM).wait()
    xc.allgather(jnp.ones(16, jnp.float32)).wait()
    xc.broadcast(jnp.ones(16, jnp.float32)).wait()
    stats = xc.pop_op_stats()
    ops = [s["op"] for s in stats]
    assert "allreduce" in ops and "allgather" in ops and "broadcast" in ops, ops
    ar = [s for s in stats if s["op"] == "allreduce"][-1]
    assert ar["backend"] == "xla"
    assert ar["bytes"] == 1024 * 4
    assert ar["d2h_bytes"] == 1024 * 4  # host-backed results: localize fetch
    for key in ("pack", "ring", "h2d"):
        assert key in ar
    ag = [s for s in stats if s["op"] == "allgather"][-1]
    assert ag["d2h_bytes"] == 16 * 4 * 2  # every member's row fetched
    assert xc.pop_op_stats() == []
    print("XLA-STATS-OK")
    xc.shutdown()
    """
).format(repo=REPO)


@pytest.mark.skipif(not HAS_CPU_MULTIPROCESS, reason=CPU_MULTIPROCESS_SKIP)
class TestXLABackendStats:
    def test_xla_entries_carry_the_parity_keys(self, store):
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _XLA_STATS_WORKER, str(r),
                 store.address()],
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for r in range(2)
        ]
        try:
            outs = [p.communicate(timeout=180)[0] for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out
            assert "XLA-STATS-OK" in out


class TestPlanStats:
    def test_plan_bucket_accounting_matches_payload(self, store):
        cols = _ring(store, "st5", world_size=2, stripes=4)
        rng = np.random.default_rng(1)
        tree = {
            "a": rng.standard_normal(150001).astype(np.float32),
            "b": rng.standard_normal(33).astype(np.float64),
        }
        trees = [tree, {k: v * 2 for k, v in tree.items()}]

        def sync(r, c):
            return c.plan_allreduce(trees[r], ReduceOp.SUM).wait()

        _run_all(cols, sync)  # warmup: plan build
        cols[0].pop_op_stats()
        _run_all(cols, sync)
        st = [
            s for s in cols[0].pop_op_stats()
            if s["op"] == "plan_allreduce"
        ][-1]
        total = 150001 * 4 + 33 * 8
        assert st["bytes"] == total
        # host pack: full-width leaves are what the device link reads
        assert st["d2h_bytes"] == total
        assert st["device_pack"] is False
        assert st["py_staging_allocs"] == 0  # the zero-allocation contract
        assert st["plan_execs"] == 2
        # per-bucket bytes tile the payload exactly — each bucket is one
        # stripe sub-range of its group
        assert sum(b["bytes"] for b in st["buckets"]) == total
        groups = {b["group"] for b in st["buckets"]}
        assert len(groups) == 2  # f32 group striped, f64 group tiny
        for b in st["buckets"]:
            for key in ("pack_s", "ring_s", "unpack_s"):
                assert b[key] >= 0.0
        for c in cols:
            c.shutdown()


class TestShardedPlanLegStats:
    """The per-step ZeRO plan's honest wire accounting: the grad
    reduce-scatter leg and the param allgather leg bill as SEPARATE
    phase keys, each with its own wire_bytes/d2h_bytes, and the plan's
    per-bucket detail tags each bucket with its leg — the data the
    SHARD_BENCH "wins memory/FLOPs, not bytes" caveat is read from."""

    def _sharded_step(self, c, tree, wire=None, ag_wire=None):
        sh = c.plan_reduce_scatter(
            tree, ReduceOp.SUM, divisor=2.0, wire=wire, ag_wire=ag_wire
        ).wait()
        return c.plan_allgather_into(sh, wire=ag_wire).wait()

    def test_f32_legs_bill_separately(self, store):
        cols = _ring(store, "shst", world_size=2, stripes=2)
        tree = {"g": np.ones(50021, np.float32)}
        _run_all(
            cols, lambda r, c: self._sharded_step(c, tree)
        )  # warmup: plan build
        cols[0].pop_op_stats()
        _run_all(cols, lambda r, c: self._sharded_step(c, tree))
        stats = cols[0].pop_op_stats()
        rs = [s for s in stats if s["op"] == "plan_reduce_scatter"][-1]
        ag = [s for s in stats if s["op"] == "plan_allgather_into"][-1]
        assert rs["bytes"] == ag["bytes"] >= 50021 * 4
        # f32 on both legs: each leg's wire carries the full payload once
        assert rs["wire_bytes"] == rs["bytes"]
        assert ag["wire_bytes"] == ag["bytes"]
        # the shard leg scales with 1/world: strictly smaller than full
        assert 0 < rs["shard_bytes"] < rs["bytes"]
        # numpy input: nothing crossed a device link on either leg
        assert rs["d2h_bytes"] == 0 and ag["d2h_bytes"] == 0
        assert rs["py_staging_allocs"] == 0  # zero-allocation contract
        # per-leg bucket tags: the rs entry's window holds grad-leg
        # buckets only; the ag entry appends the param leg's after them,
        # so the pair reads as one step.
        assert {b["leg"] for b in rs["buckets"]} == {1}
        assert {b["leg"] for b in ag["buckets"]} == {1, 2}
        for st in (rs, ag):
            for key in ("d2h", "ring", "h2d"):
                assert st[key] >= 0.0
        for c in cols:
            c.shutdown()

    def test_q8_rs_bf16_ag_wire_bytes(self, store):
        import jax.numpy as jnp

        cols = _ring(store, "shstq", world_size=2, stripes=2)
        tree = {"g": jnp.ones(50021, jnp.float32)}
        _run_all(
            cols,
            lambda r, c: self._sharded_step(
                c, tree, wire="q8", ag_wire="bf16"
            ),
        )
        stats = cols[0].pop_op_stats()
        rs = [s for s in stats if s["op"] == "plan_reduce_scatter"][-1]
        ag = [s for s in stats if s["op"] == "plan_allgather_into"][-1]
        # q8 grad leg: ~1 byte/element + sidecar/header overhead —
        # strictly between a quarter and half of the f32 bill
        assert rs["bytes"] // 4 <= rs["wire_bytes"] < rs["bytes"] // 2
        # bf16 param leg: exactly half the f32 bill
        assert ag["wire_bytes"] == ag["bytes"] // 2
        # jax leaves: the full tree crosses down on the grad leg; only
        # the updated shard crosses down on the param leg
        assert rs["d2h_bytes"] == rs["bytes"]
        assert 0 < ag["d2h_bytes"] == rs["shard_bytes"]
        for c in cols:
            c.shutdown()


class TestHierStats:
    """The two-tier schedule's accounting: per-tier phase keys
    (intra_rs_s / inter_ring_s / intra_ag_s / intra_bcast_s) and per-tier
    MEASURED tx bytes (duplex's per-connection counters, summed) — the
    numbers that make the inter-tier byte reduction directly observable
    instead of modeled."""

    def _hier_ring(self, store, regions, **kwargs):
        cols = [
            HostCollectives(timeout=timedelta(seconds=15), **kwargs)
            for _ in regions
        ]
        addr = f"{store.address()}/hier"
        with ThreadPoolExecutor(max_workers=len(regions)) as ex:
            for f in [
                ex.submit(cols[r].configure, addr, r, len(regions), regions)
                for r in range(len(regions))
            ]:
                f.result()
        return cols

    def test_bulk_hier_per_tier_keys_and_bytes(self, store):
        regions = ["a", "a", "b", "b"]
        count = 30_000
        cols = self._hier_ring(store, regions)
        datas = [np.full(count, float(r + 1), np.float32) for r in range(4)]
        _run_all(
            cols, lambda r, c: c.allreduce_hier(datas[r].copy()).wait()
        )
        stats = [c.pop_op_stats()[-1] for c in cols]
        payload = count * 4
        for r, st in enumerate(stats):
            assert st["op"] == "allreduce_hier"
            assert st["bytes"] == payload
            for k in ("intra_rs_s", "intra_ag_s", "inter_ring_s",
                      "intra_bcast_s"):
                assert k in st
            # total wire bill = measured intra + inter traffic
            tiers = st["tiers"]
            assert st["wire_bytes"] == (
                tiers["intra"]["tx_bytes"] + tiers["inter"]["tx_bytes"]
            )
        # leaders (ranks 0, 2): each inter ring phase ships (L-1)/L of the
        # payload — here L=2, so N/2 per phase, measured within a couple
        # percent (op headers + a q8-free wire have no other overhead)
        for r in (0, 2):
            inter = stats[r]["tiers"]["inter"]
            for k in ("rs_tx_bytes", "ag_tx_bytes"):
                assert payload // 2 <= inter[k] <= payload // 2 + 512
        # non-leaders never send on the inter tier
        for r in (1, 3):
            assert stats[r]["tiers"]["inter"]["tx_bytes"] == 0
        for c in cols:
            c.shutdown()

    def test_q8_inter_wire_quarters_the_slow_link(self, store):
        # wire="q8": the inter hop ships ~1 byte/element + per-chunk
        # scales; intra stays full f32. The measured ratio is the
        # tentpole's bytes story in one assert.
        regions = ["a", "a", "b", "b"]
        count = 40_000
        cols = self._hier_ring(store, regions)
        datas = [
            np.linspace(0, 1, count, dtype=np.float32) * (r + 1)
            for r in range(4)
        ]
        _run_all(
            cols,
            lambda r, c: c.allreduce_hier(datas[r].copy(), wire="q8").wait(),
        )
        st = cols[0].pop_op_stats()[-1]
        inter = st["tiers"]["inter"]
        f32_phase = count * 4 // 2  # what the f32 inter wire would ship
        assert inter["rs_tx_bytes"] < f32_phase * 0.30, (
            f"q8 inter phase shipped {inter['rs_tx_bytes']} B, f32 would "
            f"ship {f32_phase}"
        )
        for c in cols:
            c.shutdown()

    def test_hier_plan_entry_carries_tier_breakdown(self, store):
        regions = ["a", "b", "b"]
        cols = self._hier_ring(store, regions)
        tree = {"g": np.ones(9_000, np.float32)}
        _run_all(
            cols,
            lambda r, c: c.plan_allreduce(
                tree, ReduceOp.SUM, divisor=3.0, hier=True
            ).wait(),
        )
        st = cols[0].pop_op_stats()[-1]
        assert st["op"] == "plan_allreduce"
        assert st["hier"] is True
        assert st["py_staging_allocs"] == 0
        for k in ("intra_rs_s", "inter_ring_s", "intra_ag_s",
                  "intra_bcast_s", "tiers", "buckets"):
            assert k in st
        assert st["wire_bytes"] == (
            st["tiers"]["intra"]["tx_bytes"]
            + st["tiers"]["inter"]["tx_bytes"]
        )
        for c in cols:
            c.shutdown()


class TestShmTierStats:
    """The third (intra-host shm) tier's accounting contract: shm hops
    record phase TIME but contribute ZERO tx/wire bytes (nothing is
    handed to the kernel), the TCP tiers' measured bytes are unchanged by
    the host tier's presence, and d2h accounting is transport-blind."""

    def _ring(self, store, regions, hosts, prefix, **kwargs):
        world = len(hosts if hosts is not None else regions)
        cols = [
            HostCollectives(timeout=timedelta(seconds=15), **kwargs)
            for _ in range(world)
        ]
        addr = f"{store.address()}/{prefix}"
        with ThreadPoolExecutor(max_workers=world) as ex:
            for f in [
                ex.submit(cols[r].configure, addr, r, world, regions, hosts)
                for r in range(world)
            ]:
                f.result()
        return cols

    def test_shm_hops_record_time_but_zero_wire_bytes(self, store):
        regions = ["a", "a", "b", "b"]
        hosts = ["h0", "h0", "h1", "h1"]
        count = 30_000
        cols = self._ring(store, regions, hosts, "shmstats")
        datas = [np.full(count, float(r + 1), np.float32) for r in range(4)]
        _run_all(
            cols, lambda r, c: c.allreduce_hier(datas[r].copy()).wait()
        )
        payload = count * 4
        for r, st in enumerate(c.pop_op_stats()[-1] for c in cols):
            assert st["op"] == "allreduce_hier"
            # shm phase keys present and the phases really ran
            for k in ("shm_rs_s", "shm_ag_s", "shm_bcast_s"):
                assert k in st, f"rank {r} missing {k}"
            host = st["tiers"]["host"]
            assert host["transport"] == "shm"
            assert host["world"] == 2
            assert host["rs_s"] > 0 and host["ag_s"] > 0
            # honest zero-tx accounting: the shm tier hands NOTHING to
            # the kernel...
            assert host["tx_bytes"] == 0
            # ...while the ring movement is still measured (rs + ag + the
            # broadcast all move ~payload each within the 2-member group,
            # plus 16-byte frame headers)
            assert host["shm_bytes"] > payload
            # and wire_bytes (the kernel bill) is exactly the TCP tiers'
            assert st["wire_bytes"] == (
                st["tiers"]["intra"]["tx_bytes"]
                + st["tiers"]["inter"]["tx_bytes"]
            )
        for c in cols:
            c.shutdown()

    def test_tcp_tiers_unchanged_by_host_tier(self, store):
        # The inter (region-leader) tier's measured slow-link bill must
        # be IDENTICAL with and without the host tier below it: the host
        # tier changes where the region sum is computed, not what crosses
        # the slow links. (With one host per region the intra tier is
        # empty in the hosted config — each region's lone host group IS
        # the region — so the comparison pins the inter tier.)
        regions = ["a", "a", "b", "b"]
        count = 30_000
        datas = [np.full(count, float(r + 1), np.float32) for r in range(4)]

        cols = self._ring(store, regions, None, "nohost")
        _run_all(
            cols, lambda r, c: c.allreduce_hier(datas[r].copy()).wait()
        )
        flat_stats = [c.pop_op_stats()[-1] for c in cols]
        for c in cols:
            c.shutdown()

        cols = self._ring(store, regions, ["h0", "h0", "h1", "h1"], "hosted")
        _run_all(
            cols, lambda r, c: c.allreduce_hier(datas[r].copy()).wait()
        )
        host_stats = [c.pop_op_stats()[-1] for c in cols]
        for c in cols:
            c.shutdown()

        for r in range(4):
            a = flat_stats[r]["tiers"]["inter"]
            b = host_stats[r]["tiers"]["inter"]
            for k in ("tx_bytes", "rs_tx_bytes", "ag_tx_bytes", "world"):
                assert a[k] == b[k], (
                    f"rank {r} inter[{k}] drifted: {a[k]} vs {b[k]}"
                )

    def test_d2h_bytes_identical_across_shm_and_tcp_schedules(
        self, store, monkeypatch
    ):
        # d2h accounting is transport-blind: the device->host leg happens
        # before any tier runs, so the shm and loopback-TCP host tiers
        # must bill identical d2h_bytes for identical trees.
        import jax.numpy as jnp

        hosts = ["h0", "h0"]
        count = 4096

        def measure(prefix):
            cols = self._ring(store, None, hosts, prefix)
            tree = {"g": jnp.ones((count,), jnp.float32)}
            _run_all(cols, lambda r, c: c.allreduce_hier(dict(tree)).wait())
            out = [c.pop_op_stats()[-1] for c in cols]
            for c in cols:
                c.shutdown()
            return out

        shm_stats = measure("d2h_shm")
        assert shm_stats[0]["tiers"]["host"]["transport"] == "shm"
        monkeypatch.setenv("TORCHFT_HC_SHM", "0")
        tcp_stats = measure("d2h_tcp")
        assert tcp_stats[0]["tiers"]["host"]["transport"] == "tcp"
        for r in range(2):
            assert shm_stats[r]["d2h_bytes"] == count * 4
            assert shm_stats[r]["d2h_bytes"] == tcp_stats[r]["d2h_bytes"]
            assert shm_stats[r]["bytes"] == tcp_stats[r]["bytes"]
            # the TCP fallback's host hops DO hit the kernel — the
            # honest contrast to the shm tier's zero
            assert tcp_stats[r]["tiers"]["host"]["tx_bytes"] > 0
            assert shm_stats[r]["tiers"]["host"]["tx_bytes"] == 0
