// Native concurrency stress harness, built to be run under TSan and
// ASan+UBSan (make -C native stress SANITIZE=...; scripts/sanitize.sh).
//
// Provokes exactly the interleavings the striped ring's hot paths are
// documented to survive but ordinary tests rarely hit:
//   - W HostCollectives ranks on threads, reconfiguring every round on a
//     fresh store prefix while a chaos thread abort()s random instances
//     mid-op (the stripe-abort wake-all path);
//   - persistent comm plans built/executed/freed each round, invalidated
//     by the next configure (the plan-invalidation path);
//   - a store client hammer thread running set/get/add against the same
//     StoreServer the rings rendezvous through;
//   - lighthouse + manager churn: long-poll quorums cancelled by shutdown
//     (the ConnTracker shutdown_all / condvar-cancel paths).
//
// Chaos rounds only assert liveness (ops either succeed or throw; nothing
// hangs, nothing trips a sanitizer). The final chaos-free rounds assert
// CORRECTNESS: allreduce sums, plan averages and decomposed reduce-scatter
// + allgather-into must produce exact expected values.
//
// Usage: stress_native [rounds] [world] [stripes] [elems]
//   defaults: 12 rounds (last 3 chaos-free), world 3, stripes 2, 49152
//   elems (~192 KB f32: big enough for 2 effective stripes, small enough
//   that a TSan run stays in seconds).
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

#include "collectives.h"
#include "lighthouse.h"
#include "manager.h"
#include "net.h"
#include "region.h"
#include "shm.h"
#include "store.h"
#include "thread_annotations.h"
#include "wire.h"

namespace {

using namespace tft;

struct Barrier {
  explicit Barrier(int n) : n_(n) {}
  void arrive_and_wait() {
    UniqueMutexLock lock(mu_);
    int64_t gen = gen_;
    if (++count_ == n_) {
      count_ = 0;
      gen_++;
      cv_.notify_all();
      return;
    }
    while (gen_ == gen) cv_.wait(lock);
  }

 private:
  Mutex mu_;
  CondVar cv_;
  const int n_;
  int count_ TFT_GUARDED_BY(mu_) = 0;
  int64_t gen_ TFT_GUARDED_BY(mu_) = 0;
};

void sleep_ms(int64_t ms) {
  std::this_thread::sleep_for(std::chrono::milliseconds(ms));
}

std::atomic<long> g_ok{0}, g_failed{0}, g_checks{0};
std::atomic<bool> g_bad{false};

void expect(bool cond, const char* what) {
  g_checks++;
  if (!cond) {
    fprintf(stderr, "CHECK FAILED: %s\n", what);
    g_bad = true;
  }
}

// One rank's round: configure on the round's prefix, then a fixed op
// program. Any op failure (chaos abort, ring FIN from a sibling's abort)
// kills the rest of the round — the ring is dead until the next configure,
// which is exactly the production discipline.
void run_rank_round(HostCollectives& hc, int64_t rank, int64_t world,
                    size_t elems, bool chaos, int round) {
  const int64_t timeout = 8000;
  std::vector<float> data(elems);
  std::vector<float> shard(elems);  // >= shard size
  std::vector<float> gathered(elems);

  try {
    // allreduce f32: rank r contributes r+1 everywhere.
    for (size_t i = 0; i < elems; i++) data[i] = static_cast<float>(rank + 1);
    hc.allreduce(data.data(), elems, Dtype::kF32, ReduceOp::kSum, timeout);
    if (!chaos) {
      float want = static_cast<float>(world * (world + 1) / 2);
      expect(data[0] == want && data[elems - 1] == want,
             "allreduce f32 sum mismatch");
    }
    g_ok++;

    // quantized ring.
    for (size_t i = 0; i < elems; i++) data[i] = static_cast<float>(rank + 1);
    hc.allreduce_q8(data.data(), elems, timeout);
    if (!chaos)
      expect(std::fabs(data[0] - world * (world + 1) / 2.0f) <
                 0.2f * world,
             "allreduce q8 sum out of quantization class");
    g_ok++;

    // decomposed reduce-scatter + allgather_into == fused allreduce.
    for (size_t i = 0; i < elems; i++)
      data[i] = static_cast<float>((i % 31) + rank);
    hc.reduce_scatter(data.data(), elems, Dtype::kF32, ReduceOp::kSum,
                      shard.data(), /*layout_stripes=*/0, timeout);
    // The per-rank shards must tile the payload exactly (the invariant
    // every sharded consumer leans on).
    size_t tiled = 0;
    for (int64_t r = 0; r < world; r++)
      for (auto [st, len] : hc.shard_ranges(elems, sizeof(float), r, 0))
        tiled += len, (void)st;
    expect(tiled == elems, "shard_ranges do not tile the payload");
    hc.allgather_into(shard.data(), gathered.data(), elems, Dtype::kF32,
                      /*layout_stripes=*/0, timeout);
    if (!chaos) {
      bool same = true;
      for (size_t i = 0; i < elems && same; i++) {
        float want = 0;
        for (int64_t r = 0; r < world; r++)
          want += static_cast<float>((i % 31) + r);
        same = gathered[i] == want;
      }
      expect(same, "reduce_scatter + allgather_into != expected sum");
    }
    g_ok++;

    // Persistent comm plan: two leaves, wire rotating per round (native /
    // bf16 / q8 / q8+EF), executed thrice so the q8ef residual carries.
    int64_t counts[2] = {static_cast<int64_t>(elems / 2),
                         static_cast<int64_t>(elems - elems / 2)};
    int32_t dtypes[2] = {static_cast<int32_t>(Dtype::kF32),
                         static_cast<int32_t>(Dtype::kF32)};
    PlanWire wire = static_cast<PlanWire>(round % 4);
    int64_t plan = hc.plan_build(counts, dtypes, 2, wire);
    const void* ins[2] = {data.data(), data.data() + counts[0]};
    void* outs[2] = {gathered.data(), gathered.data() + counts[0]};
    for (int it = 0; it < 3; it++) {
      for (size_t i = 0; i < elems; i++)
        data[i] = static_cast<float>(rank + 1) * 0.5f;
      hc.plan_execute(plan, ins, outs, static_cast<double>(world),
                      /*has_divisor=*/true, timeout);
      if (!chaos && wire == PlanWire::kNative)
        expect(std::fabs(gathered[0] -
                         0.5f * (world + 1) / 2.0f) < 1e-6,
               "plan_execute native average mismatch");
    }
    (void)hc.plan_stats_json(plan);
    hc.plan_reset_feedback(plan);
    hc.plan_free(plan);
    g_ok++;

    // control-plane-sized ops.
    int64_t token = rank;
    std::vector<int64_t> all(world);
    hc.allgather(&token, all.data(), sizeof(token), timeout);
    if (!chaos)
      expect(all[0] == 0 && all[world - 1] == world - 1,
             "allgather rank order mismatch");
    hc.broadcast(&token, sizeof(token), /*root=*/0, timeout);
    if (!chaos) expect(token == 0, "broadcast root value mismatch");
    hc.barrier(timeout);
    g_ok++;
  } catch (const std::exception&) {
    // Chaos abort (or its ring-wide FIN) — expected; the ring stays dead
    // until the next round's configure.
    g_failed++;
  }
}

void collectives_stress(int rounds, int world, int stripes, size_t elems) {
  StoreServer store("[::]:0");
  std::string store_addr =
      "localhost:" + std::to_string(store.port());

  std::vector<std::unique_ptr<HostCollectives>> hcs;
  for (int r = 0; r < world; r++)
    hcs.push_back(std::make_unique<HostCollectives>());

  Barrier barrier(world);
  std::atomic<bool> stop{false};
  std::atomic<int> in_ops{0};
  const int chaos_until = rounds > 3 ? rounds - 3 : 0;
  std::atomic<int> cur_round{0};

  // Chaos: abort a random instance only while every rank is inside the op
  // phase — an abort landing in configure's rendezvous would stall the
  // round on the store timeout instead of exercising the wake paths.
  std::thread chaos([&] {
    std::mt19937 rng(0xC0FFEE);
    while (!stop) {
      sleep_ms(2 + static_cast<int64_t>(rng() % 12));
      if (cur_round.load() < chaos_until && in_ops.load() == world)
        hcs[rng() % world]->abort();
    }
  });

  // Store hammer: concurrent set/get/add against the rendezvous server.
  std::thread hammer([&] {
    try {
      StoreClient c(store_addr, 5000);
      int i = 0;
      while (!stop) {
        std::string k = "hammer/" + std::to_string(i % 8);
        c.set(k, std::to_string(i), 5000);
        expect(!c.get(k, 5000).empty(), "store get after set empty");
        c.add("hammer/ctr", 1, 5000);
        i++;
        sleep_ms(1);
      }
    } catch (const std::exception& e) {
      fprintf(stderr, "store hammer died: %s\n", e.what());
      g_bad = true;
    }
  });

  std::vector<std::thread> ranks;
  for (int64_t r = 0; r < world; r++) {
    ranks.emplace_back([&, r] {
      for (int round = 0; round < rounds; round++) {
        barrier.arrive_and_wait();
        if (r == 0) cur_round = round;
        bool chaos_round = round < chaos_until;
        std::string prefix =
            store_addr + "/stress/" + std::to_string(round);
        bool configured = false;
        for (int attempt = 0; attempt < 2 && !configured; attempt++) {
          try {
            hcs[r]->configure(prefix + "/" + std::to_string(attempt), r,
                              world, 15000, stripes);
            configured = true;
          } catch (const std::exception&) {
            g_failed++;
          }
        }
        expect(configured, "configure failed twice in one round");
        barrier.arrive_and_wait();
        in_ops++;
        if (configured)
          run_rank_round(*hcs[r], r, world, elems, chaos_round, round);
        in_ops--;
        barrier.arrive_and_wait();
      }
    });
  }
  for (auto& t : ranks) t.join();
  stop = true;
  chaos.join();
  hammer.join();

  // Destructor order deliberately tears rings down while instances still
  // exist (abort + pool drain under sanitizers).
  hcs.clear();
  store.shutdown();
}

// Durable-root churn: repeated INCARNATIONS of a WAL'd lighthouse on one
// log directory, each hammered by concurrent lease-renew/depart/heartbeat
// threads while quorums form — then torn down and recovered. Asserts the
// durability contract under concurrency: the recovered quorum_id
// watermark never regresses across incarnations, and a warm standby that
// takes over after the last incarnation holds a watermark >= it too.
// (Run under TSan this also exercises the WAL append path racing the
// handler threads through the lighthouse lock.)
void durable_root_churn(int iters) {
  char tmpl[] = "/tmp/tft_stress_walXXXXXX";
  char* dir = mkdtemp(tmpl);
  expect(dir != nullptr, "mkdtemp failed");
  std::string wal_dir(dir);

  int64_t watermark = 0;
  std::string last_addr;
  for (int i = 0; i < iters; i++) {
    LighthouseOpt opt;
    opt.min_replicas = 1;
    opt.join_timeout_ms = 50;
    opt.quorum_tick_ms = 10;
    opt.heartbeat_timeout_ms = 2000;
    opt.wal_dir = wal_dir;
    opt.snapshot_every = 8;  // force compactions under churn
    Lighthouse lh("[::]:0", opt);
    last_addr = lh.address();

    // The recovered watermark must carry over from the last incarnation.
    // (status_json parse kept simple: the accessor is the contract.)
    std::vector<std::thread> ts;
    for (int w = 0; w < 3; w++) {
      ts.emplace_back([&, w] {
        try {
          LighthouseClient c(last_addr, 3000);
          for (int k = 0; k < 6; k++) {
            std::vector<LeaseEntry> entries(1);
            entries[0].replica_id = "g" + std::to_string(w);
            entries[0].ttl_ms = 60000;
            entries[0].participating = true;
            entries[0].member.set_replica_id("g" + std::to_string(w));
            entries[0].member.set_address("a:1");
            entries[0].member.set_store_address("a:2");
            entries[0].member.set_step(i);
            entries[0].member.set_world_size(1);
            int64_t qid = c.lease_renew(entries, 3000);
            expect(qid >= watermark, "quorum_id regressed under churn");
            if (k == 4 && w == 2) c.depart(entries[0].replica_id, 3000);
          }
          g_ok++;
        } catch (const std::exception&) {
          g_failed++;
        }
      });
    }
    for (auto& t : ts) t.join();
    sleep_ms(50);  // let a tick commit the registrations
    int64_t qid_now = 0;
    {
      // recover-side check rides the next incarnation; here just read
      // the epoch accessors (they take the service lock — the TSan
      // surface this round exists for).
      expect(lh.active(), "wal'd root not active");
      expect(lh.root_epoch() == i + 1, "root epoch not monotone");
      qid_now = watermark;
    }
    lh.shutdown();
    WalRecovery rec = DurableLog::recover(wal_dir, now_ms(), unix_ms());
    expect(rec.state.quorum_id >= qid_now,
           "recovered watermark regressed across incarnation");
    watermark = rec.state.quorum_id;
    g_checks++;
  }

  // Final: a standby takes over from a live primary and holds the line.
  {
    LighthouseOpt opt;
    opt.min_replicas = 1;
    opt.join_timeout_ms = 50;
    opt.quorum_tick_ms = 10;
    opt.heartbeat_timeout_ms = 2000;
    opt.wal_dir = wal_dir;
    auto primary = std::make_unique<Lighthouse>("[::]:0", opt);
    LighthouseOpt sopt = opt;
    sopt.wal_dir.clear();  // in-memory standby: epochs still fence
    sopt.peers = primary->address();
    sopt.standby = true;
    sopt.takeover_ms = 400;
    Lighthouse standby("[::]:0", sopt);
    expect(!standby.active(), "standby started active");
    sleep_ms(200);  // one sync
    primary->shutdown();
    primary.reset();
    int64_t deadline = now_ms() + 10000;
    while (!standby.active() && now_ms() < deadline) sleep_ms(20);
    expect(standby.active(), "standby never took over");
    expect(standby.root_epoch() > iters, "takeover epoch not above primary");
    g_checks++;
  }

  // best-effort cleanup of the tmp dir
  ::remove((wal_dir + "/wal.log").c_str());
  ::remove((wal_dir + "/snapshot.json").c_str());
  ::remove(wal_dir.c_str());
}

void control_plane_churn(int iters) {
  for (int i = 0; i < iters; i++) {
    LighthouseOpt opt;
    opt.min_replicas = 2;
    opt.join_timeout_ms = 50;
    opt.quorum_tick_ms = 10;
    opt.heartbeat_timeout_ms = 500;
    Lighthouse lh("[::]:0", opt);
    std::string addr = lh.address();

    // Two members long-poll a quorum that completes.
    std::thread a([&] {
      try {
        torchft_tpu::QuorumMember m;
        m.set_replica_id("A");
        m.set_address("a:1");
        m.set_store_address("a:2");
        m.set_step(i);
        m.set_world_size(1);
        LighthouseClient(addr, 3000).quorum(m, 5000);
        g_ok++;
      } catch (const std::exception&) {
        g_failed++;
      }
    });
    std::thread b([&] {
      try {
        torchft_tpu::QuorumMember m;
        m.set_replica_id("B");
        m.set_address("b:1");
        m.set_store_address("b:2");
        m.set_step(i);
        m.set_world_size(1);
        LighthouseClient(addr, 3000).quorum(m, 5000);
        g_ok++;
      } catch (const std::exception&) {
        g_failed++;
      }
    });
    // Heartbeats ride the persistent-connection path concurrently.
    std::thread hb([&] {
      try {
        LighthouseClient c(addr, 3000);
        for (int k = 0; k < 5; k++) c.heartbeat("hb", 2000);
      } catch (const std::exception&) {
        g_failed++;
      }
    });
    a.join();
    b.join();
    hb.join();

    // A long-poll that can never complete (only one member of two),
    // cancelled by shutdown: the handler must wake and the tracker drain.
    std::thread lone([&] {
      try {
        torchft_tpu::QuorumMember m;
        m.set_replica_id("lone");
        m.set_address("l:1");
        m.set_store_address("l:2");
        m.set_step(0);
        m.set_world_size(1);
        LighthouseClient(addr, 3000).quorum(m, 10000);
        g_failed++;  // should have been cancelled
      } catch (const std::exception&) {
        g_ok++;  // CANCELLED (or the connection died with the server)
      }
    });
    sleep_ms(30);
    lh.shutdown();
    lone.join();
  }

  // Manager churn: world_size=2 local ranks vote, then a shutdown lands
  // while a quorum long-poll is parked (rank 1 never arrives).
  for (int i = 0; i < iters; i++) {
    LighthouseOpt opt;
    opt.min_replicas = 1;
    opt.join_timeout_ms = 50;
    opt.quorum_tick_ms = 10;
    opt.heartbeat_timeout_ms = 2000;
    Lighthouse lh("[::]:0", opt);
    StoreServer store("[::]:0");
    ManagerServer ms("stress", lh.address(), "localhost", "[::]:0",
                     store.address(), /*world_size=*/2,
                     /*heartbeat_interval_ms=*/20, /*connect_timeout_ms=*/3000);
    std::string maddr = ms.address();

    std::thread r0([&] {
      try {
        ManagerClient c(maddr, 3000);
        auto resp = c.quorum(0, i, "meta0", false, false, 5000);
        expect(resp.replica_world_size() >= 1, "manager quorum world empty");
        expect(c.should_commit(0, i, true, 5000),
               "unanimous should_commit returned false");
        g_ok++;
      } catch (const std::exception&) {
        g_failed++;
      }
    });
    std::thread r1([&] {
      try {
        ManagerClient c(maddr, 3000);
        c.quorum(1, i, "meta1", false, false, 5000);
        c.should_commit(1, i, true, 5000);
        g_ok++;
      } catch (const std::exception&) {
        g_failed++;
      }
    });
    r0.join();
    r1.join();

    std::thread parked([&] {
      try {
        ManagerClient c(maddr, 3000);
        c.quorum(0, i + 1, "meta", false, false, 10000);
        g_failed++;  // rank 1 never joins; only shutdown can end this
      } catch (const std::exception&) {
        g_ok++;
      }
    });
    sleep_ms(30);
    ms.shutdown();
    parked.join();
    store.shutdown();
    lh.shutdown();
  }
}

// Hierarchical-tier churn: a root + two region lighthouses with lease
// batchers, quorum long-polls through the regions, and chaos that kills a
// region mid-flight (its digest/poll connections die while the root keeps
// serving) plus a root long-poll cancelled by shutdown. Exercises the new
// guarded state: region digest/poll loops vs concurrent handler threads,
// root digest-apply vs tick vs region-poll waiters, lease batch application
// under renewal hammering.
void hierarchical_churn(int iters) {
  for (int i = 0; i < iters; i++) {
    LighthouseOpt opt;
    opt.min_replicas = 2;
    opt.join_timeout_ms = 50;
    opt.quorum_tick_ms = 10;
    opt.heartbeat_timeout_ms = 800;
    Lighthouse root("[::]:0", opt);
    std::string root_addr = root.address();

    RegionOpt ropt;
    ropt.digest_interval_ms = 20;
    ropt.heartbeat_timeout_ms = 800;
    ropt.connect_timeout_ms = 2000;
    auto ra = std::make_unique<RegionLighthouse>("[::]:0", root_addr, "ra", ropt);
    auto rb = std::make_unique<RegionLighthouse>("[::]:0", root_addr, "rb", ropt);
    std::string ra_addr = ra->address();
    std::string rb_addr = rb->address();

    std::atomic<bool> stop{false};

    // Lease batcher hammering region A with participating renewals for a
    // flock of simulated groups (the region's digest path under load).
    std::thread batcher([&] {
      try {
        LighthouseClient c(ra_addr, 2000);
        int k = 0;
        while (!stop) {
          std::vector<LeaseEntry> entries;
          for (int g = 0; g < 4; g++) {
            LeaseEntry e;
            e.replica_id = "sim" + std::to_string(g);
            e.ttl_ms = 500;
            e.participating = false;
            entries.push_back(std::move(e));
          }
          c.lease_renew(entries, 2000);
          if (++k % 5 == 0) c.heartbeat("hb-sim", 2000);
          sleep_ms(5);
        }
      } catch (const std::exception&) {
        // region A dies mid-run by design; renewals after that just fail
      }
    });

    // Two members quorum through DIFFERENT regions: the digest + root
    // aggregation + region poll republish path end to end.
    std::thread qa([&] {
      try {
        torchft_tpu::QuorumMember m;
        m.set_replica_id("A");
        m.set_address("a:1");
        m.set_store_address("a:2");
        m.set_step(i);
        m.set_world_size(1);
        LighthouseClient(ra_addr, 2000).quorum(m, 4000);
        g_ok++;
      } catch (const std::exception&) {
        g_failed++;
      }
    });
    std::thread qb([&] {
      try {
        torchft_tpu::QuorumMember m;
        m.set_replica_id("B");
        m.set_address("b:1");
        m.set_store_address("b:2");
        m.set_step(i);
        m.set_world_size(1);
        LighthouseClient(rb_addr, 2000).quorum(m, 4000);
        g_ok++;
      } catch (const std::exception&) {
        g_failed++;
      }
    });
    qa.join();
    qb.join();

    // Region chaos: kill region A while a long-poll is parked on it and
    // its batcher is mid-renewal; the waiter must be CANCELLED (not hang),
    // the root must keep serving region B.
    std::thread parked([&] {
      try {
        torchft_tpu::QuorumMember m;
        m.set_replica_id("lone");
        m.set_address("l:1");
        m.set_store_address("l:2");
        m.set_step(0);
        m.set_world_size(1);
        LighthouseClient(ra_addr, 2000).quorum(m, 8000);
        g_failed++;  // only region death can end this (B won't re-join)
      } catch (const std::exception&) {
        g_ok++;  // CANCELLED or connection died with the region
      }
    });
    sleep_ms(30);
    ra->shutdown();
    parked.join();
    ra.reset();

    // Root long-poll cancel: park a region-style poller directly on the
    // root (no new quorum will form), then shut the root down under it.
    std::thread root_poll([&] {
      try {
        Socket sock = connect_with_retry(root_addr, 2000);
        torchft_tpu::RegionPollRequest req;
        req.set_min_gen(1000000);  // newer than anything: parks forever
        req.set_timeout_ms(8000);
        send_msg(sock, MsgType::kRegionPollReq, req);
        recv_expect<torchft_tpu::RegionPollResponse>(sock,
                                                     MsgType::kRegionPollResp);
        g_failed++;  // should have been cancelled
      } catch (const std::exception&) {
        g_ok++;
      }
    });
    sleep_ms(30);
    stop = true;
    batcher.join();
    root.shutdown();
    root_poll.join();
    rb->shutdown();
    rb.reset();
  }
}

// Isolated-data-plane segment churn: the shm lifecycle under the exact
// patterns a SIGKILLed child leaves behind — attachments abandoned
// mid-protocol, names unlinked while mappings are live (the respawn
// path's defensive unlink), concurrent attach/read/write/detach across
// member threads, and the layout export hammered from every thread. The
// guarded registry (g_shm_mu / g_live in shm.cc) is the shared state
// under test; chaos rounds assert liveness, the final round asserts
// exact data integrity through the segment, and the whole churn must
// end with zero leaked handles.
void shm_churn(int iters, int world) {
  const size_t elems = 4096;
  int64_t base_live = ShmSegment::live_count();
  float parent_sum = 0;
  for (size_t k = 0; k < elems; k++) parent_sum += static_cast<float>(k % 97);

  for (int i = 0; i < iters; i++) {
    bool chaos = i + 1 < iters;  // last round is chaos-free: exact checks
    std::string name =
        "tft_stress_shm_" + std::to_string(getpid()) + "_" + std::to_string(i);
    std::unique_ptr<ShmSegment> seg(
        ShmSegment::Create(name, elems * sizeof(float) * (world + 1)));
    float* parent_block = static_cast<float*>(seg->data());
    for (size_t k = 0; k < elems; k++)
      parent_block[k] = static_cast<float>(k % 97);

    std::vector<std::thread> members;
    for (int r = 0; r < world; r++) {
      members.emplace_back([&, r] {
        try {
          std::unique_ptr<ShmSegment> att(ShmSegment::Attach(
              name, elems * sizeof(float) * (world + 1)));
          float* p = static_cast<float*>(att->data());
          float sum = 0;
          for (size_t k = 0; k < elems; k++) sum += p[k];
          if (!chaos)
            expect(sum == parent_sum, "shm parent block corrupted");
          float* mine = p + elems * (r + 1);
          for (size_t k = 0; k < elems; k++)
            mine[k] = static_cast<float>(r + 1) + static_cast<float>(k % 7);
          if (chaos && r == 0) {
            g_ok++;
            return;  // abandon mid-protocol: the SIGKILLed-child shape
          }
          // the layout export is lock-free pure arithmetic; hammer it
          // concurrently with segment churn
          int64_t counts[3] = {100, 7, 33};
          int32_t codes[3] = {0, 2, 0};
          std::string lay = shm_layout_json(counts, codes, 3, /*wire=*/0);
          expect(lay.find("total_bytes") != std::string::npos,
                 "shm layout json malformed");
          g_ok++;
        } catch (const std::exception&) {
          // chaos unlink races Attach: ENOENT is the expected casualty
          g_failed++;
        }
      });
    }
    if (chaos) {
      // Unlink while attachments live (and possibly while Attach races
      // us): existing mappings stay valid, late attachers fail cleanly.
      ShmSegment::Unlink(name);
    }
    for (auto& t : members) t.join();
    if (!chaos) {
      for (int r = 0; r < world; r++) {
        float* mine = parent_block + elems * (r + 1);
        expect(mine[0] == static_cast<float>(r + 1) &&
                   mine[elems - 1] == static_cast<float>(r + 1) +
                                          static_cast<float>((elems - 1) % 7),
               "shm member reply block corrupted");
      }
    }
    seg.reset();  // creator destructor: idempotent unlink after chaos
  }
  expect(ShmSegment::live_count() == base_live, "shm handles leaked");
}

// Hierarchical collectives churn: W ranks with region AND host labels
// reconfigure per round — both label sets ROTATE so region membership,
// host grouping (and therefore LEADERSHIP at both tiers) move across
// reconfigures, exercising shared-memory ring creation/attachment/
// teardown under churn — then run the hier ops per wire plus a hier
// q8ef plan (the leader-carry path), under a chaos thread that aborts
// rank 0 preferentially (a region leader in every rotation): a dead
// leader must error every tier (including co-hosted shm peers, woken by
// the poisoned ring magic) within the op deadline and the next round's
// configure must revive the full topology. Clean rounds assert exact
// sums on the native wire; the live-segment count is asserted back at
// its baseline at the end (the churn leak oracle).
void hier_collectives_churn(int rounds, int world, int stripes,
                            size_t elems) {
  if (world < 2) return;
  const int64_t shm_base = ShmSegment::live_count();
  StoreServer store("[::]:0");
  std::string store_addr = "localhost:" + std::to_string(store.port());

  std::vector<std::unique_ptr<HostCollectives>> hcs;
  for (int r = 0; r < world; r++)
    hcs.push_back(std::make_unique<HostCollectives>());

  Barrier barrier(world);
  std::atomic<bool> stop{false};
  std::atomic<int> in_ops{0};
  const int chaos_until = rounds > 2 ? rounds - 2 : 0;
  std::atomic<int> cur_round{0};

  std::thread chaos([&] {
    std::mt19937 rng(0xBADC0DE);
    while (!stop) {
      sleep_ms(2 + static_cast<int64_t>(rng() % 10));
      if (cur_round.load() < chaos_until && in_ops.load() == world)
        hcs[rng() % 2 == 0 ? 0 : rng() % world]->abort();
    }
  });

  std::vector<std::thread> ranks;
  for (int64_t r = 0; r < world; r++) {
    ranks.emplace_back([&, r] {
      const int64_t timeout = 8000;
      std::vector<float> data(elems), out(elems);
      for (int round = 0; round < rounds; round++) {
        barrier.arrive_and_wait();
        if (r == 0) cur_round = round;
        bool chaos_round = round < chaos_until;
        std::vector<std::string> regions(world);
        for (int64_t m = 0; m < world; m++)
          regions[m] =
              ((m + round) % world) < (world + 1) / 2 ? "east" : "west";
        bool two = false;
        for (auto& g : regions)
          if (g != regions[0]) two = true;
        if (!two) regions[world - 1] = "west";
        // Host labels rotate on their own cadence: pairs co-host, and
        // which ranks pair moves every round — shm rings are created,
        // attached, poisoned (chaos aborts) and torn down continuously.
        std::vector<std::string> hosts(world);
        for (int64_t m = 0; m < world; m++)
          hosts[m] = "hst" + std::to_string(((m + round) % world) / 2);
        std::string prefix = store_addr + "/hier/" + std::to_string(round);
        bool configured = false;
        for (int attempt = 0; attempt < 2 && !configured; attempt++) {
          try {
            hcs[r]->configure(prefix + "/" + std::to_string(attempt), r,
                              world, 15000, stripes, regions, stripes,
                              hosts);
            configured = true;
          } catch (const std::exception&) {
            g_failed++;
          }
        }
        expect(configured, "hier configure failed twice in one round");
        barrier.arrive_and_wait();
        in_ops++;
        if (configured) {
          try {
            expect(hcs[r]->hier_capable(),
                   "hier configure did not build the two-tier topology");
            for (int w = 0; w < 3; w++) {
              for (size_t i = 0; i < elems; i++)
                data[i] = static_cast<float>(r + 1);
              hcs[r]->allreduce_hier(data.data(), elems, Dtype::kF32,
                                     ReduceOp::kSum,
                                     static_cast<HierWire>(w), timeout);
              if (!chaos_round && w == 0) {
                float want = static_cast<float>(world * (world + 1) / 2);
                expect(data[0] == want && data[elems - 1] == want,
                       "hier allreduce sum mismatch");
              }
              g_ok++;
            }
            (void)hcs[r]->last_hier_json();
            // hier q8ef plan: the leader-side EF carry, executed twice so
            // the residual evolves, then reset (the heal discipline).
            int64_t counts[2] = {static_cast<int64_t>(elems / 2),
                                 static_cast<int64_t>(elems - elems / 2)};
            int32_t dtypes[2] = {static_cast<int32_t>(Dtype::kF32),
                                 static_cast<int32_t>(Dtype::kF32)};
            int64_t plan = hcs[r]->plan_build(counts, dtypes, 2,
                                              PlanWire::kQ8EF,
                                              /*prepacked=*/false,
                                              /*hier=*/true);
            const void* ins[2] = {data.data(), data.data() + counts[0]};
            void* outs[2] = {out.data(), out.data() + counts[0]};
            for (int it = 0; it < 2; it++) {
              for (size_t i = 0; i < elems; i++)
                data[i] = static_cast<float>(r + 1) * 0.25f;
              hcs[r]->plan_execute(plan, ins, outs,
                                   static_cast<double>(world),
                                   /*has_divisor=*/true, timeout);
            }
            hcs[r]->plan_reset_feedback(plan);
            hcs[r]->plan_free(plan);
            g_ok++;
          } catch (const std::exception&) {
            // chaos abort / leader-death FIN across tiers — expected;
            // the topology is dead until the next round's configure.
            g_failed++;
          }
        }
        in_ops--;
        barrier.arrive_and_wait();
      }
    });
  }
  for (auto& t : ranks) t.join();
  stop = true;
  chaos.join();
  hcs.clear();
  expect(ShmSegment::live_count() == shm_base,
         "hier churn leaked shm ring segments");
  store.shutdown();
}

// Regression probe for the manager state lock: a min_replicas=2
// lighthouse with one registered group long-polls the quorum for the full
// client timeout — the STALL — while a status publish and a
// checkpoint-metadata RPC on another connection must complete promptly.
// Before the fix, handle_quorum held mu_ across the lighthouse round
// trip, so both serialized behind the stall.
void stalled_lighthouse_round() {
  LighthouseOpt opt;
  opt.min_replicas = 2;  // never satisfiable here: the forward stalls
  opt.join_timeout_ms = 60000;
  opt.quorum_tick_ms = 10;
  opt.heartbeat_timeout_ms = 5000;
  Lighthouse lh("[::]:0", opt);
  StoreServer store("[::]:0");
  ManagerServer ms("stall", lh.address(), "localhost", "[::]:0",
                   store.address(), /*world_size=*/1,
                   /*heartbeat_interval_ms=*/20,
                   /*connect_timeout_ms=*/3000, "", 0, /*region=*/"east");
  std::string maddr = ms.address();

  std::atomic<bool> quorum_done{false};
  std::thread q([&] {
    try {
      ManagerClient c(maddr, 3000);
      c.quorum(0, 0, "stall-meta", false, false, 2500);
      g_failed++;  // a quorum can never form
    } catch (const std::exception&) {
      g_ok++;  // DEADLINE_EXCEEDED — expected
    }
    quorum_done = true;
  });
  sleep_ms(300);  // the forward is now parked inside the lighthouse call
  expect(!quorum_done.load(), "stall never engaged (probe broken)");
  auto t0 = std::chrono::steady_clock::now();
  ms.set_status_json("{\"probe\":1}");
  try {
    ManagerClient c(maddr, 2000);
    expect(c.checkpoint_metadata(0, 2000) == "stall-meta",
           "checkpoint metadata mismatch under stall");
  } catch (const std::exception& e) {
    fprintf(stderr, "metadata rpc under stall failed: %s\n", e.what());
    g_bad = true;
  }
  int64_t elapsed_ms =
      std::chrono::duration_cast<std::chrono::milliseconds>(
          std::chrono::steady_clock::now() - t0)
          .count();
  expect(elapsed_ms < 1500,
         "status/metadata serialized behind the stalled lighthouse quorum "
         "(state lock held across the RPC)");
  g_ok++;
  q.join();
  ms.shutdown();
  store.shutdown();
  lh.shutdown();
}

}  // namespace

int main(int argc, char** argv) {
  int rounds = argc > 1 ? atoi(argv[1]) : 12;
  int world = argc > 2 ? atoi(argv[2]) : 3;
  int stripes = argc > 3 ? atoi(argv[3]) : 2;
  size_t elems = argc > 4 ? static_cast<size_t>(atoll(argv[4])) : 49152;

  collectives_stress(rounds, world, stripes, elems);
  hier_collectives_churn(rounds > 6 ? 6 : rounds, world, stripes,
                         elems / 4);
  control_plane_churn(3);
  durable_root_churn(3);
  hierarchical_churn(3);
  stalled_lighthouse_round();
  shm_churn(6, world);

  fprintf(stderr,
          "stress_native: ok_ops=%ld failed_ops=%ld checks=%ld%s\n",
          g_ok.load(), g_failed.load(), g_checks.load(),
          g_bad ? " CHECK-FAILURES" : "");
  if (g_bad) return 1;
  if (g_ok.load() == 0) {
    fprintf(stderr, "stress_native: no op ever succeeded\n");
    return 1;
  }
  return 0;
}
