"""Pure-function tests for the C++ quorum logic.

Ports the reference's Rust unit tests for ``quorum_compute``
(reference src/lighthouse.rs:582-1001) and ``compute_quorum_results``
(reference src/manager.rs:661-850) through the JSON C-API entry points.
"""

from torchft_tpu._native import compute_quorum_results, quorum_compute

HOUR_MS = 60 * 60 * 1000


def member(replica_id, step=1, world_size=1, shrink_only=False, addr_num=None):
    n = addr_num if addr_num is not None else replica_id
    return {
        "replica_id": replica_id,
        "address": f"addr_{n}",
        "store_address": f"store_addr_{n}",
        "step": step,
        "world_size": world_size,
        "shrink_only": shrink_only,
    }


def participant(replica_id, joined_ms=0, **kw):
    return {"joined_ms": joined_ms, "member": member(replica_id, **kw)}


def opts(min_replicas=1, join_timeout_ms=HOUR_MS, heartbeat_timeout_ms=5000):
    return {
        "min_replicas": min_replicas,
        "join_timeout_ms": join_timeout_ms,
        "quorum_tick_ms": 10,
        "heartbeat_timeout_ms": heartbeat_timeout_ms,
    }


def state(participants=(), heartbeats=None, prev_quorum=None, now=0):
    return {
        "participants": {p["member"]["replica_id"]: p for p in participants},
        "heartbeats": heartbeats or {},
        "prev_quorum": prev_quorum,
        "quorum_id": 0,
    }


class TestQuorumCompute:
    # Reference src/lighthouse.rs:582-655 (test_quorum_join_timeout).
    def test_join_timeout(self):
        now = HOUR_MS * 100
        o = opts(min_replicas=1, join_timeout_ms=HOUR_MS)

        r = quorum_compute(now, state(), o)
        assert r["quorum"] is None
        assert (
            "New quorum not ready, only have 0 participants, need min_replicas 1"
            in r["reason"]
        )

        s = state(
            [participant("a", joined_ms=now), participant("b", joined_ms=now)],
            heartbeats={"a": now, "b": now},
        )
        # all healthy workers participating
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]

        # healthy worker not participating -> wait for join timeout
        s["heartbeats"]["c"] = now
        r = quorum_compute(now, s, o)
        assert r["quorum"] is None
        assert "join timeout" in r["reason"]

        # elapse past the join timeout
        s["participants"]["a"]["joined_ms"] = now - 10 * HOUR_MS
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]

    # Reference src/lighthouse.rs:657-737 (test_quorum_heartbeats).
    def test_heartbeats(self):
        now = HOUR_MS
        o = opts(min_replicas=1, join_timeout_ms=0)

        s = state([participant("a", joined_ms=now)], heartbeats={"a": now})
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]
        assert "[1/1 participants healthy][1 heartbeating]" in r["reason"]

        # expired heartbeat
        s["heartbeats"]["a"] = now - 10_000
        r = quorum_compute(now, s, o)
        assert r["quorum"] is None
        assert "[0/1 participants healthy][0 heartbeating]" in r["reason"]

        # 1 healthy, 1 expired
        s["participants"]["b"] = participant("b", joined_ms=now)
        s["heartbeats"]["b"] = now
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]
        assert len(r["quorum"]) == 1

    # Reference src/lighthouse.rs:739-821 (test_quorum_fast_prev_quorum).
    def test_fast_prev_quorum(self):
        now = HOUR_MS
        o = opts(min_replicas=1, join_timeout_ms=HOUR_MS)

        assert quorum_compute(now, state(), o)["quorum"] is None

        s = state([participant("a", joined_ms=now)], heartbeats={"a": now})
        # one worker alive but not participating -> split brain guard
        s["heartbeats"]["b"] = now
        r = quorum_compute(now, s, o)
        assert r["quorum"] is None
        assert "need at least half" in r["reason"]

        # previous quorum containing only "a" -> fast quorum
        s["prev_quorum"] = {"quorum_id": 1, "participants": [member("a")]}
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]
        assert "Fast quorum" in r["reason"]

        # expanding quorum via fast quorum
        s["participants"]["b"] = participant("b", joined_ms=now)
        s["heartbeats"]["b"] = now
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]
        assert len(r["quorum"]) == 2

    # Reference src/lighthouse.rs:823-908 (test_quorum_shrink_only).
    def test_shrink_only(self):
        now = HOUR_MS
        o = opts(min_replicas=1, join_timeout_ms=HOUR_MS)
        s = state(
            [
                participant("a", joined_ms=now, shrink_only=True),
                # participant not in the previous quorum
                participant("c", joined_ms=now, shrink_only=True),
            ],
            heartbeats={"a": now, "c": now},
            prev_quorum={
                "quorum_id": 1,
                "participants": [member("a"), member("b")],
            },
        )
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]
        assert "[shrink_only=true]" in r["reason"]
        assert len(r["quorum"]) == 1
        assert r["quorum"][0]["replica_id"] == "a"

    # Reference src/lighthouse.rs:954-1001 (test_quorum_split_brain).
    def test_split_brain(self):
        now = HOUR_MS
        o = opts(min_replicas=1, join_timeout_ms=HOUR_MS)

        assert quorum_compute(now, state(), o)["quorum"] is None

        s = state([participant("a", joined_ms=now)], heartbeats={"a": now})
        r = quorum_compute(now, s, o)
        assert r["quorum"] is not None, r["reason"]

        # another worker alive but not participating: 1 <= 2/2
        s["heartbeats"]["b"] = now
        r = quorum_compute(now, s, o)
        assert r["quorum"] is None
        assert (
            "New quorum not ready, only have 1 participants, need at least half of 2 "
            "healthy workers [1/1 participants healthy][2 heartbeating]" in r["reason"]
        )

    def test_deterministic_ordering(self):
        now = HOUR_MS
        o = opts(min_replicas=1, join_timeout_ms=0)
        s = state(
            [participant(rid, joined_ms=now) for rid in ("zeta", "alpha", "mid")],
            heartbeats={"zeta": now, "alpha": now, "mid": now},
        )
        r = quorum_compute(now, s, o)
        assert [m["replica_id"] for m in r["quorum"]] == ["alpha", "mid", "zeta"]


class TestComputeQuorumResults:
    # Reference src/manager.rs:727-776 (test_compute_quorum_results_first_step).
    def test_first_step(self):
        quorum = {
            "quorum_id": 1,
            "participants": [
                member("replica_0", step=0, addr_num="0"),
                member("replica_1", step=0, addr_num="1"),
            ],
        }

        r = compute_quorum_results("replica_0", 0, quorum)
        assert not r.heal
        assert r.replica_rank == 0
        assert r.recover_src_rank is None
        assert r.recover_dst_ranks == [1]

        r = compute_quorum_results("replica_1", 0, quorum)
        assert r.heal
        assert r.replica_rank == 1
        assert r.recover_src_rank == 0
        assert r.recover_dst_ranks == []

        # rank 1 assignments are offset from rank 0's
        r = compute_quorum_results("replica_1", 1, quorum)
        assert not r.heal
        assert r.replica_rank == 1
        assert r.recover_src_rank is None
        assert r.recover_dst_ranks == [0]

    # Reference src/manager.rs:778-850 (test_compute_quorum_results_recovery):
    # 5 replicas, 0/2/4 behind at step 0, 1/3 at max step 1.
    def test_recovery_matrix(self):
        quorum = {
            "quorum_id": 1,
            "participants": [
                member("replica_0", step=0, addr_num="0"),
                member("replica_1", step=1, addr_num="1"),
                member("replica_2", step=0, addr_num="2"),
                member("replica_3", step=1, addr_num="3"),
                member("replica_4", step=0, addr_num="4"),
            ],
        }

        r = compute_quorum_results("replica_0", 0, quorum)
        assert r.heal
        assert r.recover_src_manager_address == "addr_1"
        assert r.replica_rank == 0
        assert r.recover_src_rank == 1
        assert r.recover_dst_ranks == []

        r = compute_quorum_results("replica_1", 0, quorum)
        assert not r.heal
        assert r.recover_src_manager_address == ""
        assert r.replica_rank == 1
        assert r.recover_src_rank is None
        assert sorted(r.recover_dst_ranks) == [0, 4]

        r = compute_quorum_results("replica_3", 0, quorum)
        assert not r.heal
        assert r.replica_rank == 3
        assert r.recover_src_rank is None
        assert r.recover_dst_ranks == [2]

        # rank 1 assignments are offset from rank 0's
        r = compute_quorum_results("replica_1", 1, quorum)
        assert not r.heal
        assert r.replica_rank == 1
        assert r.recover_src_rank is None
        assert r.recover_dst_ranks == [2]

    def test_max_step_cohort(self):
        quorum = {
            "quorum_id": 7,
            "participants": [
                member("a", step=5, addr_num="a"),
                member("b", step=3, addr_num="b"),
                member("c", step=5, addr_num="c"),
            ],
        }
        r = compute_quorum_results("a", 0, quorum)
        assert r.max_step == 5
        assert r.max_world_size == 2
        assert r.max_rank == 0
        assert r.replica_world_size == 3
        # primary store for rank 0 comes from the max-step cohort
        assert r.store_address == "store_addr_a"

        r = compute_quorum_results("b", 0, quorum)
        assert r.heal and r.max_rank is None

    def test_not_in_quorum_raises(self):
        quorum = {"quorum_id": 1, "participants": [member("a")]}
        try:
            compute_quorum_results("ghost", 0, quorum)
            raise AssertionError("expected error")
        except RuntimeError as e:
            assert "not participating" in str(e)
