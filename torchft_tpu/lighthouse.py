"""Lighthouse CLI: ``python -m torchft_tpu.lighthouse``.

The standalone quorum service, the role of the reference's
``torchft_lighthouse`` entrypoint (reference pyproject.toml:37-38,
src/bin/lighthouse.rs:10-23). Defaults mirror the reference CLI
(src/lighthouse.rs:66-103).

Three roles (``--role``):

- ``flat`` (default): the single-service deployment — every replica group
  heartbeats/renews into this one process.
- ``root``: identical server, but named for the hierarchical deployment —
  region lighthouses push membership digests into it and it computes the
  global quorum.
- ``region``: the middle tier. Serves the manager-facing protocol locally,
  aggregates its groups into digests pushed to ``--root``, long-polls the
  global quorum back out. See docs/OPERATIONS.md "control-plane deployment"
  for when to add a region tier.

Every role serves ``GET /status.json`` (machine-readable members, lease
deadlines, last quorum id, tier role) next to the HTML dashboard;
:func:`fetch_status` is the programmatic consumer (bench_lighthouse uses it
instead of scraping HTML).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import threading
import urllib.request
from typing import Optional, Sequence

from . import _native

logger = logging.getLogger(__name__)


def _fetch_json(addr: str, path: str, timeout: float) -> dict:
    if not addr.startswith("http://") and not addr.startswith("https://"):
        addr = "http://" + addr
    with urllib.request.urlopen(addr + path, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def fetch_status(addr: str, timeout: float = 5.0) -> dict:
    """Fetches a lighthouse's (any role) machine-readable status view.

    ``addr`` is the service address (``http://host:port`` or ``host:port``).
    """
    return _fetch_json(addr, "/status.json", timeout)


def fetch_quorum(addr: str, timeout: float = 5.0) -> dict:
    """Fetches a REGION lighthouse's cached view of the last global quorum
    (``GET /quorum.json``): served from the region-side cache the standing
    root poll maintains, so reading it generates no root traffic — the
    read-mostly path for dashboards and fleet tooling. ``age_ms`` is the
    time since the cache was refreshed off the root (null before the first
    root quorum lands); with the root down the cache keeps serving while
    ``age_ms`` grows and ``root_connected`` goes false."""
    return _fetch_json(addr, "/quorum.json", timeout)


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        prog="torchft_tpu.lighthouse",
        description="Quorum service (flat, hierarchical root, or region tier) "
        "for torchft_tpu replica groups.",
    )
    parser.add_argument("--bind", default="[::]:29510")
    parser.add_argument(
        "--role",
        choices=("flat", "root", "region"),
        default="flat",
        help="flat/root: the quorum-computing service; region: aggregate "
        "local groups into digests pushed to --root",
    )
    parser.add_argument(
        "--root",
        default=os.environ.get("TORCHFT_LIGHTHOUSE_ROOT", ""),
        help="root lighthouse address (required for --role region; env "
        "TORCHFT_LIGHTHOUSE_ROOT)",
    )
    parser.add_argument(
        "--region-id",
        default="",
        help="stable region name reported in root status (default: bind addr)",
    )
    parser.add_argument(
        "--digest-interval-ms",
        type=int,
        default=int(os.environ.get("TORCHFT_DIGEST_INTERVAL_MS", "100")),
        help="cadence of periodic region->root digests (urgent pushes fire "
        "immediately; env TORCHFT_DIGEST_INTERVAL_MS)",
    )
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument("--join_timeout_ms", type=int, default=60000)
    parser.add_argument("--quorum_tick_ms", type=int, default=100)
    parser.add_argument("--heartbeat_timeout_ms", type=int, default=5000)
    # ---- durable control plane (flat/root roles; see OPERATIONS.md
    # "control-plane durability & failover") ----
    parser.add_argument(
        "--wal-dir",
        default=os.environ.get("TORCHFT_LH_WAL_DIR", ""),
        help="write-ahead quorum log + snapshot directory (env "
        "TORCHFT_LH_WAL_DIR); empty = in-memory only",
    )
    parser.add_argument(
        "--snapshot-every",
        type=int,
        default=int(os.environ.get("TORCHFT_LH_SNAPSHOT_EVERY", "0")),
        help="WAL records per snapshot compaction (env "
        "TORCHFT_LH_SNAPSHOT_EVERY; 0 = default 512)",
    )
    parser.add_argument(
        "--peers",
        default=os.environ.get("TORCHFT_LH_PEERS", ""),
        help="comma-separated OTHER root endpoints of this root's "
        "failover set (env TORCHFT_LH_PEERS)",
    )
    parser.add_argument(
        "--standby",
        action="store_true",
        default=os.environ.get("TORCHFT_LH_STANDBY", "") in ("1", "on", "true"),
        help="start as a passive warm standby: tail the active peer and "
        "take over when its lease lapses (env TORCHFT_LH_STANDBY=1)",
    )
    parser.add_argument(
        "--takeover-ms",
        type=int,
        default=int(os.environ.get("TORCHFT_LH_TAKEOVER_MS", "0")),
        help="standby takeover bound: sync starvation longer than this "
        "claims a new root epoch (env TORCHFT_LH_TAKEOVER_MS; 0 = 3000)",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if args.role == "region":
        if not args.root:
            parser.error("--role region requires --root (or TORCHFT_LIGHTHOUSE_ROOT)")
        server: object = _native.RegionLighthouse(
            root_addr=args.root,
            region_id=args.region_id or args.bind,
            bind=args.bind,
            digest_interval_ms=args.digest_interval_ms,
            heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        )
    else:
        server = _native.Lighthouse(
            bind=args.bind,
            min_replicas=args.min_replicas,
            join_timeout_ms=args.join_timeout_ms,
            quorum_tick_ms=args.quorum_tick_ms,
            heartbeat_timeout_ms=args.heartbeat_timeout_ms,
            wal_dir=args.wal_dir,
            snapshot_every=args.snapshot_every,
            peers=args.peers,
            standby=args.standby,
            takeover_ms=args.takeover_ms,
        )
    logger.info(f"{args.role} lighthouse serving on {server.address()}")  # type: ignore[attr-defined]

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.shutdown()  # type: ignore[attr-defined]


if __name__ == "__main__":
    main()
