"""Live checkpoint transport: recovering replicas fetch weights from a healthy
peer over HTTP instead of from disk.

Reference: torchft/checkpointing.py (CheckpointTransport ABC :34-88,
CheckpointServer :110-270). The lock-gating discipline is identical: the
server starts *disallowed*; ``send_checkpoint`` publishes a state dict for
exactly one step and allows reads; ``disallow_checkpoint`` (called from
``Manager.should_commit``, reference manager.py:591) re-locks it so the dict
can never be read mid-mutation. A request for any other step gets a 400.

Serialization is pytree-native: leaves are pulled to host (numpy) and the
tree is pickled STREAMING in both directions — chunked transfer encoding
into the socket on send, incremental unpickle off the response on receive —
so neither end ever holds the serialized payload as one buffer (peak extra
memory is one leaf, matching the reference's streamed torch.save,
reference checkpointing.py:139-170). jax arrays are reconstructed as numpy
on the receiver; the caller decides device placement/sharding
(``jax.device_put``) — the transport never touches devices.

Transport striping: by default the receiver fetches the payload as N byte
ranges over N PARALLEL connections (``TORCHFT_CKPT_STRIPES``, default 4;
the server serves ``/checkpoint/{step}/part/{i}/{n}`` from a per-step
pickle cache). A single TCP stream is window-limited on the
high-bandwidth-delay links heal traffic actually crosses — the same
bottleneck the collectives ring escapes with striped connections — and
heal time is dominated by this transfer. Striped mode trades the streamed
path's bounded memory for bandwidth (one full serialized copy on each
end); ``stripes=1`` or a pre-striping peer falls back to the streamed
single-connection path.

Security model: deserialization uses a SAFELISTED unpickler — only CLASSES
from the scientific-stack modules state dicts are actually made of (numpy,
optax, jax, collections, ml_dtypes), the two numpy array reconstructors,
and a narrow builtins set can be referenced. Plain functions are never
resolvable (a REDUCE on a function is the pickle code-execution
primitive), and the safelist is snapshotted per load so a payload cannot
widen it mid-deserialization. This is deliberately stricter than the
reference's ``torch.load(weights_only=False)`` (reference
checkpointing.py:203). It is hardening, not authentication: the endpoint
is unauthenticated HTTP, so the checkpoint port must only be reachable
inside the training cluster's trusted network — same deployment
requirement as the reference. Custom user state classes outside the
safelist: call :func:`register_safe_modules` at startup on every replica.
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import socket
import threading
import urllib.error
import urllib.request
from abc import ABC, abstractmethod
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Generic, List, Optional, TypeVar

import numpy as np

logger: logging.Logger = logging.getLogger(__name__)

T = TypeVar("T")


class CheckpointTransport(Generic[T], ABC):
    """Pluggable live-recovery transport. Reference checkpointing.py:34-88."""

    @abstractmethod
    def metadata(self) -> str:
        """Returns transport metadata (e.g. the URL prefix) that recovering
        replicas need; shipped to peers through the quorum RPC."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        """Makes ``state_dict`` for ``step`` available to ``dst_ranks``."""

    def disallow_checkpoint(self) -> None:
        """Called once the training loop may mutate the state dict again."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        """Fetches the state dict for ``step`` from the peer described by
        ``metadata``."""

    def shutdown(self, wait: bool = True) -> None:
        ...


def _to_host(tree: Any) -> Any:
    """Device→host: every array leaf becomes numpy (zero-copy where possible)."""
    import jax

    return jax.tree_util.tree_map(
        lambda l: np.asarray(l) if hasattr(l, "__array__") else l, tree
    )


def serialize_state_dict(state_dict: Any) -> bytes:
    """Pickles a pytree with all array leaves on host."""
    buf = io.BytesIO()
    pickle.dump(_to_host(state_dict), buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def dump_state_dict_stream(state_dict: Any, fileobj: Any) -> None:
    """Streams the pickled pytree straight into ``fileobj`` (a socket
    wrapper): pickle emits incrementally, so peak extra memory is one
    leaf's buffer, not the whole payload — the reference streams
    torch.save into the HTTP response the same way (reference
    checkpointing.py:139-170)."""
    pickle.dump(_to_host(state_dict), fileobj, protocol=pickle.HIGHEST_PROTOCOL)


def load_state_dict_stream(fileobj: Any) -> Any:
    """Safelisted unpickle reading incrementally from ``fileobj`` (e.g. an
    HTTP response): bounded-memory inverse of
    :func:`dump_state_dict_stream` — the full payload is never held as one
    bytes object. The safelist applies unchanged (it gates global lookups,
    not framing)."""
    return _SafeUnpickler(fileobj).load()


class _ChunkedWriter:
    """Minimal HTTP/1.1 chunked transfer encoder over the handler's
    ``wfile``; lets the server stream a response whose length is unknown
    up front (the streamed pickle)."""

    def __init__(self, wfile: Any) -> None:
        self._wfile = wfile

    def write(self, data: Any) -> int:
        # protocol-5 pickle passes PickleBuffer objects, not just bytes;
        # go through a flat memoryview so any buffer-protocol payload
        # (numpy array data included) streams without a copy
        mv = memoryview(data).cast("B")
        if mv.nbytes:
            self._wfile.write(f"{mv.nbytes:x}\r\n".encode("ascii"))
            self._wfile.write(mv)
            self._wfile.write(b"\r\n")
        return mv.nbytes

    def close(self) -> None:
        self._wfile.write(b"0\r\n\r\n")


# Module roots whose CLASSES state dicts are really made of. Extendable for
# user classes via register_safe_modules. NOTE: deliberately does NOT
# include torchft_tpu itself — a payload resolving this module's own
# helpers (e.g. register_safe_modules) could widen the list mid-load.
_SAFE_MODULE_ROOTS = {
    "numpy", "optax", "jax", "collections", "ml_dtypes",
}
# Non-class globals required by the numpy array pickle format. Functions
# are otherwise NEVER resolvable (a REDUCE on an arbitrary function is the
# code-execution primitive); these two reconstructors only build arrays.
_SAFE_EXACT = {
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
}
# Builtins narrowed to data constructors: resolving e.g. builtins.eval or
# getattr is how pickle payloads become code execution.
_SAFE_BUILTINS = {
    "bool", "bytearray", "bytes", "complex", "dict", "float", "frozenset",
    "int", "list", "range", "set", "slice", "str", "tuple",
}


def register_safe_modules(*roots: str) -> None:
    """Allows CLASSES from additional top-level modules (e.g. your package
    defining a custom state holder) to be referenced by incoming
    checkpoints. Call at startup on every replica — the set is snapshotted
    when a load begins, so a payload cannot extend it mid-load."""
    _SAFE_MODULE_ROOTS.update(roots)


class _SafeUnpickler(pickle.Unpickler):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        # Snapshot: registration during a hostile load has no effect on it.
        self._roots = frozenset(_SAFE_MODULE_ROOTS)

    def find_class(self, module: str, name: str) -> Any:
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        if (module, name) in _SAFE_EXACT:
            return super().find_class(module, name)
        if module.partition(".")[0] in self._roots:
            obj = super().find_class(module, name)
            # Classes only: data containers may be constructed, but plain
            # functions (the REDUCE code-execution primitive) may not.
            if isinstance(obj, type):
                return obj
        raise pickle.UnpicklingError(
            f"checkpoint references disallowed global {module}.{name}; "
            "if this is your own state CLASS, call "
            "torchft_tpu.checkpointing.register_safe_modules"
            f"({module.partition('.')[0]!r}) on every replica"
        )


def deserialize_state_dict(raw: bytes) -> Any:
    """Inverse of :func:`serialize_state_dict` through the safelisted
    unpickler (see module docstring). Array leaves come back as numpy."""
    return _SafeUnpickler(io.BytesIO(raw)).load()


class _TimedAcquire:
    """Lock acquire with timeout that raises instead of returning False.
    Reference checkpointing.py:91-107."""

    def __init__(self, lock: threading.Lock, timeout: timedelta) -> None:
        self._lock = lock
        self._timeout = timeout

    def __enter__(self) -> None:
        if not self._lock.acquire(timeout=self._timeout.total_seconds()):
            raise TimeoutError(
                f"timed out acquiring checkpoint lock after {self._timeout}"
            )

    def __exit__(self, *exc: object) -> None:
        self._lock.release()


class CheckpointServer(CheckpointTransport[T]):
    """Threaded HTTP server streaming ``GET /checkpoint/{step}``.

    Reference checkpointing.py:110-270. The server starts in the *disallowed*
    state: requests block on the gate lock until ``send_checkpoint``
    publishes a dict, and re-block after ``disallow_checkpoint``.
    """

    def __init__(self, timeout: timedelta = timedelta(seconds=30)) -> None:
        self._checkpoint_lock = threading.Lock()
        self._disallowed = False
        self._step = -1
        self._timeout = timeout
        self._state_dict: Any = None
        # One-shot pickle cache backing the striped /part/ endpoint
        self._serialized: Any = None
        self._serialized_step = -1

        # Gate starts held: nothing readable until the first send_checkpoint.
        self.disallow_checkpoint()

        ckpt_server = self

        class RequestHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self) -> None:
                try:
                    prefix = "/checkpoint/"
                    if not self.path.startswith(prefix):
                        self.send_error(404, "unknown path")
                        return
                    rest = self.path[len(prefix):].split("/")
                    if len(rest) == 4 and rest[1] == "part":
                        # striped fetch: /checkpoint/{step}/part/{i}/{n}
                        self._serve_part(
                            int(rest[0]), int(rest[2]), int(rest[3])
                        )
                        return
                    if len(rest) != 1:
                        self.send_error(404, "unknown path")
                        return
                    with _TimedAcquire(
                        ckpt_server._checkpoint_lock, ckpt_server._timeout
                    ):
                        step = ckpt_server._step
                        requested = int(rest[0])
                        if requested != step:
                            self.send_error(
                                400,
                                f"invalid checkpoint requested: serving {step} "
                                f"but got {requested}",
                            )
                            return
                        # STREAMED response (chunked): the pickle goes
                        # straight to the socket as it is produced — no
                        # full-payload buffer on the server, so multi-GB
                        # states don't spike host RAM inside the lock
                        # window (reference checkpointing.py:139-170
                        # streams torch.save the same way). The
                        # device->host pull happens BEFORE the 200 is
                        # committed: a wedged d2h (the dominant failure
                        # class) still gets a clean 500, and only a
                        # pickling error can corrupt an in-flight chunk
                        # stream (the peer then fails loudly on framing).
                        host_tree = _to_host(ckpt_server._state_dict)
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "application/octet-stream"
                        )
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        out = _ChunkedWriter(self.wfile)
                        pickle.dump(
                            host_tree, out,
                            protocol=pickle.HIGHEST_PROTOCOL,
                        )
                        out.close()
                except Exception as e:  # noqa: BLE001 - report to the peer
                    logger.exception("checkpoint server error")
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass

            def _serve_part(self, requested: int, i: int, n: int) -> None:
                """One byte-range of the serialized checkpoint, for the
                striped (parallel-connection) fetch. The gate lock is held
                only to validate the step and build/fetch the serialized
                cache — NOT while the body streams, or the N part requests
                would serialize and the parallel fetch would be a no-op.
                The cache is an immutable bytes object, so a concurrent
                disallow_checkpoint (which drops the server's reference)
                cannot mutate an in-flight response."""
                if n < 1 or not (0 <= i < n):
                    self.send_error(404, f"bad part {i}/{n}")
                    return
                with _TimedAcquire(
                    ckpt_server._checkpoint_lock, ckpt_server._timeout
                ):
                    step = ckpt_server._step
                    if requested != step:
                        self.send_error(
                            400,
                            f"invalid checkpoint requested: serving {step} "
                            f"but got {requested}",
                        )
                        return
                    payload = ckpt_server._serialized
                    if payload is None or ckpt_server._serialized_step != step:
                        # Serialized exactly once per published step, shared
                        # by every part of every striped reader. Memory cost
                        # (one full pickle) is the striped transport's
                        # bandwidth-for-memory trade; the single-stream
                        # endpoint above stays allocation-free.
                        payload = serialize_state_dict(
                            ckpt_server._state_dict
                        )
                        ckpt_server._serialized = payload
                        ckpt_server._serialized_step = step
                start = len(payload) * i // n
                end = len(payload) * (i + 1) // n
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(end - start))
                self.end_headers()
                self.wfile.write(payload[start:end])

            def log_message(self, format: str, *args: object) -> None:
                logger.debug(f"checkpoint server: {format % args}")

        class _Server(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            request_queue_size = 1024
            daemon_threads = True

        self._server = _Server(("::", 0), RequestHandler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name="checkpoint_server",
        )
        self._thread.start()

    @classmethod
    def load_from_address(
        cls, address: str, timeout: timedelta, stripes: Optional[int] = None
    ) -> T:
        """Fetches a checkpoint from a step-qualified URL.
        Reference checkpointing.py:187-203.

        ``stripes`` > 1 (default: env ``TORCHFT_CKPT_STRIPES``, else 4)
        fetches the payload as that many byte ranges over PARALLEL HTTP
        connections — the same window-limit escape the collectives ring
        uses, and the lever that moves heal-time checkpoint transfer off a
        single TCP stream's throughput ceiling. Falls back to the
        single-stream (bounded-memory) fetch against servers without the
        ``/part/`` endpoint; ``stripes=1`` selects it directly."""
        if stripes is None:
            stripes = int(os.environ.get("TORCHFT_CKPT_STRIPES", "4"))
        stripes = max(1, min(int(stripes), 64))
        logger.info(f"fetching checkpoint from {address} (stripes={stripes})")
        if stripes > 1:
            try:
                return cls._load_striped(address, timeout, stripes)
            except urllib.error.HTTPError as e:
                if e.code not in (404, 500):
                    raise
                # 404/500: a pre-striping peer that can't parse the /part/
                # path — heal must proceed at single-stream speed, not fail
                logger.warning(
                    "peer checkpoint server lacks the striped endpoint "
                    f"(HTTP {e.code}); falling back to single-stream fetch"
                )
            except OSError as e:
                # socket timeout / reset mid-stripe (e.g. the server is
                # still serializing a large dict under the gate lock). The
                # streamed path needs no up-front serialize, so the heal
                # can still succeed there.
                logger.warning(
                    f"striped checkpoint fetch failed ({e!r}); "
                    "falling back to single-stream fetch"
                )
        with urllib.request.urlopen(
            address, timeout=timeout.total_seconds()
        ) as f:
            # incremental unpickle off the response stream (http.client
            # de-chunks transparently): bounded memory on the receiver too
            return load_state_dict_stream(f)

    @classmethod
    def _load_striped(cls, address: str, timeout: timedelta, stripes: int) -> T:
        """Parallel byte-range fetch + one safelisted deserialize. Holds
        the full serialized payload on the receiver (the striped
        transport's bandwidth-for-memory trade)."""

        def fetch(i: int) -> bytes:
            # One retry on 500: the server builds its pickle cache lazily
            # under the gate lock, so the FIRST part request of a large
            # checkpoint can hold the lock past the server's lock timeout
            # and 500 its siblings. By the retry the cache exists and
            # parts stream immediately — without it, one slow serialize
            # would kick the whole heal down to single-stream speed.
            for attempt in (0, 1):
                try:
                    with urllib.request.urlopen(
                        f"{address}/part/{i}/{stripes}",
                        timeout=timeout.total_seconds(),
                    ) as f:
                        return f.read()
                except urllib.error.HTTPError as e:
                    if attempt or e.code != 500:
                        raise

        with ThreadPoolExecutor(
            max_workers=stripes, thread_name_prefix="ckpt_stripe"
        ) as ex:
            parts = list(ex.map(fetch, range(stripes)))
        return deserialize_state_dict(b"".join(parts))

    def address(self) -> str:
        """URL prefix of this server; append the step to fetch."""
        port = self._server.socket.getsockname()[1]
        return f"http://{socket.gethostname()}:{port}/checkpoint/"

    def allow_checkpoint(self, step: int) -> None:
        """Publishes ``step``; unblocks readers. Reference :246-254."""
        self._step = step
        if self._disallowed:
            self._disallowed = False
            self._checkpoint_lock.release()

    def disallow_checkpoint(self) -> None:
        """Re-locks the gate so the dict can be mutated. Reference :256-259."""
        if not self._disallowed:
            self._disallowed = True
            self._checkpoint_lock.acquire()
            # the dict may mutate now; the pickle cache is stale
            self._serialized = None
            self._serialized_step = -1

    # -- CheckpointTransport --

    def metadata(self) -> str:
        return self.address()

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        self._state_dict = state_dict
        self._serialized = None  # new dict, even at an unchanged step
        self._serialized_step = -1
        self.allow_checkpoint(step)

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        return self.load_from_address(f"{metadata}{step}", timeout)

    def shutdown(self, wait: bool = True) -> None:
        """Stops serving. Requests in flight hold the gate lock until done."""
        self._server.shutdown()
        if wait:
            self._thread.join()
        self._server.server_close()
