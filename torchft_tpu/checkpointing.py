"""Live checkpoint transport: recovering replicas fetch weights from a healthy
peer over HTTP instead of from disk.

Reference: torchft/checkpointing.py (CheckpointTransport ABC :34-88,
CheckpointServer :110-270). The lock-gating discipline is identical: the
server starts *disallowed*; ``send_checkpoint`` publishes a state dict for
exactly one step and allows reads; ``disallow_checkpoint`` (called from
``Manager.should_commit``, reference manager.py:591) re-locks it so the dict
can never be read mid-mutation. A request for any other step gets a 400.

Serialization is pytree-native: leaves are pulled to host (numpy) and the
tree is pickled STREAMING in both directions — chunked transfer encoding
into the socket on send, incremental unpickle off the response on receive —
so neither end ever holds the serialized payload as one buffer (peak extra
memory is one leaf, matching the reference's streamed torch.save,
reference checkpointing.py:139-170). jax arrays are reconstructed as numpy
on the receiver; the caller decides device placement/sharding
(``jax.device_put``) — the transport never touches devices.

Transport striping: by default the receiver fetches the payload as N byte
ranges over N PARALLEL connections (``TORCHFT_CKPT_STRIPES``, default 4;
the server serves ``/checkpoint/{step}/part/{i}/{n}`` from a per-step
pickle cache). A single TCP stream is window-limited on the
high-bandwidth-delay links heal traffic actually crosses — the same
bottleneck the collectives ring escapes with striped connections — and
heal time is dominated by this transfer. Striped mode trades the streamed
path's bounded memory for bandwidth (one full serialized copy on each
end); ``stripes=1`` or a pre-striping peer falls back to the streamed
single-connection path.

Streamed ZERO-COPY heal (the default when both ends speak it): the pickle
paths above serialize the whole dict, ship it, then deserialize, then
upload — three full-payload stop-the-world passes on the heal critical
path. The stream endpoints apply the CommPlan discipline (persistent
native comm plans, torchft_tpu/collectives.py) to the heal payload
instead: the LAYOUT (skeleton tree + per-leaf byte offsets) is computed
once per published step, the donor serves raw byte ranges straight out of
the live host buffers (memoryview slices — no per-request pickle, no
serialized copy), and the receiver ``readinto``s the ranges over
``TORCHFT_HEAL_STREAMS`` parallel connections into ONE preallocated
buffer, reconstructing each leaf as a zero-copy view the moment its bytes
land and dispatching its (async) device upload while later stripes are
still on the wire. Only the small skeleton rides pickle (through the same
safelist); the bulk payload is pure bytes — never executable. An optional
``wire="bf16"`` (``TORCHFT_HEAL_WIRE``) halves the bytes of f32 leaves
under an ``"opt_state"`` key — optimizer moments tolerate bf16 rounding
— while everything else (params included, whatever the caller named
them) ships raw bytes, so the healed replica's weights are bit-identical
to the donor's. Pre-stream peers 404 the endpoints and the client falls
back to the pickle paths unchanged.

Security model: deserialization uses a SAFELISTED unpickler — only CLASSES
from the scientific-stack modules state dicts are actually made of (numpy,
optax, jax, collections, ml_dtypes), the two numpy array reconstructors,
and a narrow builtins set can be referenced. Plain functions are never
resolvable (a REDUCE on a function is the pickle code-execution
primitive), and the safelist is snapshotted per load so a payload cannot
widen it mid-deserialization. This is deliberately stricter than the
reference's ``torch.load(weights_only=False)`` (reference
checkpointing.py:203). It is hardening, not authentication: the endpoint
is unauthenticated HTTP, so the checkpoint port must only be reachable
inside the training cluster's trusted network — same deployment
requirement as the reference. Custom user state classes outside the
safelist: call :func:`register_safe_modules` at startup on every replica.
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import socket
import threading
import time
import urllib.error
import urllib.request
from abc import ABC, abstractmethod
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from datetime import timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Generic, List, Optional, Tuple, TypeVar

import numpy as np

from ._native import (
    WireCorruption,
    crc32c as _crc32c,
    crc32c_combine as _crc32c_combine,
    crc32c_update as _crc32c_update,
)

logger: logging.Logger = logging.getLogger(__name__)

T = TypeVar("T")


class CheckpointTransport(Generic[T], ABC):
    """Pluggable live-recovery transport. Reference checkpointing.py:34-88."""

    @abstractmethod
    def metadata(self) -> str:
        """Returns transport metadata (e.g. the URL prefix) that recovering
        replicas need; shipped to peers through the quorum RPC."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        """Makes ``state_dict`` for ``step`` available to ``dst_ranks``."""

    def disallow_checkpoint(self) -> None:
        """Called once the training loop may mutate the state dict again."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        """Fetches the state dict for ``step`` from the peer described by
        ``metadata``."""

    def shutdown(self, wait: bool = True) -> None:
        ...


def _to_host(tree: Any) -> Any:
    """Device→host: every array leaf becomes numpy (zero-copy where possible)."""
    import jax

    return jax.tree_util.tree_map(
        lambda l: np.asarray(l) if hasattr(l, "__array__") else l, tree
    )


def serialize_state_dict(state_dict: Any) -> bytes:
    """Pickles a pytree with all array leaves on host."""
    buf = io.BytesIO()
    pickle.dump(_to_host(state_dict), buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def dump_state_dict_stream(state_dict: Any, fileobj: Any) -> None:
    """Streams the pickled pytree straight into ``fileobj`` (a socket
    wrapper): pickle emits incrementally, so peak extra memory is one
    leaf's buffer, not the whole payload — the reference streams
    torch.save into the HTTP response the same way (reference
    checkpointing.py:139-170)."""
    pickle.dump(_to_host(state_dict), fileobj, protocol=pickle.HIGHEST_PROTOCOL)


def load_state_dict_stream(fileobj: Any) -> Any:
    """Safelisted unpickle reading incrementally from ``fileobj`` (e.g. an
    HTTP response): bounded-memory inverse of
    :func:`dump_state_dict_stream` — the full payload is never held as one
    bytes object. The safelist applies unchanged (it gates global lookups,
    not framing)."""
    return _SafeUnpickler(fileobj).load()


class _ChunkedWriter:
    """Minimal HTTP/1.1 chunked transfer encoder over the handler's
    ``wfile``; lets the server stream a response whose length is unknown
    up front (the streamed pickle)."""

    def __init__(self, wfile: Any) -> None:
        self._wfile = wfile

    def write(self, data: Any) -> int:
        # protocol-5 pickle passes PickleBuffer objects, not just bytes;
        # go through a flat memoryview so any buffer-protocol payload
        # (numpy array data included) streams without a copy
        mv = memoryview(data).cast("B")
        if mv.nbytes:
            self._wfile.write(f"{mv.nbytes:x}\r\n".encode("ascii"))
            self._wfile.write(mv)
            self._wfile.write(b"\r\n")
        return mv.nbytes

    def close(self) -> None:
        self._wfile.write(b"0\r\n\r\n")


# Module roots whose CLASSES state dicts are really made of. Extendable for
# user classes via register_safe_modules. NOTE: deliberately does NOT
# include torchft_tpu itself — a payload resolving this module's own
# helpers (e.g. register_safe_modules) could widen the list mid-load.
_SAFE_MODULE_ROOTS = {
    "numpy", "optax", "jax", "collections", "ml_dtypes",
}
# Non-class globals required by the numpy array pickle format. Functions
# are otherwise NEVER resolvable (a REDUCE on an arbitrary function is the
# code-execution primitive); these two reconstructors only build arrays.
# _ArraySlot is this module's own streamed-heal placeholder (a frozen
# data-only dataclass) — the ONE torchft_tpu name a skeleton payload may
# reference; everything else in this package stays unresolvable.
_SAFE_EXACT = {
    ("torchft_tpu.checkpointing", "_ArraySlot"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
}
# Builtins narrowed to data constructors: resolving e.g. builtins.eval or
# getattr is how pickle payloads become code execution.
_SAFE_BUILTINS = {
    "bool", "bytearray", "bytes", "complex", "dict", "float", "frozenset",
    "int", "list", "range", "set", "slice", "str", "tuple",
}


def register_safe_modules(*roots: str) -> None:
    """Allows CLASSES from additional top-level modules (e.g. your package
    defining a custom state holder) to be referenced by incoming
    checkpoints. Call at startup on every replica — the set is snapshotted
    when a load begins, so a payload cannot extend it mid-load."""
    _SAFE_MODULE_ROOTS.update(roots)


class _SafeUnpickler(pickle.Unpickler):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        # Snapshot: registration during a hostile load has no effect on it.
        self._roots = frozenset(_SAFE_MODULE_ROOTS)

    def find_class(self, module: str, name: str) -> Any:
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        if (module, name) in _SAFE_EXACT:
            return super().find_class(module, name)
        if module.partition(".")[0] in self._roots:
            obj = super().find_class(module, name)
            # Classes only: data containers may be constructed, but plain
            # functions (the REDUCE code-execution primitive) may not.
            if isinstance(obj, type):
                return obj
        raise pickle.UnpicklingError(
            f"checkpoint references disallowed global {module}.{name}; "
            "if this is your own state CLASS, call "
            "torchft_tpu.checkpointing.register_safe_modules"
            f"({module.partition('.')[0]!r}) on every replica"
        )


def deserialize_state_dict(raw: bytes) -> Any:
    """Inverse of :func:`serialize_state_dict` through the safelisted
    unpickler (see module docstring). Array leaves come back as numpy."""
    return _SafeUnpickler(io.BytesIO(raw)).load()


# -- streamed zero-copy heal transport --------------------------------------

# readinto granularity on the receiver: also the grain at which completed
# leaves become eligible for their h2d dispatch while later bytes are
# still on the wire.
_STREAM_CHUNK = 1 << 20


@dataclass(frozen=True)
class _ArraySlot:
    """Placeholder for one array leaf in the streamed-heal skeleton: where
    its bytes live in the packed stream and how to decode them. Pure data
    — safe to reconstruct from an untrusted payload (safelisted exactly,
    see ``_SAFE_EXACT``)."""

    shape: Tuple[int, ...]
    dtype: str       # original dtype name (what the receiver restores)
    wire_dtype: str  # dtype as shipped (bf16 when downcast on the wire)
    offset: int      # byte offset into the packed stream
    nbytes: int


def _dtype_by_name(name: str) -> np.dtype:
    """np.dtype from its name, resolving ml_dtypes names (bfloat16) that
    plain numpy only knows once ml_dtypes is imported."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _is_opt_state_path(path: Any) -> bool:
    """True when a tree_flatten_with_path keypath passes through a
    component named ``opt_state`` — the ONLY leaves the bf16 wire may
    downcast. Protect-by-default: a layout this predicate doesn't
    recognize ships raw f32 (no compression) rather than silently
    rounding what might be weights — bit-identity of the healed
    replica's parameters must hold for ARBITRARY user state dicts, not
    just ones that happen to name their weights ``params``."""
    for entry in path:
        key = getattr(entry, "key", getattr(entry, "name", None))
        if key is None:
            key = getattr(entry, "idx", None)
        if key == "opt_state":
            return True
    return False


def _heal_wire_from_env() -> Optional[str]:
    wire = os.environ.get("TORCHFT_HEAL_WIRE", "").strip().lower()
    if wire in ("", "none", "f32", "raw"):
        return None
    if wire != "bf16":
        raise ValueError(f"unsupported TORCHFT_HEAL_WIRE: {wire!r}")
    return wire


class _StreamStaging:
    """The donor half of the streamed heal: the CommPlan discipline
    applied to a published state dict. Built ONCE per (step, wire) —
    layout = skeleton tree (array leaves replaced by :class:`_ArraySlot`)
    + per-leaf byte offsets — and then every range request is served as
    memoryview slices straight off the live host buffers: no per-request
    pickle, no concatenated serialized copy. ``wire="bf16"`` casts f32
    leaves INSIDE an ``opt_state`` subtree once at build (the only
    copies the staging ever makes beyond non-contiguous inputs).

    ``shard_of=(rank, world)`` range-limits the capture: the layout
    (offsets, skeleton, ``total``) is computed from shapes alone, then
    only the byte span intersecting this member's ``total*rank//world
    .. total*(rank+1)//world`` range is materialized — a straddling
    leaf contributes just its in-range element slice (aligned to the
    wire itemsize), never the whole array. A durable snapshot member
    only ever writes its own ~1/W shard, so this caps the
    trainer-visible capture cost at ~1/W of the packed stream instead
    of all of it. The floor split MUST mirror ``durable.shard_bounds``;
    range reads outside the captured span raise rather than ship
    silent gaps."""

    def __init__(
        self,
        state_dict: Any,
        wire: Optional[str],
        seq: int = 0,
        snapshot: bool = False,
        shard_of: Optional[Tuple[int, int]] = None,
        pin_leaves: bool = False,
    ) -> None:
        import jax

        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
            state_dict
        )
        # Pass 1 — layout only. Offsets, wire dtypes and the packed
        # total follow from shapes, so the full skeleton exists before a
        # single byte of array data is touched. ``None`` plan entries
        # keep alignment with the skeleton's non-array leaves.
        plan: List[
            Optional[Tuple[Any, Any, np.dtype, np.dtype, int, int]]
        ] = []
        skeleton_leaves: List[Any] = []
        offset = 0
        for path, leaf in leaves_with_path:
            if not (isinstance(leaf, np.ndarray) or _is_jax_leaf(leaf)):
                # scalars / strings / exotic leaves ride the skeleton
                # pickle exactly as before
                skeleton_leaves.append(leaf)
                plan.append(None)
                continue
            odtype = np.dtype(leaf.dtype)
            if (
                wire == "bf16"
                and odtype == np.dtype(np.float32)
                and _is_opt_state_path(path)
            ):
                import ml_dtypes

                wdtype = np.dtype(ml_dtypes.bfloat16)
            else:
                wdtype = odtype
            shape = tuple(leaf.shape)
            nbytes = int(np.prod(shape, dtype=np.int64)) * wdtype.itemsize
            skeleton_leaves.append(
                _ArraySlot(
                    shape=shape,
                    dtype=odtype.name,
                    wire_dtype=wdtype.name,
                    offset=offset,
                    nbytes=nbytes,
                )
            )
            plan.append((path, leaf, odtype, wdtype, offset, nbytes))
            offset += nbytes
        self.total = offset
        if shard_of is not None:
            rank, world = shard_of
            begin = offset * rank // world
            end = offset * (rank + 1) // world
        else:
            begin, end = 0, offset
        self._range = (begin, end)
        if snapshot:
            # Snapshot capture: dispatch every in-range leaf's d2h
            # before materializing any of them, so the transfers overlap
            # each other instead of serializing leaf by leaf — this is
            # the whole trainer stall of an async durable snapshot.
            for ent in plan:
                if ent is None:
                    continue
                _, leaf, _, _, off, nbytes = ent
                if off < end and off + nbytes > begin and _is_jax_leaf(
                    leaf
                ):
                    try:
                        leaf.copy_to_host_async()
                    except AttributeError:
                        pass
        # entries: materialized memoryview, or a deferred-cast
        # ``(f32_slice_view, wire_dtype)`` pair resolved by _seg()
        segments: List[Any] = []
        starts: List[int] = []
        captured = 0
        # ``pin_leaves``: instead of an owning host copy, an uncompressed
        # jax leaf is captured as a zero-copy view with the immutable
        # Array itself pinned here — the XLA buffer cannot be freed while
        # the staging lives. ONLY sound when the trainer never donates
        # these buffers to a jit (donation reuses the device allocation
        # under the view); numpy leaves are mutable in place and always
        # get the owning copy regardless.
        self._pins: List[Any] = []
        for ent in plan:
            if ent is None:
                continue
            path, leaf, odtype, wdtype, off, nbytes = ent
            if off >= end or off + nbytes <= begin:
                # outside this member's shard: layout only, no copy
                continue
            # Leaf-local byte span this shard needs, aligned outward to
            # whole wire elements (a floor-split boundary can land
            # mid-element; the overlapping element is captured by both
            # neighbours, and write_range slices it back to the exact
            # byte). Only the in-range element slice is ever
            # materialized — the straddled remainder of a huge leaf is
            # a peer's duty, not this member's stall.
            ws = wdtype.itemsize
            lo = (max(begin, off) - off) // ws * ws
            hi = min(
                nbytes, -(-(min(end, off + nbytes) - off) // ws) * ws
            )
            sub = np.ascontiguousarray(np.asarray(leaf)).reshape(-1)[
                lo // ws: hi // ws
            ]
            if wdtype != odtype:
                if snapshot and pin_leaves and _is_jax_leaf(leaf):
                    # Deferred wire downcast: the pin keeps the
                    # immutable f32 leaf alive, so the astype (the
                    # compression itself) runs on the WRITER thread at
                    # first segment access — off the trainer stall
                    # entirely.
                    self._pins.append(leaf)
                    segments.append((sub, wdtype))
                    starts.append(off + lo)
                    captured += hi - lo
                    continue
                arr = sub.astype(wdtype)  # the cast owns its bytes
            elif not snapshot:
                # live heal staging: views of the trainer's buffers are
                # fine, the trainer blocks while ranges are read
                arr = np.ascontiguousarray(sub)
            elif pin_leaves and _is_jax_leaf(leaf):
                # zero-copy capture: the pinned immutable Array backs
                # the view for the staging's whole lifetime
                self._pins.append(leaf)
                arr = sub
            elif isinstance(leaf, np.ndarray) and not np.may_share_memory(
                sub, leaf
            ):
                arr = sub  # ascontiguousarray above already copied
            else:
                # Donation/aliasing guard: a SNAPSHOT staging outlives
                # the commit boundary — the background writer reads it
                # while the trainer runs steps N+1..N+k. Every captured
                # slice must own its bytes: a numpy leaf the trainer
                # mutates in place, or a jax leaf whose ``__array__``
                # aliased the device buffer (CPU backend zero-copy /
                # cached npy value) that a later donated jit overwrites,
                # would otherwise leak step-N+1 tensors into the step-N
                # snapshot.
                arr = sub.copy()
            if arr.nbytes != hi - lo:
                raise AssertionError(
                    f"packed layout drift: leaf materialized to "
                    f"{arr.nbytes} bytes, layout planned {hi - lo}"
                )
            # byte view (not a copy): numpy refuses buffer-protocol
            # export of non-native dtypes (ml_dtypes bfloat16), so go
            # through a uint8 reinterpret first
            segments.append(
                memoryview(arr.reshape(-1).view(np.uint8)).cast("B")
            )
            starts.append(off + lo)
            captured += hi - lo
        self.captured_bytes = captured
        self._segments = segments
        self._starts = starts
        skeleton = jax.tree_util.tree_unflatten(treedef, skeleton_leaves)
        buf = io.BytesIO()
        pickle.dump(
            {
                "v": 1,
                "wire": wire,
                "total": offset,
                "seq": seq,
                "skeleton": skeleton,
            },
            buf,
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        self.meta = buf.getvalue()

    def _seg(self, i: int) -> memoryview:
        """Segment ``i`` as a byte view, resolving a deferred wire cast
        on first access (writer-thread side of the zero-copy capture;
        cached so crc + write cast once)."""
        seg = self._segments[i]
        if not isinstance(seg, memoryview):
            sub, wdtype = seg
            arr = sub.astype(wdtype)
            seg = memoryview(arr.reshape(-1).view(np.uint8)).cast("B")
            self._segments[i] = seg
        return seg

    def _check_range(self, begin: int, end: int) -> None:
        cb, ce = self._range
        if begin < cb or end > ce:
            raise ValueError(
                f"range [{begin}, {end}) outside captured span "
                f"[{cb}, {ce}) of a shard-limited staging"
            )

    def write_range(self, wfile: Any, begin: int, end: int) -> None:
        """Streams bytes [begin, end) of the packed layout into ``wfile``
        as zero-copy slices of the staged buffers."""
        import bisect

        if begin >= end:
            return
        self._check_range(begin, end)
        i = bisect.bisect_right(self._starts, begin) - 1
        pos = begin
        while pos < end and i < len(self._segments):
            seg = self._seg(i)
            seg_start = self._starts[i]
            lo = pos - seg_start
            hi = min(len(seg), end - seg_start)
            if lo < hi:
                wfile.write(seg[lo:hi])
                pos = seg_start + hi
            i += 1

    def range_crc32c(self, begin: int, end: int) -> int:
        """CRC32C over bytes [begin, end) of the packed layout — the
        integrity header each /stream/ range response carries (the same
        Castagnoli polynomial the ring frames ride). Walks the exact
        slices :meth:`write_range` ships (zero-copy, chained through the
        native incremental update), so header and body can never
        disagree about what was covered."""
        import bisect

        if begin >= end:
            return _crc32c(b"")
        self._check_range(begin, end)
        i = bisect.bisect_right(self._starts, begin) - 1
        pos = begin
        parts: List[memoryview] = []
        while pos < end and i < len(self._segments):
            seg = self._seg(i)
            seg_start = self._starts[i]
            lo = pos - seg_start
            hi = min(len(seg), end - seg_start)
            if lo < hi:
                parts.append(seg[lo:hi])
                pos = seg_start + hi
            i += 1
        return _crc32c_combine(parts)


def _is_jax_leaf(leaf: Any) -> bool:
    import sys

    jax = sys.modules.get("jax")
    return jax is not None and isinstance(leaf, jax.Array)


def load_packed_meta(raw: bytes) -> Dict[str, Any]:
    """Safelisted unpickle of a packed-stream meta blob (the
    :class:`_StreamStaging` ``meta`` bytes): layout skeleton and wire
    parameters, never arbitrary code (same ``_SafeUnpickler`` the heal
    receiver applies to donor metadata)."""
    meta = _SafeUnpickler(io.BytesIO(raw)).load()
    if not isinstance(meta, dict) or "skeleton" not in meta:
        raise ValueError("packed meta blob missing skeleton")
    return meta


def rebuild_from_packed(
    meta: Dict[str, Any], buf: Any, *, device_put: bool = False
) -> Any:
    """Reconstruct a state tree from a packed byte buffer laid out by
    :class:`_StreamStaging` — the streamed-heal walker without the wire.
    ``buf`` must hold all ``meta['total']`` bytes (a durable snapshot
    reassembled from its shard files, or one donor range already
    verified). Wire-downcast leaves (bf16 opt-state) are cast back to
    their original dtype; with ``device_put`` each rebuilt leaf
    dispatches its async upload and the call blocks only on the residual
    drain."""
    import jax

    total = int(meta["total"])
    if len(buf) < total:
        raise ValueError(
            f"packed buffer holds {len(buf)} bytes, layout needs {total}"
        )
    slots, treedef = jax.tree_util.tree_flatten(meta["skeleton"])
    out_leaves: List[Any] = []
    device_leaves: List[Any] = []
    for slot in slots:
        if not isinstance(slot, _ArraySlot):
            out_leaves.append(slot)
            continue
        wdtype = _dtype_by_name(slot.wire_dtype)
        arr = np.frombuffer(
            buf,
            dtype=wdtype,
            count=slot.nbytes // wdtype.itemsize,
            offset=slot.offset,
        ).reshape(slot.shape)
        odtype = _dtype_by_name(slot.dtype)
        if wdtype != odtype:
            arr = arr.astype(odtype)
        if device_put and jax.dtypes.canonicalize_dtype(odtype) == odtype:
            import jax.numpy as jnp

            leaf: Any = jnp.asarray(arr)
            device_leaves.append(leaf)
        else:
            leaf = arr
        out_leaves.append(leaf)
    if device_leaves:
        jax.block_until_ready(device_leaves)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


class _TimedAcquire:
    """Lock acquire with timeout that raises instead of returning False.
    Reference checkpointing.py:91-107."""

    def __init__(self, lock: threading.Lock, timeout: timedelta) -> None:
        self._lock = lock
        self._timeout = timeout

    def __enter__(self) -> None:
        if not self._lock.acquire(timeout=self._timeout.total_seconds()):
            raise TimeoutError(
                f"timed out acquiring checkpoint lock after {self._timeout}"
            )

    def __exit__(self, *exc: object) -> None:
        self._lock.release()


class CheckpointServer(CheckpointTransport[T]):
    """Threaded HTTP server streaming ``GET /checkpoint/{step}``.

    Reference checkpointing.py:110-270. The server starts in the *disallowed*
    state: requests block on the gate lock until ``send_checkpoint``
    publishes a dict, and re-block after ``disallow_checkpoint``.
    """

    def __init__(self, timeout: timedelta = timedelta(seconds=30)) -> None:
        self._checkpoint_lock = threading.Lock()
        self._disallowed = False
        self._step = -1
        self._timeout = timeout
        self._state_dict: Any = None
        # One-shot pickle cache backing the striped /part/ endpoint
        self._serialized: Any = None
        self._serialized_step = -1
        # Streamed-heal staging, one per wire encoding, built once per
        # published step (the /streammeta/ + /stream/ endpoints)
        self._stagings: Dict[Optional[str], _StreamStaging] = {}
        self._stagings_step = -1
        # Publish nonce: bumped on every allow_checkpoint. Range
        # requests must echo the nonce their meta established — a
        # republish AT THE SAME STEP between a client's meta fetch and a
        # straggler range request would otherwise serve that range from
        # the NEW dict (identical layout, so no framing error) and hand
        # the healer a silently torn mix of two checkpoints.
        self._publish_seq = 0
        # In-flight /stream/ range responses: their bodies are zero-copy
        # views of the LIVE state-dict buffers (unlike the /part/
        # endpoint's immutable pickle cache), so disallow_checkpoint must
        # drain them before the training loop may mutate the dict.
        self._stream_inflight = 0
        self._stream_cv = threading.Condition()
        # What the last recv_checkpoint measured (path taken, fetch/h2d
        # seconds, bytes, wire, streams) — benches fold this into their
        # heal breakdowns.
        self.last_fetch_stats: Optional[Dict[str, Any]] = None

        # Gate starts held: nothing readable until the first send_checkpoint.
        self.disallow_checkpoint()

        ckpt_server = self

        class RequestHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self) -> None:
                try:
                    prefix = "/checkpoint/"
                    if not self.path.startswith(prefix):
                        self.send_error(404, "unknown path")
                        return
                    rest = self.path[len(prefix):].split("/")
                    if len(rest) == 4 and rest[1] == "part":
                        # striped fetch: /checkpoint/{step}/part/{i}/{n}
                        self._serve_part(
                            int(rest[0]), int(rest[2]), int(rest[3])
                        )
                        return
                    if len(rest) == 3 and rest[1] == "streammeta":
                        # streamed-heal layout: /checkpoint/{step}/streammeta/{wire}
                        self._serve_stream_meta(int(rest[0]), rest[2])
                        return
                    if len(rest) == 6 and rest[1] == "stream":
                        # streamed-heal range:
                        # /checkpoint/{step}/stream/{i}/{n}/{wire}/{seq}
                        self._serve_stream_part(
                            int(rest[0]), int(rest[2]), int(rest[3]),
                            rest[4], int(rest[5]),
                        )
                        return
                    if len(rest) != 1:
                        self.send_error(404, "unknown path")
                        return
                    with _TimedAcquire(
                        ckpt_server._checkpoint_lock, ckpt_server._timeout
                    ):
                        step = ckpt_server._step
                        requested = int(rest[0])
                        if requested != step:
                            self.send_error(
                                400,
                                f"invalid checkpoint requested: serving {step} "
                                f"but got {requested}",
                            )
                            return
                        # STREAMED response (chunked): the pickle goes
                        # straight to the socket as it is produced — no
                        # full-payload buffer on the server, so multi-GB
                        # states don't spike host RAM inside the lock
                        # window (reference checkpointing.py:139-170
                        # streams torch.save the same way). The
                        # device->host pull happens BEFORE the 200 is
                        # committed: a wedged d2h (the dominant failure
                        # class) still gets a clean 500, and only a
                        # pickling error can corrupt an in-flight chunk
                        # stream (the peer then fails loudly on framing).
                        host_tree = _to_host(ckpt_server._state_dict)
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "application/octet-stream"
                        )
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        out = _ChunkedWriter(self.wfile)
                        pickle.dump(
                            host_tree, out,
                            protocol=pickle.HIGHEST_PROTOCOL,
                        )
                        out.close()
                except Exception as e:  # noqa: BLE001 - report to the peer
                    logger.exception("checkpoint server error")
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass

            def _serve_part(self, requested: int, i: int, n: int) -> None:
                """One byte-range of the serialized checkpoint, for the
                striped (parallel-connection) fetch. The gate lock is held
                only to validate the step and build/fetch the serialized
                cache — NOT while the body streams, or the N part requests
                would serialize and the parallel fetch would be a no-op.
                The cache is an immutable bytes object, so a concurrent
                disallow_checkpoint (which drops the server's reference)
                cannot mutate an in-flight response."""
                if n < 1 or not (0 <= i < n):
                    self.send_error(404, f"bad part {i}/{n}")
                    return
                with _TimedAcquire(
                    ckpt_server._checkpoint_lock, ckpt_server._timeout
                ):
                    step = ckpt_server._step
                    if requested != step:
                        self.send_error(
                            400,
                            f"invalid checkpoint requested: serving {step} "
                            f"but got {requested}",
                        )
                        return
                    payload = ckpt_server._serialized
                    if payload is None or ckpt_server._serialized_step != step:
                        # Serialized exactly once per published step, shared
                        # by every part of every striped reader. Memory cost
                        # (one full pickle) is the striped transport's
                        # bandwidth-for-memory trade; the single-stream
                        # endpoint above stays allocation-free.
                        payload = serialize_state_dict(
                            ckpt_server._state_dict
                        )
                        ckpt_server._serialized = payload
                        ckpt_server._serialized_step = step
                start = len(payload) * i // n
                end = len(payload) * (i + 1) // n
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(end - start))
                self.end_headers()
                self.wfile.write(payload[start:end])

            def _staging_for(
                self, requested: int, wire_tok: str, track: bool = False,
                seq: Optional[int] = None,
            ) -> Optional[_StreamStaging]:
                """Validates the step and returns the (lazily built)
                zero-copy staging for ``wire_tok`` under the gate lock;
                the LAYOUT is immutable after build, so range bodies
                stream OUTSIDE the lock (parallel range fetches would
                otherwise serialize). ``track=True`` additionally
                registers an in-flight reader WHILE the gate lock is
                still held — range bodies alias the live state-dict
                buffers, and disallow_checkpoint drains tracked readers
                before the dict may mutate. Returns None after having
                sent an error response."""
                wire = None if wire_tok in ("none", "f32", "raw") else wire_tok
                if wire not in (None, "bf16"):
                    self.send_error(404, f"unknown heal wire {wire_tok!r}")
                    return None
                with _TimedAcquire(
                    ckpt_server._checkpoint_lock, ckpt_server._timeout
                ):
                    step = ckpt_server._step
                    if requested != step:
                        self.send_error(
                            400,
                            f"invalid checkpoint requested: serving {step} "
                            f"but got {requested}",
                        )
                        return None
                    if seq is not None and seq != ckpt_server._publish_seq:
                        # Stale publish: the dict was republished (same
                        # step is possible) since this client's meta
                        # fetch — serving the range would mix two
                        # checkpoints. Fail loudly; the client's heal
                        # errors and retries against the new publish.
                        self.send_error(
                            400,
                            f"stale publish: serving seq "
                            f"{ckpt_server._publish_seq}, range asked "
                            f"for {seq}",
                        )
                        return None
                    if ckpt_server._stagings_step != step:
                        ckpt_server._stagings = {}
                        ckpt_server._stagings_step = step
                    staging = ckpt_server._stagings.get(wire)
                    if staging is None:
                        staging = _StreamStaging(
                            ckpt_server._state_dict,
                            wire,
                            seq=ckpt_server._publish_seq,
                        )
                        ckpt_server._stagings[wire] = staging
                    if track:
                        with ckpt_server._stream_cv:
                            ckpt_server._stream_inflight += 1
                    return staging

            def _serve_stream_meta(self, requested: int, wire_tok: str) -> None:
                staging = self._staging_for(requested, wire_tok)
                if staging is None:
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(staging.meta)))
                self.end_headers()
                self.wfile.write(staging.meta)

            def _serve_stream_part(
                self, requested: int, i: int, n: int, wire_tok: str,
                seq: int,
            ) -> None:
                if n < 1 or not (0 <= i < n):
                    self.send_error(404, f"bad stream part {i}/{n}")
                    return
                staging = self._staging_for(
                    requested, wire_tok, track=True, seq=seq
                )
                if staging is None:
                    return
                try:
                    begin = staging.total * i // n
                    end = staging.total * (i + 1) // n
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/octet-stream"
                    )
                    self.send_header("Content-Length", str(end - begin))
                    # Per-range CRC32C (same polynomial as the ring
                    # frames): the receiver verifies before trusting the
                    # bytes — a flipped bit on a heal range otherwise
                    # installs corrupted weights with no vote to catch it.
                    self.send_header(
                        "X-TFT-Crc32c",
                        f"{staging.range_crc32c(begin, end):08x}",
                    )
                    self.end_headers()
                    staging.write_range(self.wfile, begin, end)
                finally:
                    with ckpt_server._stream_cv:
                        ckpt_server._stream_inflight -= 1
                        ckpt_server._stream_cv.notify_all()

            def log_message(self, format: str, *args: object) -> None:
                logger.debug(f"checkpoint server: {format % args}")

        class _Server(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            request_queue_size = 1024
            daemon_threads = True

        self._server = _Server(("::", 0), RequestHandler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name="checkpoint_server",
        )
        self._thread.start()

    @classmethod
    def load_from_address(
        cls,
        address: str,
        timeout: timedelta,
        stripes: Optional[int] = None,
        wire: Optional[str] = "env",
        streams: Optional[int] = None,
        device_put: Optional[bool] = None,
    ) -> T:
        """Fetches a checkpoint from a step-qualified URL.
        Reference checkpointing.py:187-203.

        The STREAMED zero-copy pipeline is tried first (see module
        docstring): layout fetch, then ``streams`` parallel raw byte
        ranges (default: env ``TORCHFT_HEAL_STREAMS``, else ``stripes``)
        read straight into one preallocated buffer, each leaf's device
        upload dispatched while later ranges are still on the wire.
        ``wire`` selects the stream encoding (default: env
        ``TORCHFT_HEAL_WIRE``; ``"bf16"`` halves non-param f32 bytes,
        ``None`` ships everything raw). Pre-stream peers fall back to the
        pickled paths: ``stripes`` > 1 (default: env
        ``TORCHFT_CKPT_STRIPES``, else 4) fetches the pickle as parallel
        byte ranges; a pre-striping peer or ``stripes=1`` takes the
        single-connection streamed-pickle fetch."""
        out, _stats = cls._fetch(
            address, timeout, stripes, wire, streams, device_put
        )
        return out

    @classmethod
    def _fetch(
        cls,
        address: str,
        timeout: timedelta,
        stripes: Optional[int] = None,
        wire: Optional[str] = "env",
        streams: Optional[int] = None,
        device_put: Optional[bool] = None,
    ) -> Tuple[T, Dict[str, Any]]:
        """load_from_address returning ``(tree, stats)`` — the stats dict
        names the path taken and its fetch/h2d seconds for heal-latency
        attribution."""
        if stripes is None:
            stripes = int(os.environ.get("TORCHFT_CKPT_STRIPES", "4"))
        stripes = max(1, min(int(stripes), 64))
        if wire == "env":
            wire = _heal_wire_from_env()
        if streams is None:
            streams = int(
                os.environ.get("TORCHFT_HEAL_STREAMS", str(stripes))
            )
        streams = max(1, min(int(streams), 64))
        logger.info(
            f"fetching checkpoint from {address} "
            f"(streams={streams}, wire={wire}, pickle stripes={stripes})"
        )
        t0 = time.perf_counter()
        try:
            return cls._load_stream(address, timeout, wire, streams, device_put)
        except urllib.error.HTTPError as e:
            if e.code not in (404, 500):
                raise
            # 404/500: a pre-stream peer (or a gate-timeout) — heal must
            # proceed over the pickled paths, not fail
            logger.warning(
                "peer checkpoint server lacks the zero-copy stream "
                f"endpoint (HTTP {e.code}); falling back to pickled fetch"
            )
        except TimeoutError:
            # The stream burned the caller's whole timeout budget
            # (TimeoutError is an OSError subclass — without this clause
            # it would fall through below and each pickled fallback
            # would start a FRESH full-timeout attempt against the same
            # wedged donor, stretching a 30 s heal budget to ~90 s of
            # no-redundancy window the quorum never agreed to).
            raise
        except WireCorruption as e:
            # DETECTED corruption on a stream range: never install the
            # bytes. The pickled fallback re-reads everything from
            # scratch (a transient flip heals itself; a persistently
            # corrupting path will fail there too and surface as a
            # failed heal, not silent weight rot).
            logger.error(
                f"heal stream failed integrity check ({e}); refetching "
                "via the pickled fallback"
            )
        except OSError as e:
            if isinstance(
                getattr(e, "reason", None), TimeoutError
            ):
                # urllib wraps connect/read timeouts as
                # URLError(reason=TimeoutError) — same budget-exhaustion
                # case as the clause above, same verdict.
                raise
            logger.warning(
                f"streamed checkpoint fetch failed ({e!r}); "
                "falling back to pickled fetch"
            )
        if stripes > 1:
            try:
                out = cls._load_striped(address, timeout, stripes)
                return out, {
                    "path": "striped",
                    "stripes": stripes,
                    "fetch_s": time.perf_counter() - t0,
                }
            except urllib.error.HTTPError as e:
                if e.code not in (404, 500):
                    raise
                # 404/500: a pre-striping peer that can't parse the /part/
                # path — heal must proceed at single-stream speed, not fail
                logger.warning(
                    "peer checkpoint server lacks the striped endpoint "
                    f"(HTTP {e.code}); falling back to single-stream fetch"
                )
            except OSError as e:
                # socket timeout / reset mid-stripe (e.g. the server is
                # still serializing a large dict under the gate lock). The
                # streamed path needs no up-front serialize, so the heal
                # can still succeed there.
                logger.warning(
                    f"striped checkpoint fetch failed ({e!r}); "
                    "falling back to single-stream fetch"
                )
        with urllib.request.urlopen(
            address, timeout=timeout.total_seconds()
        ) as f:
            # incremental unpickle off the response stream (http.client
            # de-chunks transparently): bounded memory on the receiver too
            out = load_state_dict_stream(f)
        return out, {"path": "single", "fetch_s": time.perf_counter() - t0}

    @classmethod
    def _load_stream(
        cls,
        address: str,
        timeout: timedelta,
        wire: Optional[str],
        streams: int,
        device_put: Optional[bool],
    ) -> Tuple[T, Dict[str, Any]]:
        """The zero-copy receiver: layout fetch, ``streams`` parallel
        range readers ``readinto``-ing one preallocated buffer, and a
        walker that reconstructs each leaf as a view (f32 path: zero
        copies) the moment its bytes are covered — dispatching its async
        device upload while later ranges are still on the wire. Raises
        ``urllib.error.HTTPError(404)`` against pre-stream peers (the
        caller falls back)."""
        import jax

        if device_put is None:
            # Heal payloads feed straight into jitted code; uploading
            # during the fetch costs nothing extra and removes a full
            # payload pass after it. Host-only users pass False.
            device_put = True
        deadline = time.monotonic() + timeout.total_seconds()
        wire_tok = wire if wire is not None else "none"
        t0 = time.perf_counter()
        with urllib.request.urlopen(
            f"{address}/streammeta/{wire_tok}",
            timeout=timeout.total_seconds(),
        ) as f:
            meta = _SafeUnpickler(f).load()
        total = int(meta["total"])
        seq = int(meta.get("seq", 0))
        skeleton = meta["skeleton"]
        slots, treedef = jax.tree_util.tree_flatten(skeleton)
        buf = bytearray(total)
        view = memoryview(buf)
        bounds = [total * i // streams for i in range(streams + 1)]
        progress = list(bounds[:-1])
        cond = threading.Condition()
        errors: List[BaseException] = []
        # Set when the walker gives up (error/timeout): surviving pull
        # threads must stop downloading, or they'd compete with the
        # pickled fallback fetch for the same link and pin the donor's
        # in-flight reader count against its next disallow.
        cancel = threading.Event()

        def pull(i: int) -> None:
            try:
                begin, end = bounds[i], bounds[i + 1]
                if begin >= end:
                    return
                with urllib.request.urlopen(
                    # the publish nonce from the meta rides every range
                    # request: a republish in between (same step
                    # included) 400s instead of serving torn bytes
                    f"{address}/stream/{i}/{streams}/{wire_tok}/{seq}",
                    timeout=timeout.total_seconds(),
                ) as resp:
                    want_crc = resp.headers.get("X-TFT-Crc32c")
                    pos = begin
                    # Incremental CRC folded into the readinto loop: the
                    # verify never costs a second memory pass on the
                    # heal critical path.
                    crc_state = 0xFFFFFFFF
                    while pos < end and not cancel.is_set():
                        n = resp.readinto(
                            view[pos:min(pos + _STREAM_CHUNK, end)]
                        )
                        if not n:
                            raise OSError(
                                f"heal stream {i} ended early at "
                                f"{pos}/{end}"
                            )
                        if want_crc is not None:
                            crc_state = _crc32c_update(
                                crc_state, view[pos:pos + n]
                            )
                        pos += n
                        if pos >= end and want_crc is not None:
                            # Verify BEFORE publishing the final
                            # progress: the walker only ever consumes
                            # integrity-checked ranges (a pre-CRC donor
                            # sends no header and is trusted as before).
                            got_crc = crc_state ^ 0xFFFFFFFF
                            if got_crc != int(want_crc, 16):
                                raise WireCorruption(
                                    "wire corruption: heal stream range "
                                    f"{i} CRC32C mismatch (got "
                                    f"{got_crc:08x}, donor sent "
                                    f"{want_crc}, bytes [{begin}, {end}))"
                                )
                        with cond:
                            progress[i] = pos
                            cond.notify_all()
            except BaseException as e:  # noqa: BLE001 - wake the walker
                with cond:
                    errors.append(e)
                    cond.notify_all()

        threads = [
            threading.Thread(
                target=pull, args=(i,), daemon=True,
                name=f"heal_stream_{i}",
            )
            for i in range(streams)
        ]
        for t in threads:
            t.start()

        def wait_covered(begin: int, end: int) -> None:
            with cond:
                while True:
                    if errors:
                        raise errors[0]
                    if all(
                        progress[j] >= min(end, bounds[j + 1])
                        for j in range(streams)
                        if bounds[j] < end and bounds[j + 1] > begin
                    ):
                        return
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            "streamed heal fetch timed out "
                            f"(covered through ~{min(progress)}/{total} "
                            "bytes)"
                        )
                    cond.wait(min(remaining, 1.0))

        out_leaves: List[Any] = []
        device_leaves: List[Any] = []
        try:
            for slot in slots:
                if not isinstance(slot, _ArraySlot):
                    out_leaves.append(slot)
                    continue
                wait_covered(slot.offset, slot.offset + slot.nbytes)
                wdtype = _dtype_by_name(slot.wire_dtype)
                arr = np.frombuffer(
                    buf,
                    dtype=wdtype,
                    count=slot.nbytes // wdtype.itemsize,
                    offset=slot.offset,
                ).reshape(slot.shape)
                odtype = _dtype_by_name(slot.dtype)
                if wdtype != odtype:
                    arr = arr.astype(odtype)
                if (
                    device_put
                    # x64-off jax would silently narrow f64/i64 leaves
                    # at upload; those stay host-side numpy (the
                    # transport contract returns the donor's exact
                    # dtypes — the caller owns any canonicalizing
                    # placement)
                    and jax.dtypes.canonicalize_dtype(odtype) == odtype
                ):
                    import jax.numpy as jnp

                    # async h2d dispatch: the upload rides under the
                    # remaining range reads
                    leaf: Any = jnp.asarray(arr)
                    device_leaves.append(leaf)
                else:
                    leaf = arr
                out_leaves.append(leaf)
            for t in threads:
                t.join(max(0.0, deadline - time.monotonic()))
            with cond:
                if errors:
                    raise errors[0]
                if any(t.is_alive() for t in threads):
                    raise TimeoutError(
                        "streamed heal fetch timed out draining"
                    )
        except BaseException:
            # Stop surviving pull threads before the caller falls back
            # (or gives up): abandoned full-range downloads would race
            # the fallback for the same link and hold the donor's
            # in-flight reader count against its next disallow.
            cancel.set()
            raise
        fetch_s = time.perf_counter() - t0
        h2d_s = 0.0
        if device_leaves:
            # The residual upload drain AFTER the last byte arrived — the
            # part of h2d the overlap could not hide.
            t1 = time.perf_counter()
            jax.block_until_ready(device_leaves)
            h2d_s = time.perf_counter() - t1
        return (
            jax.tree_util.tree_unflatten(treedef, out_leaves),
            {
                "path": "stream",
                "wire": wire,
                "streams": streams,
                "bytes": total,
                "fetch_s": fetch_s,
                "h2d_s": h2d_s,
            },
        )

    @classmethod
    def _load_striped(cls, address: str, timeout: timedelta, stripes: int) -> T:
        """Parallel byte-range fetch + one safelisted deserialize. Holds
        the full serialized payload on the receiver (the striped
        transport's bandwidth-for-memory trade)."""

        def fetch(i: int) -> bytes:
            # One retry on 500: the server builds its pickle cache lazily
            # under the gate lock, so the FIRST part request of a large
            # checkpoint can hold the lock past the server's lock timeout
            # and 500 its siblings. By the retry the cache exists and
            # parts stream immediately — without it, one slow serialize
            # would kick the whole heal down to single-stream speed.
            for attempt in (0, 1):
                try:
                    with urllib.request.urlopen(
                        f"{address}/part/{i}/{stripes}",
                        timeout=timeout.total_seconds(),
                    ) as f:
                        return f.read()
                except urllib.error.HTTPError as e:
                    if attempt or e.code != 500:
                        raise

        with ThreadPoolExecutor(
            max_workers=stripes, thread_name_prefix="ckpt_stripe"
        ) as ex:
            parts = list(ex.map(fetch, range(stripes)))
        return deserialize_state_dict(b"".join(parts))

    def address(self) -> str:
        """URL prefix of this server; append the step to fetch."""
        port = self._server.socket.getsockname()[1]
        return f"http://{socket.gethostname()}:{port}/checkpoint/"

    def allow_checkpoint(self, step: int) -> None:
        """Publishes ``step``; unblocks readers. Reference :246-254."""
        self._step = step
        self._publish_seq += 1
        # A staging built under the previous publish carries that
        # publish's nonce in its meta; serving it now would 400 every
        # range. Rebuild lazily under the new nonce.
        self._stagings = {}
        self._stagings_step = -1
        if self._disallowed:
            self._disallowed = False
            self._checkpoint_lock.release()

    def disallow_checkpoint(self) -> None:
        """Re-locks the gate so the dict can be mutated. Reference :256-259.

        Additionally drains in-flight /stream/ range responses before
        returning: their bodies are zero-copy views of the live buffers,
        and a mutation racing a tail of the stream would ship torn bytes
        to a healing replica. New stream readers can't start once the
        gate lock is held (they register under it); stragglers are waited
        out up to the server timeout — a reader still writing past that
        is itself beyond its deadline, and wedging the training loop on
        it would be worse."""
        if not self._disallowed:
            self._disallowed = True
            self._checkpoint_lock.acquire()
            # the dict may mutate now; the pickle + stream caches are stale
            self._serialized = None
            self._serialized_step = -1
            self._stagings = {}
            self._stagings_step = -1
            deadline = time.monotonic() + self._timeout.total_seconds()
            with self._stream_cv:
                while self._stream_inflight > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        logger.warning(
                            f"{self._stream_inflight} streamed heal "
                            "reader(s) still in flight at disallow "
                            "timeout; proceeding (their fetch already "
                            "exceeded its deadline)"
                        )
                        break
                    self._stream_cv.wait(remaining)

    # -- CheckpointTransport --

    def metadata(self) -> str:
        return self.address()

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        self._state_dict = state_dict
        self._serialized = None  # new dict, even at an unchanged step
        self._serialized_step = -1
        self._stagings = {}
        self._stagings_step = -1
        self.allow_checkpoint(step)

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        out, stats = self._fetch(f"{metadata}{step}", timeout)
        self.last_fetch_stats = stats
        return out

    def shutdown(self, wait: bool = True) -> None:
        """Stops serving. Requests in flight hold the gate lock until done."""
        self._server.shutdown()
        if wait:
            self._thread.join()
        self._server.server_close()
