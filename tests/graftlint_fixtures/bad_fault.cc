// Fixture: a native injection point calling the fault engine RAW —
// bypassing TFT_FAULT_CHECK's disarmed fast path. fault_guard must fire.
#include "fault.h"

void leaky_seam() {
  // BAD: pays the decision mutex + hash on every call, armed or not.
  tft::fault::Decision fd =
      tft_fault_maybe(tft::fault::kSeamRingSend, 0, 0);
  (void)fd;
}

void guarded_seam() {
  // GOOD: the macro form — must NOT be flagged.
  tft::fault::Decision fd = TFT_FAULT_CHECK(tft::fault::kSeamRingSend, 0, 0);
  (void)fd;
}
