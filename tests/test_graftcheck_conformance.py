"""Conformance: graftcheck's explored traces against the real objects.

Each protocol model ships a conformance here, closing the loop between
the abstract transition system and the shipped implementation:

- an explored counterexample from a model's BROKEN variant is mapped
  onto the real code, which must refuse exactly the transition the
  broken model performed (the fence exists, and it is the one the model
  says matters); and
- the CORRECT model's predicted verdict (no silent commit, no expired
  member in a quorum, torn tails dropped, identical argmin, gap ->
  abort) is asserted against the live objects driven through the same
  schedule — including one seeded chaos_run.py fleet replay for the
  step-transaction model.

If a model drifts from the code it claims to verify, these tests — not
a clean-but-meaningless exhaustive sweep — catch it.
"""

import os
import struct
import sys
import zlib
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
# tools/ must outrank scripts/: scripts/graftcheck.py (the CLI) would
# otherwise shadow the tools/graftcheck package at import time.
sys.path.insert(0, str(REPO_ROOT / "scripts"))
sys.path.insert(0, str(REPO_ROOT / "tools"))
sys.path.insert(0, str(REPO_ROOT))

import graftcheck  # noqa: E402
from graftcheck import decision as decision_model  # noqa: E402
from graftcheck.core import explore, replay  # noqa: E402

from torchft_tpu import _native  # noqa: E402
from torchft_tpu._native import (  # noqa: E402
    WalLog,
    depart_apply,
    lease_apply,
    quorum_step,
    wal_recover,
)
from torchft_tpu.durable import (  # noqa: E402
    MANIFEST_NAME,
    LocalDirStore,
    ManifestLog,
    inconsistent_marker,
    live_commits,
)
from torchft_tpu.policy import (  # noqa: E402
    SENTINEL_COST_S,
    choose_target,
)
from torchft_tpu.serving import WireDetection, _catch_up_plan  # noqa: E402

# The model's hysteresis constants (HYST_NUM/HYST_DEN = 3/4) express
# "challenger must beat cur * (1 - h)" with h = 1/4.
HYSTERESIS = 1.0 - decision_model.HYST_NUM / decision_model.HYST_DEN


def _to_real_costs(costs):
    """Model cost (saturated at SENT) -> the policy engine's float cost."""
    return [
        SENTINEL_COST_S if c >= decision_model.SENT else float(c)
        for c in costs
    ]


class TestDecisionConformance:
    """decision model <-> policy.choose_target: the identical-argmin the
    uniform_data_step property rides."""

    def _tables(self):
        # Every aggregated cost table reachable in the model: aggregate
        # of any non-empty multiset of MEASURES up to world=3 members.
        seen = set()
        meas = decision_model.MEASURES
        for a in range(len(meas)):
            for b in range(-1, len(meas)):
                for c in range(-1, len(meas)):
                    vecs = [meas[i] for i in (a, b, c) if i >= 0]
                    seen.add(decision_model.aggregate(vecs))
        return sorted(seen)

    def test_model_choose_matches_real_choose_target(self):
        tables = self._tables()
        assert len(tables) > 5
        for costs in tables:
            for cur in range(len(costs)):
                model_pick = decision_model.choose(costs, cur)
                real_pick = choose_target(
                    _to_real_costs(costs), cur, HYSTERESIS
                )
                assert model_pick == real_pick, (costs, cur)

    def test_all_sentinel_keeps_incumbent(self):
        # The argmin_all_sentinel broken variant's fence, on real code.
        costs = [SENTINEL_COST_S, SENTINEL_COST_S]
        assert choose_target(costs, 1, HYSTERESIS) == 1

    def test_sentineled_incumbent_always_loses(self):
        assert choose_target([3.0, SENTINEL_COST_S], 1, HYSTERESIS) == 0

    def test_hysteresis_near_tie_stands_still(self):
        # 3 does not beat 4 * 0.75; 2 does.
        assert choose_target([3.0, 4.0], 1, HYSTERESIS) == 1
        assert choose_target([2.0, 4.0], 1, HYSTERESIS) == 0


class TestDurableConformance:
    """durable model <-> inconsistent_marker / live_commits /
    ManifestLog replay."""

    def _marker(self, rank, step=3, quorum_id=2, world=2):
        return {
            "step": step,
            "quorum_id": quorum_id,
            "world": world,
            "total": world,
            "wire": "f32",
            "rank": rank,
        }

    def test_broken_commit_blocked_by_real_fence(self):
        # The acceptance-criteria counterexample: the broken model
        # commits set 1 after a single writer's shard+marker. Map the
        # trace's marker writes onto the real predicate: it must refuse.
        result = explore(
            graftcheck.make("durable", "commit_without_fence")
        )
        trace = result.violation.trace
        committed_set = next(
            lbl.split("_s")[1] for lbl in trace if lbl.startswith("commit_s")
        )
        writers = {
            int(lbl.rsplit("_w", 1)[1])
            for lbl in trace
            if lbl.startswith("marker_s%s_" % committed_set)
        }
        assert writers != {0, 1}  # the broken model committed early
        markers = {r: self._marker(r) for r in writers}
        bad = inconsistent_marker(
            markers, step=3, quorum_id=2, world=2, total=2, wire="f32"
        )
        assert bad is not None  # the real fence blocks this commit
        missing_rank = bad[0]
        assert missing_rank not in writers and bad[1] is None

    def test_complete_marker_set_is_commit_eligible(self):
        markers = {r: self._marker(r) for r in (0, 1)}
        assert (
            inconsistent_marker(
                markers, step=3, quorum_id=2, world=2, total=2, wire="f32"
            )
            is None
        )

    def test_stale_quorum_marker_rejected(self):
        # The model's fence action (stale qid writer abandoned).
        markers = {0: self._marker(0), 1: self._marker(1, quorum_id=1)}
        bad = inconsistent_marker(
            markers, step=3, quorum_id=2, world=2, total=2, wire="f32"
        )
        assert bad == (1, markers[1])

    def test_live_commits_matches_model_semantics(self):
        records = [
            {"t": "commit", "dir": "set-0"},
            {"t": "commit", "dir": "set-1"},
            {"t": "retire", "dir": "set-0"},
            {"t": "commit", "dir": "set-2"},
        ]
        assert [r["dir"] for r in live_commits(records)] == [
            "set-1",
            "set-2",
        ]

    def test_manifest_torn_tail_never_wins(self, tmp_path):
        # use_torn_tail's fence on the real log: a torn commit record
        # (crash mid-append) is dropped by replay, so the previous
        # commit stays the restorable winner.
        store = LocalDirStore(str(tmp_path))
        log = ManifestLog(store)
        log.append({"t": "commit", "dir": "set-0"})
        log.append({"t": "commit", "dir": "set-1"})
        torn = ManifestLog.frame({"t": "commit", "dir": "set-2"})[:-5]
        store.append(MANIFEST_NAME, torn)
        records, dropped = log.replay()
        assert dropped == len(torn)
        assert [r["dir"] for r in live_commits(records)] == [
            "set-0",
            "set-1",
        ]


class TestWalConformance:
    """wal model <-> the native DurableLog (WalLog/wal_recover): replay
    drops the torn tail, epochs survive, and the correct model refuses
    the broken variant's first move."""

    def test_torn_tail_dropped_promise_not_replayed(self, tmp_path):
        d = str(tmp_path / "wal")
        os.makedirs(d)
        log = WalLog(d)
        log.log_epoch(1)
        log.log_quorum(
            {"quorum_id": 1, "participants": [], "created_ms": 0}, 1, 1
        )
        log.close()
        path = os.path.join(d, "wal.log")
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 4)  # crash mid-append of the quorum record
        rec = wal_recover(d, 0, 0)
        # The torn quorum promise is dropped, never partially applied;
        # the intact epoch record survives (publish-after-log means the
        # fleet never saw the promise either: no regression possible).
        assert rec["dropped_tail_bytes"] > 0
        assert rec["root_epoch"] == 1
        assert rec["quorum_gen"] == 0

    def test_clean_log_replays_promise(self, tmp_path):
        d = str(tmp_path / "wal")
        os.makedirs(d)
        log = WalLog(d)
        log.log_epoch(1)
        log.log_quorum(
            {"quorum_id": 1, "participants": [], "created_ms": 0}, 1, 1
        )
        log.close()
        rec = wal_recover(d, 0, 0)
        assert rec["dropped_tail_bytes"] == 0
        assert rec["quorum_gen"] == 1

    def test_correct_model_refuses_broken_first_move(self):
        # publish_before_log's counterexample rides a publish that
        # precedes the log write. Replayed against the CORRECT model,
        # the schedule either has no such labeled transition (the fence
        # removed it) or — where the label exists but is sequenced
        # behind the log write — ends in a state the correct model
        # still certifies clean. Either way the broken verdict cannot
        # be reproduced under the fence.
        broken = explore(graftcheck.make("wal", "publish_before_log"))
        correct = graftcheck.make("wal")
        from graftcheck.core import ReplayError

        try:
            states = replay(correct, broken.violation.trace)
        except ReplayError:
            return  # the fence removed the transition outright
        assert correct.check(states[-1]) == []


class TestLeaseConformance:
    """lease model <-> the pure _native lease/quorum API."""

    EMPTY = {
        "participants": {},
        "heartbeats": {},
        "lease_ttls": {},
        "prev_quorum": None,
        "quorum_id": 0,
    }

    def _entry(self, rid, ttl_ms, participating=True):
        return {
            "replica_id": rid,
            "ttl_ms": ttl_ms,
            "participating": participating,
            "member": {
                "replica_id": rid,
                "address": f"addr_{rid}",
                "store_address": f"store_{rid}",
                "step": 1,
                "world_size": 1,
                "shrink_only": False,
                "force_reconfigure": False,
            },
        }

    def _opts(self):
        return {
            "min_replicas": 1,
            "join_timeout_ms": 0,
            "quorum_tick_ms": 10,
            "heartbeat_timeout_ms": 5000,
        }

    def test_expired_member_never_in_formed_quorum(self):
        # The no_prune broken variant forms a quorum containing a member
        # whose lease ran out; the real quorum_step must prune it.
        s = lease_apply(
            self.EMPTY,
            [self._entry("a", 1000), self._entry("b", 10_000)],
            5,
        )
        r = quorum_step(2000, 2000, s, self._opts())  # a's lease expired
        names = [m["replica_id"] for m in r["quorum"]["participants"]]
        assert names == ["b"]

    def test_departed_member_leaves_immediately(self):
        s = lease_apply(
            self.EMPTY,
            [self._entry("a", 10_000), self._entry("b", 10_000)],
            5,
        )
        s = depart_apply(s, "a")
        assert "a" not in s["participants"]
        r = quorum_step(10, 10, s, self._opts())
        names = [m["replica_id"] for m in r["quorum"]["participants"]]
        assert names == ["b"]

    def test_quorum_id_monotone_across_reconfigs(self):
        # qid_monotone, realized: every membership change bumps the
        # quorum_id; it never regresses (the watermark the wal model's
        # restarted roots re-learn).
        s = lease_apply(self.EMPTY, [self._entry("a", 10_000)], 5)
        r1 = quorum_step(10, 10, s, self._opts())
        q1 = r1["quorum"]["quorum_id"]
        # renew every live member in the same batch as the joiner (the
        # canonical reconfig sequence test_lease.py establishes)
        s = lease_apply(
            r1["state"],
            [self._entry("a", 10_000), self._entry("b", 10_000)],
            20,
        )
        r2 = quorum_step(30, 30, s, self._opts())
        q2 = r2["quorum"]["quorum_id"]
        assert r2["changed"] and q2 > q1


class TestServingConformance:
    """serving model <-> _catch_up_plan: complete chains install, any
    gap aborts (a detection, never a torn install)."""

    def test_delta_chain_installs(self):
        manifests = {
            1: {"kind": "snapshot"},
            2: {"kind": "delta"},
            3: {"kind": "delta"},
        }
        assert _catch_up_plan(1, manifests) == [2, 3]
        assert _catch_up_plan(-1, manifests) == [1, 2, 3]

    def test_version_never_regresses(self):
        manifests = {1: {"kind": "snapshot"}}
        assert _catch_up_plan(1, manifests) == []
        assert _catch_up_plan(5, manifests) == []

    def test_gap_aborts_instead_of_torn_install(self):
        # no_integrity's verdict inverted: the real planner raises a
        # typed detection rather than assembling a torn mix.
        manifests = {1: {"kind": "snapshot"}, 3: {"kind": "delta"}}
        with pytest.raises(WireDetection):
            _catch_up_plan(1, manifests)


class TestStepTxnFleetConformance:
    """step_txn model <-> the live fleet (scripts/chaos_run.py): the
    correct model's exhaustively-verified verdict — no silent commit, no
    mixed-epoch commit, liveness — replayed as a seeded schedule whose
    fault mirrors the model's message-corruption action (a ring bit flip
    is the wire realization of a corrupted vote/decide payload)."""

    def test_seeded_fleet_reaches_model_verdict(self):
        import chaos_run
        from torchft_tpu.chaos import FaultEvent, FaultPlan

        # The model sweeps to 600k states violation-free; its verdict
        # for any single corrupted message is detect-and-discard.
        capped = explore(graftcheck.make("step_txn"), max_states=30_000)
        assert capped.violation is None

        rec = chaos_run.run_schedule(
            1237,
            "ddp",
            groups=2,
            steps=4,
            plan=FaultPlan(
                seed=1237,
                events=(FaultEvent(1, "ring_send", "bit_flip", 1),),
            ),
            deadline_s=120,
        )
        assert rec["silent_commits"] == 0
        assert rec["epoch_purity_ok"]
        assert rec["crc_detections"] >= 1
        assert rec["liveness_ok"] and rec["bit_identity_ok"]
