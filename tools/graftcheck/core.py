"""graftcheck core: explicit-state exploration of protocol transition systems.

A *model* is a small pure transition system extracted from one of the
repo's distributed protocols (step transaction, leases, WAL, durable
manifest, decision transaction, serving install).  It exposes:

- ``name``        -- registry key, also used in replay lines
- ``properties``  -- documented names of the invariants ``check`` enforces
- ``initial()``   -- the initial state, a hashable nested tuple
- ``actions(s)``  -- ``[(label, next_state), ...]`` in deterministic order;
                     labels are strings, unique per state (replay keys on
                     them)
- ``check(s)``    -- list of violated property names for state ``s``

The explorer runs a breadth-first sweep with state-hash deduplication, a
depth bound and a distinct-state budget.  Parent pointers reconstruct the
shortest counterexample trace, which is printed as a replay line in the
established ``chaos_run.py`` format::

    replay: --model step_txn --trace '["work0", "latch1", ...]'

``replay()`` re-executes a trace label-by-label from ``initial()`` so a
counterexample can be stepped through deterministically (and so the
conformance tests can drive the real Python objects with the same
schedule the model explored).

Determinism contract: models must not consult wall-clock time or
ambient randomness -- all nondeterminism is enumerated as actions.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

State = Any  # hashable nested tuples


@dataclass(frozen=True)
class Counterexample:
    """Shortest-path witness of a property violation."""

    model: str
    prop: str
    trace: tuple  # action labels from initial() to the violating state
    state: State

    def replay_line(self) -> str:
        # Mirrors scripts/chaos_run.py's "replay: --config ... --seed ..."
        return "replay: --model %s --trace '%s'" % (
            self.model,
            json.dumps(list(self.trace)),
        )


@dataclass
class Exploration:
    """Result of one exhaustive sweep."""

    model: str
    states: int = 0  # distinct states reached
    transitions: int = 0  # edges examined (including duplicates)
    depth_reached: int = 0
    complete: bool = False  # frontier drained within the budget
    truncated_by: str = ""  # "", "max_states", or "max_depth"
    violation: Optional[Counterexample] = None
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.violation is None

    def summary(self) -> str:
        status = "ok" if self.ok else "VIOLATION(%s)" % self.violation.prop
        scope = "complete" if self.complete else "truncated:%s" % self.truncated_by
        return "%-12s %9d states %10d transitions  depth %3d  %-22s %6.1fs  %s" % (
            self.model,
            self.states,
            self.transitions,
            self.depth_reached,
            scope,
            self.elapsed_s,
            status,
        )


class Model:
    """Base class for protocol models (subclasses override the four hooks)."""

    name = "model"
    properties: tuple = ()

    def initial(self) -> State:
        raise NotImplementedError

    def actions(self, state: State) -> list:
        raise NotImplementedError

    def check(self, state: State) -> list:
        raise NotImplementedError

    # Committed exploration budget: exhaustive up to this depth / state count.
    def budget(self) -> dict:
        return {"max_depth": 64, "max_states": 400_000}


def explore(
    model: Model,
    max_depth: Optional[int] = None,
    max_states: Optional[int] = None,
    progress: Optional[Callable[[int], None]] = None,
) -> Exploration:
    """Breadth-first exhaustive sweep of ``model`` with dedup and budgets.

    Stops at the first property violation (BFS order makes the witness a
    shortest trace) or when the frontier drains / the budget trips.
    """
    budget = model.budget()
    if max_depth is None:
        max_depth = budget["max_depth"]
    if max_states is None:
        max_states = budget["max_states"]

    t0 = time.monotonic()
    result = Exploration(model=model.name)

    init = model.initial()
    # state -> (parent_state, label_from_parent); None for the root.
    seen: dict = {init: None}
    queue: deque = deque([(init, 0)])
    result.states = 1

    violated = model.check(init)
    if violated:
        result.violation = Counterexample(model.name, violated[0], (), init)
        result.elapsed_s = time.monotonic() - t0
        return result

    truncated_depth = False
    while queue:
        state, depth = queue.popleft()
        if depth > result.depth_reached:
            result.depth_reached = depth
        if depth >= max_depth:
            truncated_depth = True
            continue
        for label, nxt in model.actions(state):
            result.transitions += 1
            if nxt in seen:
                continue
            seen[nxt] = (state, label)
            result.states += 1
            if progress is not None and result.states % 50_000 == 0:
                progress(result.states)
            violated = model.check(nxt)
            if violated:
                result.violation = Counterexample(
                    model.name, violated[0], _trace(seen, nxt), nxt
                )
                result.elapsed_s = time.monotonic() - t0
                return result
            if result.states >= max_states:
                result.truncated_by = "max_states"
                result.elapsed_s = time.monotonic() - t0
                return result
            queue.append((nxt, depth + 1))

    result.complete = not truncated_depth
    if truncated_depth:
        result.truncated_by = "max_depth"
    result.elapsed_s = time.monotonic() - t0
    return result


def _trace(seen: dict, state: State) -> tuple:
    labels = []
    cur = state
    while seen[cur] is not None:
        parent, label = seen[cur]
        labels.append(label)
        cur = parent
    return tuple(reversed(labels))


class ReplayError(Exception):
    pass


def replay(model: Model, trace: Iterable[str]) -> list:
    """Re-execute ``trace`` from ``initial()``; returns the visited states.

    Each label must name exactly one enabled action in the state where it
    is applied -- models keep labels unique per state for this reason.
    """
    state = model.initial()
    states = [state]
    for i, label in enumerate(trace):
        matches = [nxt for lbl, nxt in model.actions(state) if lbl == label]
        if not matches:
            raise ReplayError(
                "%s: step %d: action %r not enabled" % (model.name, i, label)
            )
        if len(matches) > 1:
            raise ReplayError(
                "%s: step %d: action %r ambiguous (%d matches)"
                % (model.name, i, label, len(matches))
            )
        state = matches[0]
        states.append(state)
    return states


# ---------------------------------------------------------------------------
# Small helpers shared by the models.


def tup_set(items) -> tuple:
    """Canonical (sorted, deduplicated) tuple -- a hashable set."""
    return tuple(sorted(set(items)))


def tup_bag(items) -> tuple:
    """Canonical (sorted) tuple with duplicates kept -- a hashable multiset."""
    return tuple(sorted(items))


def bag_remove(bag: tuple, item) -> tuple:
    """Remove one occurrence of ``item`` from a canonical multiset."""
    out = list(bag)
    out.remove(item)
    return tuple(out)
