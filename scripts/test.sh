#!/bin/bash
# One-command build + test, the role of the reference's scripts/test.sh
# (reference scripts/test.sh:10-12: `cargo test && pytest`). Builds the
# native control plane, then runs the Python suite (which exercises the
# native lighthouse/manager/store/ring through ctypes — the C++ has no
# separate test runner; its behavior is covered end-to-end by
# tests/test_control_plane.py, test_quorum.py, test_collectives.py).
set -ex

cd "$(dirname "$0")/.."

make -C native -j"$(nproc)"
python -m pytest tests/ -x -q
