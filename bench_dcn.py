"""Measured comparison of the two cross-replica-group data planes.

VERDICT.md round 1 item 7 asked for the DCN story to be decided with data,
not defaults. This benchmark runs both backends over the same 2-process
cohort on this host and records, for each:

  - allreduce throughput at small/large payloads (the steady-state cost),
  - configure() latency on a membership change (the churn cost),
  - behavior when the peer dies mid-collective (the wedge hazard).

Writes DCN_BENCH.json and prints a summary. The architectural conclusions
live in DCN.md. CPU/gloo/localhost numbers are proxies for TPU-host/DCN —
absolute bandwidths will differ on real fabric, but the structural gaps
(reconfigure invalidating device state; wedge-on-death vs fail-fast) are
platform-independent.

Usage: python bench_dcn.py            # orchestrates everything
"""

import json
import os
import subprocess
import sys
import time
from datetime import timedelta

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SIZES = {"4MB": 1 << 20, "64MB": 16 << 20}  # f32 element counts
ITERS = 5
DEATH_CAP_S = 20.0


def _worker_host(rank: int, store_addr: str, mode: str) -> None:
    import numpy as np

    from torchft_tpu.collectives import HostCollectives, ReduceOp

    hc = HostCollectives(timeout=timedelta(seconds=60),
                         connect_timeout=timedelta(seconds=60))
    t0 = time.perf_counter()
    hc.configure(f"{store_addr}/q0", rank, 2)
    configure_s = time.perf_counter() - t0
    results = {"configure_s": configure_s}

    if mode == "bench":
        for name, n in SIZES.items():
            buf = np.ones((n,), np.float32) * (rank + 1)
            hc.allreduce(buf, ReduceOp.SUM).wait()  # warm
            t0 = time.perf_counter()
            for _ in range(ITERS):
                hc.allreduce(buf, ReduceOp.SUM).wait()
            dt = (time.perf_counter() - t0) / ITERS
            results[name] = {"s": dt, "MBps": (n * 4 / 1e6) / dt}
        t0 = time.perf_counter()
        hc.configure(f"{store_addr}/q1", rank, 2)  # membership change
        results["reconfigure_s"] = time.perf_counter() - t0
    elif mode == "death":
        buf = np.ones((SIZES["4MB"],), np.float32)
        hc.allreduce(buf, ReduceOp.SUM).wait()  # both alive
        if rank == 1:
            os._exit(1)  # die before the next op
        time.sleep(0.5)
        t0 = time.perf_counter()
        try:
            hc.allreduce(buf, ReduceOp.SUM).wait(
                timeout=timedelta(seconds=DEATH_CAP_S)
            )
            results["death"] = {"outcome": "no-error", "s": None}
        except Exception as e:  # noqa: BLE001
            results["death"] = {
                "outcome": f"error:{type(e).__name__}",
                "s": time.perf_counter() - t0,
            }
    print("RESULT " + json.dumps(results), flush=True)
    hc.shutdown()


def _worker_xla(rank: int, store_addr: str, mode: str) -> None:
    from torchft_tpu.platform import apply_jax_platform_env

    apply_jax_platform_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchft_tpu import XLACollectives
    from torchft_tpu.collectives import ReduceOp

    keep_global = mode == "bench_global"
    xc = XLACollectives(timeout=timedelta(seconds=60),
                        connect_timeout=timedelta(seconds=60),
                        keep_global=keep_global)
    t0 = time.perf_counter()
    xc.configure(f"{store_addr}/q0", rank, 2)
    results = {"configure_s": time.perf_counter() - t0}

    if mode in ("bench", "bench_global"):
        for name, n in SIZES.items():
            buf = jnp.ones((n,), jnp.float32) * (rank + 1)
            jax.block_until_ready(buf)
            jax.block_until_ready(xc.allreduce(buf, ReduceOp.SUM).wait())
            t0 = time.perf_counter()
            for _ in range(ITERS):
                jax.block_until_ready(xc.allreduce(buf, ReduceOp.SUM).wait())
            dt = (time.perf_counter() - t0) / ITERS
            results[name] = {"s": dt, "MBps": (n * 4 / 1e6) / dt}
        if mode == "bench":
            # Membership change = full runtime teardown + re-init; live
            # arrays (params!) do not survive, so the realistic cost also
            # includes snapshotting state to host and re-placing it.
            state = jnp.ones((SIZES["64MB"],), jnp.float32)
            jax.block_until_ready(state)
            t0 = time.perf_counter()
            saved = np.asarray(state)
            xc.configure(f"{store_addr}/q1", rank, 2)
            state = jnp.asarray(saved)
            jax.block_until_ready(state)
            results["reconfigure_s"] = time.perf_counter() - t0
    elif mode == "death":
        buf = jnp.ones((SIZES["4MB"],), jnp.float32)
        jax.block_until_ready(xc.allreduce(buf, ReduceOp.SUM).wait())
        if rank == 1:
            os._exit(1)
        time.sleep(0.5)
        t0 = time.perf_counter()
        try:
            w = xc.allreduce(buf, ReduceOp.SUM)
            jax.block_until_ready(
                w.wait(timeout=timedelta(seconds=DEATH_CAP_S))
            )
            results["death"] = {"outcome": "no-error", "s": None}
        except Exception as e:  # noqa: BLE001
            elapsed = time.perf_counter() - t0
            kind = type(e).__name__
            outcome = (
                f"wedged>= {DEATH_CAP_S}s" if elapsed >= DEATH_CAP_S - 0.5
                else f"error:{kind}"
            )
            results["death"] = {"outcome": outcome, "s": elapsed}
    print("RESULT " + json.dumps(results), flush=True)
    if mode != "death":
        xc.shutdown()
    else:
        os._exit(0)  # distributed runtime knows the peer is gone; skip teardown


def _spawn(backend: str, mode: str, store_addr: str):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JAX_CPU_COLLECTIVES_IMPLEMENTATION="gloo")
    env.pop("XLA_FLAGS", None)
    return [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", backend,
             str(r), store_addr, mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(2)
    ]


def _collect(procs, allow_fail=False, timeout=300.0):
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "(timeout)"
        outs.append((p.returncode, out))
    results = []
    for rc, out in outs:
        if not allow_fail:
            assert rc == 0, f"worker failed:\n{out[-2000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                results.append(json.loads(line[len("RESULT "):]))
    return results


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        backend, rank, store_addr, mode = (
            sys.argv[2], int(sys.argv[3]), sys.argv[4], sys.argv[5]
        )
        if backend == "host":
            _worker_host(rank, store_addr, mode)
        else:
            _worker_xla(rank, store_addr, mode)
        return

    from torchft_tpu import Store

    report = {"sizes": {k: v * 4 // (1 << 20) for k, v in SIZES.items()},
              "iters": ITERS}
    for backend, modes in (
        ("host", ["bench", "death"]),
        ("xla", ["bench", "bench_global", "death"]),
    ):
        report[backend] = {}
        for mode in modes:
            store = Store()
            try:
                procs = _spawn(backend, mode, store.address())
                res = _collect(procs, allow_fail=(mode == "death"))
            finally:
                store.shutdown()
            # rank 0's numbers (rank 1 exits early in death mode)
            report[backend][mode] = res[0] if res else {}
            print(f"{backend}/{mode}: {json.dumps(report[backend][mode])}",
                  flush=True)

    with open(os.path.join(REPO, "DCN_BENCH.json"), "w") as f:
        json.dump(report, f, indent=2)
    print("wrote DCN_BENCH.json")


if __name__ == "__main__":
    main()
