"""Fault-tolerant training-step state machine.

Reference: torchft/manager.py:73-705. Every training step is a transaction:

- ``start_quorum()`` establishes membership asynchronously, overlapped with
  the forward/backward computation (quorum RPCs ride a one-thread executor;
  the jitted step runs concurrently — XLA dispatch is already async).
- ``allreduce()`` averages gradient pytrees across replica groups through the
  reconfigurable host collectives; errors are latched, never raised into the
  train loop, and a failed reduce returns the input unchanged so the step can
  be discarded by the commit vote.
- ``should_commit()`` is a distributed AND-vote: if any rank in the group saw
  an error, every group discards the step.
- Recovering replicas fetch live weights from a healthy peer over HTTP
  (:mod:`torchft_tpu.checkpointing`) instead of restarting the world.

TPU mapping: a "replica group" is a TPU slice. Intra-group parallelism (the
HSDP shard dimension) is pjit/shard_map over the slice's ICI mesh and is
invisible to this class; only the cross-group (DCN) gradient average and the
control plane live here, so a dead slice can never wedge an ICI collective.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, TypeVar, cast

from . import _native
from ._native import ManagerClient, StoreClient
from .checkpointing import CheckpointServer, CheckpointTransport
from .collectives import Collectives, ReduceOp, Work, _completed
from .futures import work_timeout
from .metrics import Metrics
from .profiling import Profiler, span

logger: logging.Logger = logging.getLogger(__name__)

MANAGER_ADDR_KEY: str = "manager_addr"
REPLICA_ID_KEY: str = "replica_id"
T = TypeVar("T")


class WorldSizeMode(Enum):
    """How the effective world size behaves under faults.
    Reference manager.py:55-70."""

    DYNAMIC = 0
    FIXED_WITH_SPARES = 1


class Manager:
    """Fault tolerance manager for one rank of one replica group.

    Reference manager.py:73-705. Typically composed with
    :class:`torchft_tpu.optim.OptimizerWrapper` and a gradient-averaging
    wrapper so the train loop stays ``zero_grad(); grads; step()``-shaped.
    """

    def __init__(
        self,
        collectives: Collectives,
        load_state_dict: Optional[Callable[[T], None]],
        state_dict: Optional[Callable[[], T]],
        min_replica_size: int,
        use_async_quorum: bool = True,
        timeout: timedelta = timedelta(seconds=60),
        quorum_timeout: timedelta = timedelta(seconds=60),
        connect_timeout: timedelta = timedelta(seconds=20),
        rank: Optional[int] = None,
        world_size: Optional[int] = None,
        world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
        store_addr: Optional[str] = None,
        lighthouse_addr: Optional[str] = None,
        lighthouse_root_addr: Optional[str] = None,
        region_probe_max: Optional[int] = None,
        lease_ttl: Optional[timedelta] = None,
        region: Optional[str] = None,
        host_label: Optional[str] = None,
        replica_id: Optional[str] = None,
        hostname: str = socket.gethostname(),
        heartbeat_interval: timedelta = timedelta(milliseconds=100),
        checkpoint_transport: Optional[CheckpointTransport[Dict[str, T]]] = None,
        profiler: Optional["Profiler"] = None,
        iso_collectives: Optional[Collectives] = None,
        durable_restore: Optional[Callable[[], Optional[int]]] = None,
    ) -> None:
        """
        Args:
            collectives: the reconfigurable cross-replica-group collectives.
            load_state_dict: callback restoring USER state from a recovery
                checkpoint (the manager handles its own state separately).
            state_dict: callback capturing USER state for recovery transfer.
            min_replica_size: minimum replica groups for a committable step.
            use_async_quorum: overlap quorum with forward/backward; healing
                replicas then skip participation for one step (reference
                manager.py:119-127).
            rank / world_size: this rank within the replica group (env
                ``RANK``/``WORLD_SIZE`` when None).
            store_addr: ``host:port`` of the replica group's rendezvous
                Store (env ``MASTER_ADDR``+``MASTER_PORT`` when None; if
                neither is set and world_size == 1, an in-process Store is
                created).
            lighthouse_addr: this group's lighthouse (env
                ``TORCHFT_LIGHTHOUSE``): the flat/root service, or the
                group's REGION lighthouse under a hierarchical tier.
            lighthouse_root_addr: root fallback for the hierarchical tier
                (env ``TORCHFT_LIGHTHOUSE_ROOT``): a dead region demotes
                the group to direct-root registration until it returns.
                May be a COMMA-SEPARATED endpoint list (the durable
                control plane's root failover set — active root + warm
                standbys); renewals rotate to the next endpoint on
                failure. ``lighthouse_addr`` accepts a list the same way.
            region_probe_max: bounded give-up for the demoted manager's
                once-per-TTL region re-probes (env
                ``TORCHFT_REGION_PROBE_MAX``, default 20): after this
                many consecutive failed probes the manager stops probing
                and stays on the root — a region GONE from the topology
                must not leak a doomed connect attempt per TTL for the
                rest of the tenure. 0 = probe forever (the pre-bound
                behavior; a revived region then always wins the group
                back).
            lease_ttl: membership lease duration (env
                ``TORCHFT_LEASE_TTL_MS``; None = the lighthouse's
                heartbeat-timeout default). Renewals are jittered and back
                off exponentially while the lighthouse is unreachable.
            region: this replica group's topology label (env
                ``TORCHFT_REGION``; "" = unlabeled) — the same label the
                hierarchical lighthouse tier is deployed by. It rides the
                quorum, and when EVERY quorum member carries one (>= 2
                distinct regions), ``configure`` hands the region map to
                the data plane, which compiles the topology-aware
                two-tier collective schedule (intra-region rings + an
                inter-region leader ring; see
                ``HostCollectives.allreduce_hier``).
            host_label: this replica group's HOST label (env
                ``TORCHFT_HOST``; defaults to the machine hostname, ""
                disables). It rides the quorum like ``region``, and
                whenever a (region, host) pair groups >= 2 members,
                ``configure`` hands the host map to the data plane, which
                builds the shared-memory intra-host ring tier below the
                region tiers — co-hosted members sync at memcpy speed
                instead of loopback TCP (``TORCHFT_HC_SHM`` gates the
                transport).
            replica_id: replica group name; a uuid suffix is appended by
                group rank 0 (reference manager.py:196-200).
            profiler: windowed jax profiler capture advanced once per
                step; defaults to ``Profiler.from_env()``
                (``TORCHFT_PROFILE_DIR`` etc., torchft_tpu.profiling).
            iso_collectives: optional SECONDARY data plane — an
                :class:`~torchft_tpu.isolated_xla.IsolatedXLACollectives`
                backend reconfigured alongside the primary on every
                quorum change (on an ``/iso`` store sub-prefix, so the
                two planes never cross-talk) and dispatched through
                :meth:`iso_allreduce`. AdaptiveDDP's ``xla_iso``
                candidate probes it against the host ring with the same
                lockstep-vote argmin that picks the schedule.
            durable_restore: the durable tier's cold-start fallback —
                a callable (``DurableCheckpointer.restore_latest``)
                that applies the latest committed durable checkpoint
                (user + manager state) and returns its step, or None
                when nothing is committed. Consulted ONCE, inside the
                first quorum, and only when the quorum reports no live
                donor (``max_step == 0``): a cold fleet restores
                without the trainer calling restore before its loop,
                while a live donor always wins (its weights are at
                least as fresh as any durable snapshot).
                ``DurableCheckpointer`` registers itself through
                :meth:`set_durable_restore`.
        """
        self._load_state_dict = load_state_dict
        self._user_state_dict = state_dict
        self._min_replica_size = min_replica_size
        self._use_async_quorum = use_async_quorum
        self._timeout = timeout
        self._quorum_timeout = quorum_timeout
        self._connect_timeout = connect_timeout
        self._world_size_mode = world_size_mode

        self._rank: int = rank if rank is not None else int(os.environ.get("RANK", 0))
        self._world_size: int = (
            world_size
            if world_size is not None
            else int(os.environ.get("WORLD_SIZE", 1))
        )

        self._owned_store: Optional[_native.Store] = None
        if store_addr is None:
            if "MASTER_ADDR" in os.environ and "MASTER_PORT" in os.environ:
                store_addr = (
                    f"{os.environ['MASTER_ADDR']}:{os.environ['MASTER_PORT']}"
                )
            elif self._world_size == 1:
                self._owned_store = _native.Store()
                store_addr = self._owned_store.address()
            else:
                raise ValueError(
                    "store_addr (or MASTER_ADDR/MASTER_PORT) required when "
                    "world_size > 1"
                )
        self._store_addr = store_addr
        self._store = StoreClient(store_addr, connect_timeout=connect_timeout)

        self._collectives = collectives
        self._iso_collectives = iso_collectives
        self._iso_ok = False
        self._checkpoint_transport: CheckpointTransport[Dict[str, T]] = (
            checkpoint_transport
            if checkpoint_transport is not None
            else CheckpointServer(timeout=timeout)
        )
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="async_quorum"
        )
        self._quorum_future: Optional[Any] = None

        self._step = 0
        self._batches_committed = 0
        self._quorum_id = -1
        self._errored: Optional[Exception] = None
        self._op_epoch = 0
        # Makes the {epoch check -> error latch} in work callbacks atomic
        # against the {epoch bump -> error clear} in start_quorum; without
        # it a stale callback could pass the check, lose the GIL across the
        # bump+clear, then latch into the new step.
        self._error_lock = threading.Lock()
        self._force_reconfigure = False
        self._healing = False
        self._pending_work: List[Work] = []
        self._commit_hooks: List[Any] = []
        self._durable_restore = durable_restore
        self._durable_consulted = False
        self._pending_state_dict: Optional[Dict[str, object]] = None
        self._participating_rank: Optional[int] = None
        self._participating_world_size: int = 0
        self._metrics = Metrics()
        # Last measured effective wire throughput (MB/s), updated by
        # observe_op_stats(); None until a ring op has been observed.
        self._last_wire_eff_mbps: Optional[float] = None
        # Per-tier effective throughput of the last hierarchical op
        # (MB/s per tier key; shm host tiers measure ring movement over
        # phase wall). Empty until a hier op has been observed.
        self._last_tier_mbps: Dict[str, float] = {}
        # Resident optimizer-state bytes as reported by the training
        # strategy (ShardedDDP reports its ~1/W shard); None until one
        # reports. Exported through signals() so the policy engine can
        # price the sharded candidate's memory term.
        self._opt_state_bytes: Optional[int] = None
        self._profiler = (
            profiler if profiler is not None else Profiler.from_env()
        )

        lighthouse_addr = lighthouse_addr or os.environ.get("TORCHFT_LIGHTHOUSE")
        lighthouse_root_addr = lighthouse_root_addr or os.environ.get(
            "TORCHFT_LIGHTHOUSE_ROOT", ""
        )
        if region_probe_max is None:
            region_probe_max = int(
                os.environ.get("TORCHFT_REGION_PROBE_MAX", "20")
            )
        self._region_probe_max = region_probe_max
        if lease_ttl is None:
            env_ttl = os.environ.get("TORCHFT_LEASE_TTL_MS")
            if env_ttl:
                lease_ttl = timedelta(milliseconds=int(env_ttl))
        if region is None:
            region = os.environ.get("TORCHFT_REGION", "")
        self._region = region
        if host_label is None:
            host_label = os.environ.get("TORCHFT_HOST", socket.gethostname())
        self._host_label = host_label
        # The quorum's region and host maps (replica-rank order),
        # refreshed every quorum; what hier_capable() and the configure
        # call key off.
        self._replica_regions: List[str] = []
        self._replica_hosts: List[str] = []
        replica_id = replica_id if replica_id is not None else ""

        self._manager: Optional[_native.Manager] = None
        if self._rank == 0:
            if lighthouse_addr is None:
                raise ValueError(
                    "lighthouse_addr (or TORCHFT_LIGHTHOUSE) required on rank 0"
                )
            # Group rank 0 hosts the native manager server and publishes its
            # address + the uuid-qualified replica id through the store
            # (reference manager.py:184-211).
            replica_id = (
                f"{replica_id}:{uuid.uuid4()}" if replica_id else str(uuid.uuid4())
            )
            bind = f"[::]:{int(os.environ.get('TORCHFT_MANAGER_PORT', 0))}"
            self._manager = _native.Manager(
                replica_id=replica_id,
                lighthouse_addr=lighthouse_addr,
                hostname=hostname,
                bind=bind,
                store_addr=store_addr,
                world_size=self._world_size,
                heartbeat_interval=heartbeat_interval,
                connect_timeout=connect_timeout,
                root_addr=lighthouse_root_addr,
                lease_ttl=lease_ttl,
                region=region,
                host=host_label,
                region_probe_max=region_probe_max,
            )
            self._store.set(MANAGER_ADDR_KEY, self._manager.address().encode())
            self._store.set(REPLICA_ID_KEY, replica_id.encode())

        addr = self._store.get(MANAGER_ADDR_KEY, timeout=connect_timeout).decode()
        self._client = ManagerClient(addr, connect_timeout=connect_timeout)
        self._replica_id = self._store.get(
            REPLICA_ID_KEY, timeout=connect_timeout
        ).decode()
        self._logger = _ManagerLogger(self, self._replica_id, self._rank)

    def shutdown(self) -> None:
        if self._profiler is not None:
            self._profiler.shutdown()
        self._checkpoint_transport.shutdown(wait=False)
        self._executor.shutdown(wait=True)
        if self._iso_collectives is not None:
            self._iso_collectives.shutdown()
        if self._manager is not None:
            self._manager.shutdown()

    # -- step lifecycle --

    def start_quorum(
        self,
        allow_heal: bool = True,
        shrink_only: bool = False,
        timeout: Optional[timedelta] = None,
    ) -> None:
        """Computes a new quorum, asynchronously unless configured otherwise.

        Must be called at the start of every train step (before the first
        ``allreduce``) on every rank. Reference manager.py:365-415.
        """
        if self._profiler is not None:
            self._profiler.on_step(self._step)
        if self._quorum_future is not None:
            # Wait for the previous quorum (and any healing) to finish. Its
            # errors were already surfaced through allreduce/should_commit;
            # a new step starts from a clean slate.
            try:
                self._quorum_future.result()
            except Exception:
                pass

        with self._error_lock:
            self._op_epoch += 1
            self._errored = None
        self._healing = False
        self._pending_work = []
        self._quorum_future = self._executor.submit(
            self._async_quorum,
            allow_heal=allow_heal,
            shrink_only=shrink_only,
            quorum_timeout=timeout or self._quorum_timeout,
        )
        if not self._use_async_quorum:
            self.wait_quorum()
            if self._healing:
                # Eagerly apply the fetched checkpoint so the optimizer sees
                # the recovered state this same step; sync-mode healers then
                # participate fully (reference :406-414).
                self._apply_pending_state_dict()
                self._healing = False

    def wait_quorum(self) -> None:
        """Blocks until the quorum started by ``start_quorum`` completes."""
        assert (
            self._quorum_future is not None
        ), "must call start_quorum before wait_quorum"
        self._quorum_future.result()

    def _async_quorum(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: timedelta
    ) -> None:
        # Atomically consume the rebuild request so a report_error racing
        # with the RPC can't be wiped by an unconditional clear afterwards;
        # restore it if the RPC fails (the rebuild still hasn't happened).
        with self._error_lock:
            force_reconfigure = self._force_reconfigure
            self._force_reconfigure = False
        try:
            with self._metrics.timed("quorum"), span("torchft::quorum"):
                result = self._client.quorum(
                    rank=self._rank,
                    step=self._step,
                    checkpoint_metadata=self._checkpoint_transport.metadata(),
                    shrink_only=shrink_only,
                    force_reconfigure=force_reconfigure,
                    timeout=quorum_timeout,
                )
        except Exception:
            if force_reconfigure:
                with self._error_lock:
                    self._force_reconfigure = True
            raise

        quorum_id = result.quorum_id
        store_address = result.store_address

        if self._use_async_quorum or not allow_heal:
            # Participate only if already at max step: healing overlaps with
            # this step, so recovering replicas sit it out (reference
            # manager.py:452-456).
            participating_rank: Optional[int] = result.max_rank
            participating_world = result.max_world_size
        else:
            participating_rank = result.replica_rank
            participating_world = result.replica_world_size

        if self._world_size_mode == WorldSizeMode.FIXED_WITH_SPARES:
            # Spares join collectives with zeroed grads; the divisor stays
            # fixed so numerics never change under churn. Clamped with min()
            # so a cohort BELOW min_replica_size still fails the
            # enough-replicas vote in should_commit (reference :459-468).
            if (
                participating_rank is not None
                and participating_rank >= self._min_replica_size
            ):
                participating_rank = None
            participating_world = min(participating_world, self._min_replica_size)

        self._participating_rank = participating_rank
        self._participating_world_size = participating_world
        heal = allow_heal and result.heal

        if self._durable_restore is not None and not self._durable_consulted:
            # Restore-time donor/durable arbitration, one-shot at the
            # first quorum. A live donor (max_step > 0) always beats the
            # durable tier — its weights are at least as fresh as any
            # committed snapshot and the normal heal path ships them —
            # so the durable fallback only fires on a COLD fleet: no
            # member has committed a step and this member hasn't
            # restored anything itself. Every member consults its own
            # restore_latest against the shared store, so the fleet
            # rises at one consistent committed step; members that find
            # nothing init-sync from a restored peer as usual.
            self._durable_consulted = True
            if self._step == 0 and result.max_step == 0:
                restored = self._durable_restore()
                if restored is not None:
                    self._metrics.incr("durable_cold_restores")
                    self._logger.info(
                        f"cold fleet: restored durable step {restored} "
                        "(no live donor in quorum)"
                    )

        if quorum_id != self._quorum_id:
            if self._quorum_id != -1:
                # Membership moved (or a data-plane error forced a rebuild)
                # mid-run — the rolling churn signal the policy engine and
                # the status export watch. The FIRST configure is a cold
                # start, not churn.
                self._metrics.mark("churn")
            # Reconfigure the data plane on a store prefix unique to this
            # quorum AND this local rank: cross-group rings are per local
            # rank, and stale members can't collide (reference :470-477).
            prefix = f"{store_address}/torchft/{quorum_id}/{self._rank}"
            self._logger.info(f"reconfiguring collectives quorum_id={quorum_id}")
            # The quorum's region and host maps (one label per replica
            # rank) ride into the data plane: a host ring compiles them
            # into the hierarchical schedule when usable; other backends
            # ignore them. The hosts kwarg is passed only to backends
            # that declare it (every in-repo backend does) so external
            # stand-ins with the pre-host signature keep working.
            regions = list(result.replica_regions)
            self._replica_regions = regions
            hosts = list(result.replica_hosts)
            self._replica_hosts = hosts
            cfg_kwargs: Dict[str, Any] = {"regions": regions or None}
            if hosts and any(hosts) and self._configure_takes_hosts():
                cfg_kwargs["hosts"] = hosts
            with self._metrics.timed("reconfigure"), span(
                "torchft::reconfigure"
            ):
                self._collectives.configure(
                    prefix, result.replica_rank, result.replica_world_size,
                    **cfg_kwargs,
                )
            if self._iso_collectives is not None:
                # The secondary (isolated) plane reconfigures on its own
                # sub-prefix: same quorum, disjoint store keys — its
                # kill-and-respawn cannot collide with the ring's
                # rendezvous, and a stale child never cross-talks. A
                # failure here (un-spawnable child, dead fork server)
                # must NEVER take the primary plane down with it: the
                # plane is marked unusable, iso dispatches latch, and the
                # AdaptiveDDP probe's failure sentinel keeps the
                # candidate from ever winning ("never beat-by-crash").
                with self._metrics.timed("reconfigure_iso"):
                    try:
                        self._iso_collectives.configure(
                            f"{prefix}/iso",
                            result.replica_rank,
                            result.replica_world_size,
                        )
                        self._iso_ok = True
                    except Exception as e:  # noqa: BLE001
                        self._iso_ok = False
                        self._metrics.incr("iso_configure_failures")
                        self._logger.exception(
                            f"isolated data plane configure failed "
                            f"(primary plane unaffected): {e}"
                        )
            self._metrics.incr("reconfigures")
            self._quorum_id = quorum_id

        if allow_heal:
            if result.recover_dst_ranks:
                # This replica is a recovery source: publish live weights.
                self._logger.info(
                    f"peers need recovery from us {result.recover_dst_ranks}"
                )
                with span("torchft::send_checkpoint"):
                    self._checkpoint_transport.send_checkpoint(
                        dst_ranks=result.recover_dst_ranks,
                        step=result.max_step,
                        state_dict=self._manager_state_dict(),
                        timeout=self._timeout,
                    )
            if heal:
                self._healing = True
                # A recovery at max_step 0 is the initial weight
                # synchronization every fresh cohort's non-primary runs —
                # not a fault. Counting it as a heal would seed the policy
                # engine's churn-cost signal with a phantom fault recovery
                # on every clean startup.
                self._metrics.incr(
                    "heals" if result.max_step > 0 else "init_sync_heals"
                )
                self._logger.info(
                    f"healing required, fetching checkpoint from "
                    f"{result.recover_src_manager_address} step={result.max_step}"
                )
                primary_client = ManagerClient(
                    result.recover_src_manager_address,
                    connect_timeout=self._connect_timeout,
                )
                checkpoint_metadata = primary_client.checkpoint_metadata(
                    self._rank, timeout=self._timeout
                )
                assert result.recover_src_rank is not None
                with self._metrics.timed("heal_fetch"), span(
                    "torchft::recv_checkpoint"
                ):
                    checkpoint = self._checkpoint_transport.recv_checkpoint(
                        src_rank=result.recover_src_rank,
                        metadata=checkpoint_metadata,
                        step=result.max_step,
                        timeout=self._timeout,
                    )
                # Manager state is applied immediately (so step/commit
                # counters are right); user state waits for a safe point on
                # the main thread (reference :514-526).
                self._pending_state_dict = cast(Dict[str, object], checkpoint)
                self.load_state_dict(
                    cast(Dict[str, int], checkpoint["torchft"])
                )

    def _apply_pending_state_dict(self) -> None:
        assert self._healing, "apply_pending_state_dict called when not healing"
        # Settle the quorum thread first: it is the writer of
        # _pending_state_dict (reference manager.py:531-532).
        self.wait_quorum()
        assert (
            self._pending_state_dict is not None
        ), "checkpoint was not fetched before apply"
        assert self._load_state_dict is not None, "no load_state_dict callback"
        self._logger.info("applying pending state dict")
        with self._metrics.timed("heal_apply"):
            self._load_state_dict(cast(T, self._pending_state_dict["user"]))
        self._pending_state_dict = None

    # -- data plane --

    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.AVG,
        wire: Optional[str] = None,
    ) -> Work:
        """Fault-tolerantly averages a gradient pytree across replica groups.

        Data-plane errors never raise: on a collective failure the returned
        Work resolves to the tree AS CONTRIBUTED — the input tree for a
        participating replica, the zeroed tree for a healing/spare one
        (zero-contribution holds even on the fallback) — and the error is
        latched for ``should_commit`` (reference manager.py:242-303). A failed or
        timed-out QUORUM, however, DOES raise out of this call (via
        ``wait_quorum``) — membership failure means the step cannot proceed
        at all, matching reference manager.py:265. Non-participating
        (healing/spare) replicas contribute zeros. ``op`` must be AVG
        (divide by ``num_participants``, the live divisor, reference
        :279-291) or SUM. ``wire`` forwards to the collectives backend
        (``"q8"`` = int8-quantized ring chunks, constant wire bytes in
        world size — see Collectives.allreduce).
        """
        def dispatch(zeroed_tree: Any) -> Work:
            if op == ReduceOp.AVG:
                # The participant average rides the collectives' divisor
                # path (applied host-side in the ring, where the bytes
                # already are) — no extra jit program or device dispatch
                # per step. Divisor = num_participants, NOT ring size:
                # healing/spare members contribute zeros and don't count
                # (reference manager.py:279-291).
                num_participants = self.num_participants()
                assert num_participants >= 1
                divisor: Optional[float] = float(num_participants)
            elif op == ReduceOp.SUM:
                divisor = None
            else:
                raise ValueError(f"unsupported managed allreduce op: {op}")
            return self._collectives.allreduce(
                zeroed_tree, ReduceOp.SUM, divisor=divisor, wire=wire
            )

        return self._managed_dispatch("allreduce", tree, dispatch, lambda t: t)

    def plan_allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.AVG,
        wire: Optional[str] = None,
        device_pack: Optional[bool] = None,
        hier: bool = False,
    ) -> Work:
        """Fault-tolerantly averages a gradient pytree through a
        persistent precompiled comm plan (one GIL-released native call
        per step — see Collectives.plan_allreduce). Same quorum and
        latching discipline as :meth:`allreduce`, with one difference in
        the failure default: a failed plan execute resolves to ``None``
        (not the input tree) — the plan's persistent output buffers may
        hold a partial unpack, so there is no meaningful "as contributed"
        tree to return. The error latches and ``should_commit`` discards
        the step; callers must treat a ``None`` result as an aborted
        sync, never as data. Plans are invalidated (and transparently
        rebuilt) whenever the quorum changes — configure() drops them
        with the old ring. ``wire``: None | "bf16" | "q8" | "q8ef"
        (native error feedback; reset the carry on heal via
        :meth:`reset_plan_feedback`). ``device_pack`` forwards to the
        backend (True/False/None = ``TORCHFT_DEVICE_PACK``): pack the
        wire encoding on the accelerator so d2h bytes scale with the
        wire, results bit-identical either way — see
        Collectives.plan_allreduce. ``hier`` runs the plan over the
        TWO-TIER schedule (see :meth:`allreduce_hier`); a cohort without
        a usable region map latches the error and the step is discarded
        — the sentinel path AdaptiveDDP's ``plan_hier`` candidate relies
        on, never a crash."""
        if op not in (ReduceOp.AVG, ReduceOp.SUM):
            # Static usage error: raise eagerly, don't latch.
            raise ValueError(f"unsupported managed plan_allreduce op: {op}")

        def dispatch(zeroed_tree: Any) -> Work:
            if op == ReduceOp.AVG:
                num_participants = self.num_participants()
                assert num_participants >= 1
                divisor: Optional[float] = float(num_participants)
            else:
                divisor = None
            return self._collectives.plan_allreduce(
                zeroed_tree, ReduceOp.SUM, divisor=divisor, wire=wire,
                device_pack=device_pack, hier=hier,
            )

        return self._managed_dispatch(
            "plan_allreduce", tree, dispatch, lambda t: None
        )

    def allreduce_hier(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.AVG,
        wire: Optional[str] = None,
    ) -> Work:
        """Fault-tolerantly averages a pytree over the TOPOLOGY-AWARE
        two-tier schedule (``Collectives.allreduce_hier``): intra-region
        reduce-scatter -> intra allgather -> inter-region ring among one
        leader per region -> intra broadcast, so the slow inter-region
        links carry a fraction of the flat ring's bytes and only on the
        leaders. ``wire`` (``None`` | ``"bf16"`` | ``"q8"``) applies to
        the inter hop only. Same quorum/zeroing/latching discipline as
        :meth:`allreduce` (failure resolves to the tree as contributed,
        the error latches, ``should_commit`` discards); a cohort whose
        region map is unusable (single region, unlabeled members, or a
        backend without the schedule) latches the dispatch error — the
        sentinel discipline, never a crash."""
        if op not in (ReduceOp.AVG, ReduceOp.SUM):
            raise ValueError(f"unsupported managed allreduce_hier op: {op}")

        def dispatch(zeroed_tree: Any) -> Work:
            if op == ReduceOp.AVG:
                num_participants = self.num_participants()
                assert num_participants >= 1
                divisor: Optional[float] = float(num_participants)
            else:
                divisor = None
            return self._collectives.allreduce_hier(
                zeroed_tree, ReduceOp.SUM, divisor=divisor, wire=wire
            )

        return self._managed_dispatch(
            "allreduce_hier", tree, dispatch, lambda t: t
        )

    def hier_capable(self) -> bool:
        """Whether the CURRENT quorum's data plane compiled a two-tier
        (topology-aware) schedule: every member carried a region label
        and >= 2 distinct regions were present, on a backend that
        understands topology (the host ring). Settles the quorum thread
        first — the region map is its writer."""
        if self._quorum_future is not None:
            self.wait_quorum()
        cap = getattr(self._collectives, "hier_capable", None)
        return bool(cap()) if cap is not None else False

    def _configure_takes_hosts(self) -> bool:
        try:
            import inspect

            sig = inspect.signature(self._collectives.configure)
        except (TypeError, ValueError):
            return False
        params = sig.parameters
        return "hosts" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
        )

    def replica_hosts(self) -> List[str]:
        """The current quorum's host map, indexed by replica rank (empty
        until the first quorum; empty strings for unlabeled members).
        Paired with :meth:`replica_regions`: (region, host) groups are
        what the data plane compiles into the shared-memory intra-host
        tier."""
        return list(self._replica_hosts)

    def replica_regions(self) -> List[str]:
        """The current quorum's region map, indexed by replica rank
        (empty strings for unlabeled members; empty before the first
        quorum). Settles the quorum thread first."""
        if self._quorum_future is not None:
            self.wait_quorum()
        return list(self._replica_regions)

    def has_iso_plane(self) -> bool:
        """Whether a secondary isolated data plane was attached at
        construction (NOT whether its child is currently healthy — a
        sick plane still exists, and its dispatch failures are exactly
        what the probe's sentinel discipline measures)."""
        return self._iso_collectives is not None

    def iso_collectives(self) -> Optional[Collectives]:
        """The attached isolated data plane (None without one)."""
        return self._iso_collectives

    def iso_allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.AVG,
        wire: Optional[str] = None,
    ) -> Work:
        """Fault-tolerantly averages a gradient pytree through the
        ISOLATED data plane (the disposable-child XLA backend attached
        as ``iso_collectives``): same quorum/zeroing/latching discipline
        as :meth:`allreduce`, with the failure default ``None`` — a
        child that died mid-op leaves no meaningful "as contributed"
        tree (its shared-memory staging may hold a partial result), so
        the Work resolves to ``None``, the error latches, and
        ``should_commit`` discards the step; the error's forced
        reconfigure then respawns the child at the next quorum (step-
        granularity recovery). Raises eagerly (static usage error) when
        no isolated plane was attached."""
        if self._iso_collectives is None:
            raise ValueError(
                "no isolated data plane: construct the Manager with "
                "iso_collectives=IsolatedXLACollectives(...)"
            )
        if op not in (ReduceOp.AVG, ReduceOp.SUM):
            raise ValueError(f"unsupported managed iso_allreduce op: {op}")

        def dispatch(zeroed_tree: Any) -> Work:
            if not self._iso_ok:
                raise RuntimeError(
                    "isolated data plane unusable this quorum (its "
                    "configure failed; primary plane unaffected)"
                )
            if op == ReduceOp.AVG:
                num_participants = self.num_participants()
                assert num_participants >= 1
                divisor: Optional[float] = float(num_participants)
            else:
                divisor = None
            return self._iso_collectives.allreduce(
                zeroed_tree, ReduceOp.SUM, divisor=divisor, wire=wire
            )

        return self._managed_dispatch(
            "iso_allreduce", tree, dispatch, lambda t: None
        )

    def reset_plan_feedback(self) -> None:
        """Zeroes the error-feedback carry of every cached ``q8ef`` comm
        plan — native and device-resident alike (no-op for backends
        without plans): the heal/abort discipline — a recovered or
        rolled-back member must not carry a residual from its abandoned
        trajectory."""
        self._collectives.plan_reset_feedback()

    def reduce_scatter(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.AVG,
        wire: Optional[str] = None,
    ) -> Work:
        """Fault-tolerantly reduces a pytree but stops at the
        reduce-scatter boundary: the Work resolves to this rank's
        :class:`~torchft_tpu.collectives.TreeShard` of the averaged
        flat-packed tree (the sharded-weight-update schedule — update the
        shard, then :meth:`allgather_into` the result). Same error
        contract as :meth:`allreduce` except the failure default is
        ``None`` — there is no meaningful "as contributed" shard, so a
        mid-sync failure resolves to ``None``, the error latches, and
        ``should_commit`` discards the step; callers must treat a ``None``
        shard as an aborted sync, never as data. ``op`` must be AVG or
        SUM; ``wire="q8"`` reduces over the quantized ring (the returned
        shard is full f32 — the fused op's lossy allgather phase never
        runs)."""
        if op not in (ReduceOp.AVG, ReduceOp.SUM):
            # Raise eagerly: a static usage error must not be swallowed by
            # the managed error discipline and masquerade as a cohort
            # data-plane failure.
            raise ValueError(f"unsupported managed reduce_scatter op: {op}")

        def dispatch(zeroed_tree: Any) -> Work:
            if op == ReduceOp.AVG:
                num_participants = self.num_participants()
                assert num_participants >= 1
                divisor: Optional[float] = float(num_participants)
            else:
                divisor = None
            return self._collectives.reduce_scatter(
                zeroed_tree, ReduceOp.SUM, divisor=divisor, wire=wire
            )

        return self._managed_dispatch(
            "reduce_scatter", tree, dispatch, lambda t: None
        )

    def allgather_into(self, shard: Any, wire: Optional[str] = None) -> Work:
        """Fault-tolerantly gathers every member's (updated) TreeShard
        back into the full pytree — the parameter-allgather leg of the
        sharded outer sync (``wire="bf16"`` halves its bytes). Failure
        default is ``None`` (same contract as :meth:`reduce_scatter`).
        Unlike the reduction ops, a non-participating (healing/spare)
        member's shard is NOT zeroed: the gathered tree is replicated
        state every ring member owns a slice of, not a contribution sum —
        zeroing a spare's slice would corrupt every member's result."""
        return self._managed_dispatch(
            "allgather_into",
            shard,
            lambda s: self._collectives.allgather_into(s, wire=wire),
            lambda s: None,
            zero_nonparticipating=False,
        )

    def plan_reduce_scatter(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.AVG,
        wire: Optional[str] = None,
        ag_wire: Optional[str] = None,
    ) -> Work:
        """Fault-tolerantly reduces a gradient pytree through the PLAN
        path but stops at the reduce-scatter boundary: one GIL-released
        native call over a precompiled sharded schedule, resolving to
        this rank's :class:`~torchft_tpu.collectives.TreeShard` of the
        averaged flat tree (``shard.plan`` set — route the updated shard
        back through :meth:`plan_allgather_into`). The per-step ZeRO
        grad leg. ``wire``: None | "bf16" | "q8" (the returned shard is
        full f32 on every wire — the owner's chunk never rides a lossy
        hop, the PR-2 discipline; no "q8ef": error feedback corrects a
        FUSED lossy result, and the shard isn't one). ``ag_wire``
        (None | "bf16") pre-declares the param leg's wire — it is baked
        into the plan schedule and checked cohort-wide in the op header.
        Failure default ``None`` (plan buffers may hold a partial
        result), the error latches, ``should_commit`` discards — same
        contract as :meth:`plan_allreduce`. A cohort whose backend or
        leaves can't take the sharded plan (non-f32 leaves, no plan
        support) latches the dispatch error — the sentinel discipline
        AdaptiveDDP's ``ddp_sharded`` candidate relies on, never a
        crash."""
        if op not in (ReduceOp.AVG, ReduceOp.SUM):
            # Static usage error: raise eagerly, don't latch.
            raise ValueError(
                f"unsupported managed plan_reduce_scatter op: {op}"
            )

        def dispatch(zeroed_tree: Any) -> Work:
            if op == ReduceOp.AVG:
                num_participants = self.num_participants()
                assert num_participants >= 1
                divisor: Optional[float] = float(num_participants)
            else:
                divisor = None
            return self._collectives.plan_reduce_scatter(
                zeroed_tree, ReduceOp.SUM, divisor=divisor, wire=wire,
                ag_wire=ag_wire,
            )

        return self._managed_dispatch(
            "plan_reduce_scatter", tree, dispatch, lambda t: None
        )

    def plan_allgather_into(
        self, shard: Any, wire: Optional[str] = None
    ) -> Work:
        """Fault-tolerantly gathers the cohort's (updated) plan shards
        back into the full pytree — the param leg of the per-step ZeRO
        schedule, one native call over the same precompiled plan that
        produced the shard. ``wire`` must equal the ``ag_wire`` declared
        at :meth:`plan_reduce_scatter` (``"bf16"`` halves the leg's
        bytes; every member — owner included — adopts the identically
        decoded words, so gathered params stay bit-identical across the
        cohort). Failure default ``None``; like :meth:`allgather_into`,
        a non-participating member's shard is NOT zeroed — the gather is
        replicated state, not a contribution sum."""
        return self._managed_dispatch(
            "plan_allgather_into",
            shard,
            lambda s: self._collectives.plan_allgather_into(s, wire=wire),
            lambda s: None,
            zero_nonparticipating=False,
        )

    def allgather(self, tree: Any) -> Work:
        """Fault-tolerantly gathers ``tree`` from every cohort member.

        Same error contract as :meth:`allreduce` (data-plane errors latch
        and the Work resolves to ``[tree]``; quorum failure raises), and
        the same participation discipline: a non-participating
        (healing/spare) replica's entry is ZEROED before the gather, so
        consumers averaging entry-wise must divide by
        ``num_participants()``, not the cohort size. Every ring member's
        entry appears, ordered by replica rank. Intended for
        LocalSGD-family window syncs (quantized payloads average
        member-wise after dequantization — a SUM over the wire dtype
        would overflow). No reference analog at the Manager level (the
        reference exposes allgather only on the raw PG, reference
        process_group.py:130-137).
        """
        return self._managed_dispatch(
            "allgather", tree, self._collectives.allgather, lambda t: [t]
        )

    def _managed_dispatch(
        self,
        op_name: str,
        tree: Any,
        dispatch: Callable[[Any], Work],
        default_factory: Callable[[Any], Any],
        zero_nonparticipating: bool = True,
    ) -> Work:
        """The shared managed-collective discipline: errored short-circuit,
        quorum join, participant zeroing, profiler span + metrics timer,
        timeout + error-latching wrap; failures AFTER the quorum join
        latch and resolve to ``default_factory`` applied to the tree AS
        DISPATCHED — for a non-participating (healing/spare) replica that
        is the zeroed tree, preserving the zero-contribution discipline on
        that fallback (reference manager.py:242-303, 326-363). The
        PRE-quorum short-circuit (an error already latched when the op is
        issued) returns the INPUT tree unzeroed: participation isn't
        knowable without the quorum, and the step is unconditionally
        discarded by ``should_commit`` — consumers must not treat that
        early fallback as a zero contribution."""
        if self.errored() is not None:
            return _completed(default_factory(tree))
        self.wait_quorum()
        try:
            import jax

            if zero_nonparticipating and not self.is_participating():
                tree = jax.tree_util.tree_map(
                    lambda l: l * 0 if hasattr(l, "__mul__") else l, tree
                )
            t0 = time.perf_counter()
            with span(f"torchft::{op_name}_dispatch"):
                work = dispatch(tree)
            work.add_done_callback(
                lambda _f: self._metrics.record(
                    op_name, time.perf_counter() - t0
                )
            )
            return self.wrap_work(work, default=default_factory(tree))
        except Exception as e:  # noqa: BLE001 - latch, never raise
            self._logger.exception(f"{op_name} failed immediately: {e}")
            self.report_error(e)
            return _completed(default_factory(tree))

    def wrap_work(self, work: Work, default: Any, timeout: Optional[timedelta] = None) -> Work:
        """Adds a timeout and error-swallowing to a Work: on failure the
        error is latched and ``default`` is returned (reference
        manager.py:326-363)."""
        timed = work_timeout(work, timeout or self._timeout)
        epoch = self._op_epoch

        def swallow() -> Work:
            from concurrent.futures import Future

            out: "Future[Any]" = Future()

            def on_done(f: "Future[Any]") -> None:
                exc = f.exception()
                if exc is not None:
                    self._logger.exception(f"async work failed: {exc}")
                    with self._error_lock:
                        if epoch == self._op_epoch:
                            # Works abandoned by a fail-fast should_commit
                            # may settle during a LATER step; their errors
                            # belong to the (already aborted) step that
                            # issued them and must not latch into the
                            # current one.
                            self._errored = cast(Exception, exc)
                            self._force_reconfigure = True
                    out.set_result(default)
                else:
                    out.set_result(f.result())

            timed._future.add_done_callback(on_done)
            return Work(out)

        wrapped = swallow()
        self._pending_work.append(wrapped)
        return wrapped

    # -- error tracking --

    def report_error(self, e: Exception) -> None:
        """Latch an error: the current step will not commit and collectives
        are no-ops until the next quorum (reference manager.py:305-317).

        Any error also requests a data-plane rebuild through the next quorum
        (``force_reconfigure``): a failed ring op shuts the ring down
        (native fail-fast propagation), and if membership happens to be
        unchanged the quorum_id would otherwise not bump — leaving every
        member with dead sockets. Spurious rebuilds cost one rendezvous."""
        with self._error_lock:
            self._errored = e
            self._force_reconfigure = True

    def errored(self) -> Optional[Exception]:
        return self._errored

    # -- commit protocol --

    def should_commit(
        self,
        timeout: Optional[timedelta] = None,
        count_batches: bool = True,
    ) -> bool:
        """Distributed AND-vote on step validity. Reference manager.py:545-598.

        Returns True iff every rank of every participating replica group
        completed the step without errors and quorum size >= min_replica_size.
        ``count_batches=False`` marks a CONTROL transaction (e.g. the policy
        engine's decision step): the committed step counter still advances
        (transaction ordering and heal max_step depend on it) but
        ``batches_committed`` does not — no batch was trained.
        """
        # Settle the quorum thread before reading _healing/_errored: it is
        # their writer, and an early-errored step may reach here without any
        # allreduce having waited on it. (A failed quorum raises, as it
        # would from num_participants below.)
        self.wait_quorum()

        for work in self._pending_work:
            if self._errored is not None:
                break
            work.wait()  # error-swallowing: never raises, latches instead
        self._pending_work = []

        # Apply the fetched checkpoint whenever healing — even if an error
        # latched this step. The manager step was already advanced to
        # max_step by the quorum thread, so skipping the apply would leave
        # this replica reporting max_step on stale weights and never healed
        # again (reference manager.py:575-577 applies unconditionally).
        if self._healing:
            self._apply_pending_state_dict()

        local_should_commit = (
            self._errored is None
            and self.num_participants() >= self._min_replica_size
        )
        with self._metrics.timed("commit_vote"), span("torchft::commit_vote"):
            should_commit = self._client.should_commit(
                self._rank,
                self._step,
                local_should_commit,
                timeout=timeout or self._timeout,
            )
        self._logger.info(
            f"should_commit={should_commit} enough_replicas="
            f"{self.num_participants() >= self._min_replica_size}, "
            f"errored={self._errored}"
        )

        # The checkpoint dict must not be readable while the optimizer
        # mutates it (reference manager.py:591).
        self._checkpoint_transport.disallow_checkpoint()

        if should_commit:
            self._step += 1
            if count_batches:
                self._batches_committed += self.num_participants()
        self._metrics.incr("commits" if should_commit else "aborts")
        if self._errored is not None:
            self._metrics.incr("errors")
        self._healing = False
        # Commit boundary: the quorum thread is settled (wait_quorum above)
        # and the vote is final, so (step, quorum_id) here names exactly
        # one committed fleet state — the only point where a durable
        # snapshot may capture. Hooks are observers: a failing snapshot
        # must never abort training, so exceptions are logged and dropped.
        for hook in self._commit_hooks:
            try:
                hook(self._step, self._quorum_id, should_commit)
            except Exception as e:  # noqa: BLE001
                self._logger.warn(f"commit hook failed: {e}")
        return should_commit

    # -- state --

    def load_state_dict(self, state_dict: Dict[str, int]) -> None:
        """Restores manager state (call when resuming from a durable
        checkpoint, alongside the user state). Reference manager.py:600-613."""
        self._step = state_dict["step"]
        self._batches_committed = state_dict["batches_committed"]

    def _manager_state_dict(self) -> Dict[str, object]:
        assert self._user_state_dict is not None, "no state_dict callback"
        return {
            "user": self._user_state_dict(),
            "torchft": self.state_dict(),
        }

    def state_dict(self) -> Dict[str, int]:
        """Manager state to persist alongside user checkpoints.
        Reference manager.py:615-629."""
        return {"step": self._step, "batches_committed": self._batches_committed}

    # -- observability / policy signals --

    def observe_op_stats(self) -> List[dict]:
        """Drains the data plane's per-op phase timings (``pop_op_stats``)
        THROUGH the manager, folding ring entries into the rolling
        effective-bandwidth estimate ``signals()`` reports: per op,
        ``wire_bytes / ring_s`` is the achieved wire throughput (the number
        the policy cost model divides by) and its per-connection share
        (divided by the op's stripe count) is what operators compare
        against ``TORCHFT_HC_WIRE_CAP_MBPS``. Returns the drained entries,
        so a caller that wants the raw breakdown (benches, diagnosis
        tooling) consumes the SAME drain — pop semantics are preserved,
        just routed. A backend without op stats yields ``[]``."""
        pop = getattr(self._collectives, "pop_op_stats", None)
        entries: List[dict] = pop() if pop is not None else []
        for st in entries:
            # Hierarchical entries additionally fold PER-TIER effective
            # throughput (measured tier bytes over that tier's phase
            # wall): the policy engine prices hier/shm candidates on the
            # bottleneck tier, not this op's folded average. Shm host
            # tiers bill ring movement (tx_bytes is honestly 0 there).
            tiers = st.get("tiers")
            if tiers:
                for name, t in tiers.items():
                    if name == "inter":
                        phase_s = t.get("ring_s") or 0.0
                    else:
                        phase_s = (
                            (t.get("rs_s") or 0.0) + (t.get("ag_s") or 0.0)
                            + (t.get("bcast_s") or 0.0)
                        )
                    moved = t.get("tx_bytes") or t.get("shm_bytes") or 0
                    if phase_s > 0 and moved > 0:
                        tier_eff = moved / phase_s / (1 << 20)
                        self._last_tier_mbps[name] = tier_eff
                        self._metrics.record(f"tier_{name}_MBps", tier_eff)
            ring_s = st.get("ring")
            wire_bytes = st.get("wire_bytes") or st.get("bytes")
            if not ring_s or not wire_bytes or ring_s <= 0:
                continue
            eff = wire_bytes / ring_s / (1 << 20)
            stripes = len(st.get("stripe_s") or ()) or 1
            self._metrics.record("wire_eff_MBps", eff)
            self._metrics.record("wire_conn_MBps", eff / stripes)
            self._last_wire_eff_mbps = eff
        return entries

    def signals(self, churn_window_s: float = 600.0) -> Dict[str, Any]:
        """The policy engine's input signals as one JSON-able dict:

        - ``churn_per_min``: rolling rate of data-plane reconfigures
          (quorum-id bumps after the first — kills, joins, heals, forced
          rebuilds) over the trailing ``churn_window_s``.
        - ``wire_eff_MBps``: last measured effective wire throughput of a
          ring op (``None`` until :meth:`observe_op_stats` has seen one).
        - ``tier_eff_MBps``: per-tier effective throughput of the last
          hierarchical op ({"host"/"intra"/"inter": MB/s}; ``None`` until
          one has been observed) — what prices hier/shm strategy
          candidates on their bottleneck tier.
        - ``heal``: the last streamed-heal cost breakdown (the transport's
          ``last_fetch_stats``: path/wire/bytes/fetch_s/h2d_s), plus the
          ``heal_fetch``/``heal_apply`` timer snapshots — ``None`` when
          this replica never healed.
        - ``opt_state_bytes``: resident optimizer-state bytes as last
          reported by the training strategy via
          :meth:`report_opt_state_bytes` (ShardedDDP reports its ~1/W
          shard each reshard; ``None`` until a strategy reports) — the
          policy engine's memory term for pricing ``ddp_sharded``.

        Also the payload pushed to the lighthouse ``status.json`` member
        view (see :meth:`push_status`)."""
        heal: Optional[Dict[str, Any]] = None
        fetch_stats = getattr(
            self._checkpoint_transport, "last_fetch_stats", None
        )
        timers = self._metrics.snapshot()["timers_s"]
        if fetch_stats is not None or "heal_fetch" in timers:
            heal = {
                "last_fetch": fetch_stats,
                "fetch_s": timers.get("heal_fetch"),
                "apply_s": timers.get("heal_apply"),
            }
        return {
            "churn_per_min": round(
                self._metrics.rate_per_min("churn", churn_window_s), 6
            ),
            "wire_eff_MBps": self._last_wire_eff_mbps,
            "tier_eff_MBps": dict(self._last_tier_mbps) or None,
            "heal": heal,
            "opt_state_bytes": getattr(self, "_opt_state_bytes", None),
        }

    def report_opt_state_bytes(self, nbytes: Optional[int]) -> None:
        """Records the strategy's resident optimizer-state footprint for
        :meth:`signals`. ShardedDDP calls this on every (re)shard with
        its ~1/W shard's bytes; an unsharded strategy may report its
        full state. ``None`` clears the signal."""
        self._opt_state_bytes = None if nbytes is None else int(nbytes)

    def push_status(self, extra: Optional[Dict[str, Any]] = None) -> None:
        """Publishes the current :meth:`signals` digest (plus step/commit
        progress and any ``extra`` — e.g. the policy engine's active
        strategy) to the lighthouse: it rides the native manager's lease
        renewals and appears under this member in ``/status.json``. No-op
        on ranks that don't host the native manager (group rank != 0) —
        the group's digest is rank 0's."""
        if self._manager is None:
            return
        counters = self._metrics.snapshot()["counters"]
        status: Dict[str, Any] = {
            "step": self._step,
            "commits": counters.get("commits", 0),
            "aborts": counters.get("aborts", 0),
            "heals": counters.get("heals", 0),
            **self.signals(),
        }
        if extra:
            status.update(extra)
        try:
            self._manager.set_status(status)
        except Exception as e:  # noqa: BLE001 - observability must not kill
            self._logger.warn(f"status push failed (ignored): {e}")

    # -- introspection --

    def checkpoint_transport(self) -> CheckpointTransport[Dict[str, T]]:
        """The live-recovery transport this manager heals through.
        Benches and diagnostics read its ``last_fetch_stats`` (streamed
        heal path/wire/fetch/h2d breakdown) after a heal; swapping the
        transport itself happens at construction."""
        return self._checkpoint_transport

    def metrics(self) -> "Metrics":
        """Step-level counters and timers (commits/aborts/heals/errors,
        quorum / reconfigure / allreduce / commit-vote latencies). Closes
        the observability gap the reference leaves at batches_committed
        (reference manager.py:642-653); ``metrics().snapshot()`` is
        JSON-able."""
        return self._metrics

    def current_step(self) -> int:
        """Committed step count; skipped steps don't increment it."""
        return self._step

    def replica_id(self) -> str:
        """This group's replica id (stable across restarts when the
        launcher pins it — what the durable tier keys per-member local
        state, e.g. the dataloader position, on)."""
        return self._replica_id

    def add_commit_hook(self, hook: Any) -> None:
        """Registers ``hook(step, quorum_id, committed)`` to fire at every
        ``should_commit`` resolution, after the vote settled (and after
        the step counter advanced on a commit). This is the durable
        tier's capture point: the hook runs on the trainer thread with
        the state dict quiescent — the optimizer has not yet mutated the
        next step — so a snapshot captured here is provably step-pure.
        Hooks must not raise; exceptions are swallowed and logged (a
        failing snapshot never aborts training)."""
        self._commit_hooks.append(hook)

    def set_durable_restore(
        self, fn: Optional[Callable[[], Optional[int]]]
    ) -> None:
        """Registers (or clears) the durable tier's cold-start fallback —
        see the ``durable_restore`` constructor arg.
        ``DurableCheckpointer.__init__`` calls this so the arbitration
        is wired by merely constructing the checkpointer; a trainer that
        still calls ``restore_latest()`` itself before the first quorum
        is unaffected (a nonzero restored step disarms the consult)."""
        self._durable_restore = fn

    def batches_committed(self) -> int:
        """Total batches committed across all replicas and steps."""
        return self._batches_committed

    def num_participants(self) -> int:
        """Replica groups participating in the current step."""
        assert self._quorum_future is not None, "quorum not started"
        self.wait_quorum()
        return self._participating_world_size

    def quorum_id(self) -> int:
        """Id of the current quorum (bumps exactly when membership — and
        therefore the data plane — was reconfigured). Sharded consumers
        key partition-dependent state on it: the DiLoCo sharded outer
        sync re-shards its outer-optimizer state whenever the id moved
        since the state was built (a join/leave/heal changed the ring, so
        the old shard boundaries no longer tile the cohort). Settles the
        quorum thread first — it is the writer."""
        assert self._quorum_future is not None, "quorum not started"
        self.wait_quorum()
        return self._quorum_id

    def participating_rank(self) -> Optional[int]:
        """This group's rank among participants; None when healing/spare."""
        assert self._quorum_future is not None, "quorum not started"
        self.wait_quorum()
        return self._participating_rank

    def is_participating(self) -> bool:
        """False while healing or a spare: gradients are zeroed then
        (reference manager.py:693-705)."""
        if self._participating_rank is None:
            return False
        if self._healing:
            assert self._use_async_quorum
            return False
        return True

    def is_healing(self) -> bool:
        """True while this step is recovering state from a peer (the fetched
        checkpoint is applied at the ``should_commit`` safe point). Pipelined
        wrappers read this BEFORE voting to know that gradients dispatched
        earlier in the step were computed from pre-heal weights and must be
        recomputed (torchft_tpu.ddp.PipelinedDDP). Settles the quorum thread
        first — it is the writer."""
        assert self._quorum_future is not None, "quorum not started"
        self.wait_quorum()
        return self._healing


class _ManagerLogger:
    """Prefixes logs with [replica/rank - step N]. Reference manager.py:708-727."""

    def __init__(self, manager: Manager, replica_id: str, rank: int) -> None:
        self._logger = logging.getLogger(f"{__name__}.{replica_id}")
        self._replica_id = replica_id
        self._rank = rank
        self._manager = manager

    def prefix(self) -> str:
        return (
            f"[{self._replica_id}/{self._rank} - step "
            f"{self._manager.current_step()}]"
        )

    def info(self, msg: str) -> None:
        self._logger.info(f"{self.prefix()} {msg}")

    def warn(self, msg: str) -> None:
        self._logger.warning(f"{self.prefix()} {msg}")

    def exception(self, msg: str) -> None:
        self._logger.exception(f"{self.prefix()} {msg}")
