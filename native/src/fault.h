// Deterministic chaos plane: seeded fault injection at native wire seams,
// plus the CRC32C the guarded frame format rides on.
//
// DESIGN. Every training step is a transaction (error anywhere -> latch ->
// vote discards -> heal); the chaos plane exists to *exercise* that
// invariant from one seeded schedule instead of hand-written SIGKILLs. A
// fault plan is armed process-wide (tft_fault_arm, JSON rules); each
// injection point asks, per (seam, member, op_index), whether a fault
// fires — the decision is a pure splitmix64 hash of (seed, seam, member,
// op_index, rule), so the same (seed, plan) replays the same schedule.
//
// HOT-PATH CONTRACT. Disarmed (the production state), an injection point
// costs exactly ONE relaxed atomic load and a predictable branch — no
// call, no lock, no hash. That is what the TFT_FAULT_CHECK macro compiles
// to when g_armed is 0. graftlint's `fault_guard` rule enforces that no
// call site reaches tft_fault_maybe() except through the macro, so the
// contract cannot silently erode as seams are added.
//
// ADDING A SEAM (see docs/DEVELOPING.md "adding an injectable seam"):
//   1. add a Seam enum value here and its name to seam_from_name in
//      fault.cc;
//   2. at the call site:
//        fault::Decision fd = TFT_FAULT_CHECK(fault::kSeamX, member, op);
//        if (fd.kind != fault::kNone) { ...seam-specific behavior... }
//      (the BEHAVIOR lives at the seam: only the seam knows how to drop,
//      delay or corrupt its own traffic);
//   3. cover it from a FaultPlan in tests/test_chaos_invariants.py.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>

namespace tft {
namespace fault {

// Injection seams. Values are wire-stable (they appear in plan JSON and
// stats); append only.
enum Seam : int {
  kSeamRingSend = 0,  // collectives.cc duplex() PAYLOAD frames
  kSeamNetSend = 1,   // net.cc Socket::send_all (control-plane frames)
  kSeamStore = 2,     // reserved: store client ops (Python-side injector)
  kSeamHeal = 3,      // reserved: heal HTTP (Python-side injector)
  kSeamChild = 4,     // reserved: isolated-child lifecycle (Python-side)
  kSeamShm = 5,       // reserved: shm attach (Python-side injector)
  kSeamRingHdr = 6,   // collectives.cc duplex() per-op HEADER frames —
                      // split from kSeamRingSend so a "mid-ring payload
                      // corruption" plan cannot be satisfied by hitting
                      // the 24-byte header (whose magic check would
                      // catch it even without CRC)
  kSeamShmRing = 7,   // collectives.cc shm_duplex() PAYLOAD frames (the
                      // host tier's shared-memory rings): drop = every
                      // publish of the op silently vanishes (asymmetric
                      // partition; the consumer stalls to its op
                      // deadline); bit_flip = a stale frame sequence
                      // ships (replayed payload, detected); truncate =
                      // a torn segment (half a frame, ring magic
                      // poisoned)
  kSeamWalWrite = 8,  // wal.cc DurableLog append (the root's write-ahead
                      // quorum log): truncate = crash mid-append (half a
                      // record on disk — the torn tail recovery must
                      // detect and drop), drop = crash before any byte
                      // lands, delay = slow disk. Both crash kinds kill
                      // the log (the process would be dead too), so the
                      // service stops making new promises.
};

// Fault kinds a native seam can realize. Python-side seams reuse the
// same names (chaos.py) so one plan schema spans both layers.
enum Kind : int {
  kNone = 0,
  kDrop = 1,       // abandon the op: shut the seam down, error out
  kDelay = 2,      // stall the send `param` ms (bounded by op deadline)
  kTruncate = 3,   // ship a partial frame, then die (torn write)
  kDuplicate = 4,  // repeat a prefix of the frame (stream desync)
  kBitFlip = 5,    // flip one bit of the frame ON THE WIRE (payload
                   // untouched at the sender — the CRC contract's prey)
  kPartition = 6,  // asymmetric partition: sends silently vanish while
                   // receives keep flowing (A->B dead, B->A alive)
};

// One firing: what to do and the hash that parameterizes it (bit
// position, prefix length). kind == kNone means "no fault here".
struct Decision {
  int kind = kNone;
  int64_t param = 0;  // rule's param (delay ms, ...)
  uint64_t h = 0;     // decision hash: deterministic per-firing entropy
};

// Armed flag. Relaxed is sufficient: arming happens-before the ops a
// harness injects into via its own synchronization (the plan is armed
// before the step starts), and a stale 0 read merely skips a fault.
extern std::atomic<uint32_t> g_armed;
inline bool armed() { return g_armed.load(std::memory_order_relaxed) != 0; }

// C++ surfaces behind the capi wrappers (capi.cc guards + JSON-ifies).
void arm_from_json(const std::string& plan_json);  // throws on bad JSON
void disarm();
std::string stats_json();

// splitmix64 — the shared deterministic mixer (same constants as
// net.cc's jitter; duplicated into chaos.py so Python plans hash
// identically).
uint64_t mix64(uint64_t x);

// Incremental CRC32C (Castagnoli), slicing-by-8. State starts at
// 0xFFFFFFFF; finalize by inverting. crc32c() does the full
// init-update-finalize for one buffer.
uint32_t crc32c_update(uint32_t state, const void* data, size_t len);
uint32_t crc32c(const void* data, size_t len);

}  // namespace fault
}  // namespace tft

extern "C" {
// The slow-path decision. NEVER call directly — every injection point
// must go through TFT_FAULT_CHECK so the disarmed cost stays one relaxed
// load (graftlint `fault_guard` greps for violations). `op_index` < 0
// uses an internal per-seam call counter (control-plane seams with no
// natural op ordering).
tft::fault::Decision tft_fault_maybe(int seam, int64_t member,
                                     int64_t op_index);
}  // extern "C"

// The disarmed fast path: one relaxed atomic load, one branch, nothing
// else. All native injection points MUST use this macro.
#define TFT_FAULT_CHECK(seam, member, op_index)                         \
  (tft::fault::armed() ? tft_fault_maybe((seam), (member), (op_index)) \
                       : tft::fault::Decision{})
