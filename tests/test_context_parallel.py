"""Ring attention (context parallelism) tests on the virtual 8-device CPU
mesh: numerical equivalence with dense causal attention, differentiability,
and composition with data- and tensor-parallel axes in one mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu.context_parallel import ring_attention
from torchft_tpu.parallel import make_mesh


def _dense_causal(q, k, v):
    """Reference: full-materialization causal attention, f32."""
    Dh = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (Dh ** -0.5)
    S = q.shape[1]
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _qkv(key, B=2, S=32, H=4, Dh=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (B, S, H, Dh)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


class TestRingAttention:
    def test_matches_dense_seq_only(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(0))
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="seq",
                             batch_axis=None)
        ref = _dense_causal(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_matches_dense_dp_x_seq_x_tp(self):
        # The composition claim: batch over "data", sequence ring over
        # "seq", heads over "model" — one mesh, one op.
        mesh = make_mesh({"data": 2, "seq": 2, "model": 2},
                         devices=jax.devices()[:8])
        q, k, v = _qkv(jax.random.PRNGKey(1), B=4, S=16, H=4)
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="seq",
                             batch_axis="data", head_axis="model")
        ref = _dense_causal(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_non_causal(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(2))
        out = ring_attention(q, k, v, mesh=mesh, batch_axis=None,
                             causal=False)
        Dh = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (Dh ** -0.5)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_flow_through_ring(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(3))

        def loss_ring(qkv):
            out = ring_attention(*qkv, mesh=mesh, batch_axis=None)
            return jnp.sum(out ** 2)

        def loss_dense(qkv):
            return jnp.sum(_dense_causal(*qkv) ** 2)

        g_ring = jax.grad(loss_ring)((q, k, v))
        g_dense = jax.grad(loss_dense)((q, k, v))
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       rtol=2e-4, atol=2e-4)

    def test_inside_jit(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(4))
        f = jax.jit(lambda a, b, c: ring_attention(
            a, b, c, mesh=mesh, batch_axis=None))
        np.testing.assert_allclose(np.asarray(f(q, k, v)),
                                   np.asarray(_dense_causal(q, k, v)),
                                   rtol=2e-5, atol=2e-5)

    def test_uneven_sequence_rejected(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(5), S=30)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh=mesh, batch_axis=None)

    def test_bf16_inputs(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(6), dtype=jnp.bfloat16)
        out = ring_attention(q, k, v, mesh=mesh, batch_axis=None)
        assert out.dtype == jnp.bfloat16
        ref = _dense_causal(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=0.05, atol=0.05,
        )
