"""graftlint's own tests: every rule must fire on its seeded fixture and
stay silent on the clean control — and the real repo must be clean.

The fixtures under tests/graftlint_fixtures/ carry one deliberate
violation per failure mode (C-API three-way drift, latch-discipline
breach, undocumented env knob, deadline-less sleep loop, out-of-entry
plan-cache mutation, chaos seam-registry drift, proto/pb_fallback wire
drift). If a rule's detector regresses, the seeded fixture stops firing
and these tests — not a 2am bridge corruption — catch it.
"""

import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "graftlint_fixtures"
sys.path.insert(0, str(REPO_ROOT))

from tools import graftlint  # noqa: E402
from tools.graftlint import (  # noqa: E402
    cache_mutation,
    capi_sync,
    env_docs,
    fault_guard,
    latch_discipline,
    proto_sync,
    sleep_deadline,
)


def messages(violations):
    return "\n".join(str(v) for v in violations)


class TestCapiSync:
    def fixture_violations(self):
        return capi_sync.check(
            REPO_ROOT,
            capi_path=FIXTURES / "bad_capi.cc",
            native_py_path=FIXTURES / "bad_native.py",
            pyi_path=FIXTURES / "bad_native.pyi",
        )

    def test_detects_each_drift_flavor(self):
        found = messages(self.fixture_violations())
        assert "tft_fix_argcount argtypes length 2 != 3" in found
        assert "tft_fix_ret64 returns 'int64_t' but declares no restype" in found
        assert "tft_fix_undeclared exported by capi.cc but has no ctypes" in found
        assert "tft_fix_stale declared in _native.py but not exported" in found
        assert "tft_fix_unstubbed exported by capi.cc but missing" in found
        assert "tft_fix_phantom stubbed in _NativeLib but not exported" in found
        # pyi side of the argcount drift too.
        assert "tft_fix_argcount stub takes 1 parameters but capi.cc takes 3" in found
        # tft_shm_* symbols ride the same three-file rule: a handle-
        # returning shm export with no restype hands Python a truncated
        # pointer (the isolated-data-plane surface is checked, not
        # grandfathered).
        assert (
            "tft_shm_fix_noresty returns 'void *' but declares no restype "
            "(ctypes defaults to c_int: truncated int64 / mangled pointer)"
            in found
        )

    def test_control_function_not_flagged(self):
        assert not any(
            "tft_fix_ok" in v.message for v in self.fixture_violations()
        )

    def test_real_bridge_is_clean(self):
        assert capi_sync.check(REPO_ROOT) == []

    def test_real_bridge_parses_nontrivially(self):
        # Guards against a parser regression silently passing vacuously.
        exports = capi_sync.parse_capi(
            (REPO_ROOT / "native/src/capi.cc").read_text()
        )
        assert len(exports) >= 40
        names = {e.name for e in exports}
        assert {"tft_hc_configure", "tft_plan_execute", "tft_last_error"} <= names
        # the shared-memory lifecycle surface is part of the checked bridge
        assert {"tft_shm_create", "tft_shm_attach", "tft_shm_layout_json"} <= names


class TestLatchDiscipline:
    def test_detects_breaches(self):
        found = messages(
            latch_discipline.check(
                REPO_ROOT, manager_path=FIXTURES / "bad_manager.py"
            )
        )
        assert "Manager.allreduce touches a managed collective" in found
        assert "raises a non-ValueError on the managed path" in found
        assert "bare re-raise on the managed path" in found
        assert "_managed_dispatch exception handler re-raises" in found
        # the isolated data plane carries the same discipline
        assert "Manager.iso_allreduce touches a managed collective" in found
        # the plan-path ops added since PR 4 are managed surface too
        assert (
            "Manager.plan_reduce_scatter raises a non-ValueError" in found
        )

    def test_clean_fixture_passes(self):
        assert (
            latch_discipline.check(
                REPO_ROOT, manager_path=FIXTURES / "good_manager.py"
            )
            == []
        )

    def test_real_manager_is_clean(self):
        assert latch_discipline.check(REPO_ROOT) == []


class TestEnvDocs:
    def test_detects_undocumented_knob(self):
        violations = env_docs.check(
            REPO_ROOT,
            docs_path=FIXTURES / "envcase" / "OPERATIONS.md",
            scan_dirs=[Path("tests/graftlint_fixtures/envcase")],
        )
        found = messages(violations)
        assert "TORCHFT_FIXTURE_UNDOCUMENTED" in found
        assert "TORCHFT_FIXTURE_DOCUMENTED" not in found
        # the typed-helper read form (_env_int("TORCHFT_X", d)) counts
        assert "TORCHFT_FIXTURE_HELPER" in found
        # ...and the _ENV_* module-constant indirection
        assert "TORCHFT_FIXTURE_INDIRECT" in found
        # a constant that is defined but never passed to a read is not
        # a read
        assert "TORCHFT_FIXTURE_NEVER_READ" not in found

    def test_real_knobs_are_documented(self):
        assert env_docs.check(REPO_ROOT) == []

    def test_real_scan_sees_known_knobs(self):
        reads = env_docs.collect_reads(REPO_ROOT, env_docs.SCAN_DIRS)
        # Python- and C++-side reads must both be visible.
        assert "TORCHFT_LIGHTHOUSE" in reads
        assert "TORCHFT_HC_WIRE_CAP_MBPS" in reads


class TestFaultGuard:
    def fixture_violations(self):
        return fault_guard.check(
            REPO_ROOT,
            scan_dir=Path("tests/graftlint_fixtures"),
            chaos_path=FIXTURES / "bad_chaos.py",
            fault_h_path=FIXTURES / "bad_fault.h",
        )

    def test_detects_raw_call_and_passes_macro_form(self):
        raw = [
            v
            for v in self.fixture_violations()
            if "raw tft_fault_maybe" in v.message
        ]
        # exactly the one raw call fires — the TFT_FAULT_CHECK form in
        # the same fixture must not
        assert len(raw) == 1
        assert "bad_fault.cc" in raw[0].file

    def test_detects_seam_registry_drift(self):
        found = messages(self.fixture_violations())
        # a native seam with no enumerator is silently unarmable
        assert "'ghost_seam' (chaos.py NATIVE_SEAMS) has no kSeamGhostSeam" in found
        # an enumerator with no TFT_FAULT_CHECK site tests nothing
        assert "'wal_write' has no TFT_FAULT_CHECK call site" in found
        # ring_send IS reachable (bad_fault.cc's macro form): not flagged
        assert "'ring_send' has no TFT_FAULT_CHECK" not in found
        # an enumerator no seam maps to is dead wiring
        assert "kSeamPhantom maps to no seam" in found
        # reserved Python-side enumerators (kSeamStore) are fine
        assert "kSeamStore" not in found
        # SEAM_KINDS must cover the registry exactly, both ways
        assert "'serving' has no SEAM_KINDS vocabulary" in found
        assert "SEAM_KINDS entry 'orphan_kind' is not a registered seam" in found

    def test_engine_files_are_exempt(self):
        # fault.h declares tft_fault_maybe and defines the macro;
        # fault.cc defines it — neither is a violation.
        assert (REPO_ROOT / "native/src/fault.h").exists()
        names = [v.file for v in fault_guard.check(REPO_ROOT)]
        assert not any("fault.h" in n or "fault.cc" in n for n in names)

    def test_real_native_tree_is_clean(self):
        assert fault_guard.check(REPO_ROOT) == []


class TestProtoSync:
    def fixture_violations(self):
        return proto_sync.check(
            REPO_ROOT,
            proto_path=FIXTURES / "bad_wire.proto",
            header_path=FIXTURES / "bad_wire.pb.h",
        )

    def test_detects_each_drift_flavor(self):
        found = messages(self.fixture_violations())
        # a proto field the header never serializes
        assert (
            "FixMember.missing_in_header (field 3) is not serialized"
            in found
        )
        # same field name, different field number
        assert (
            "FixMember.shifted is field 5 in the header but 4 in the "
            "proto" in found
        )
        # a header field the proto doesn't know
        assert (
            "FixMember.extra_in_header (field 9) serialized by the "
            "header but absent from the proto" in found
        )
        # write-only field: AppendTo emits it, Field() drops it
        assert (
            "AppendTo writes field 9 (extra_in_header) but Field() has "
            "no case" in found
        )
        # whole-message drift, both directions
        assert "message FixOnlyProto has no class" in found
        assert "class FixOnlyHeader has no message" in found

    def test_clean_controls_not_flagged(self):
        found = messages(self.fixture_violations())
        # repeated sub-message via for-loop, single-field "if (f == N)"
        # parser style, and the raw put_tag/put_varint pair all parse
        assert "FixQuorum" not in found
        assert "nonce" not in found

    def test_real_wire_contract_is_clean(self):
        assert proto_sync.check(REPO_ROOT) == []

    def test_real_pair_parses_nontrivially(self):
        # Guards against a parser regression silently passing vacuously.
        msgs = proto_sync.parse_proto(
            (REPO_ROOT / "native/torchft.proto").read_text()
        )
        classes, problems = proto_sync.parse_header(
            (REPO_ROOT / "native/src/pb_fallback/torchft.pb.h").read_text(),
            "torchft.pb.h",
        )
        assert problems == []
        assert len(msgs) >= 30 and len(msgs) == len(classes)
        proto_fields = sum(len(f) for f in msgs.values())
        header_fields = sum(len(c.fields) for c in classes.values())
        assert proto_fields == header_fields >= 80
        # spot-check a deep message parsed on both sides with matching
        # numbers (the ZeRO response carries optional + packed + repeated
        # string fields — the exotic encodings)
        mqr = msgs["ManagerQuorumResponse"]
        cqr = classes["ManagerQuorumResponse"].fields
        assert mqr.keys() == cqr.keys()
        assert all(mqr[k].number == cqr[k].number for k in mqr)


class TestSleepDeadline:
    def test_detects_deadline_less_loop(self):
        violations = sleep_deadline.check(
            REPO_ROOT, test_paths=[FIXTURES / "bad_sleeps.py"]
        )
        assert len(violations) == 1
        assert violations[0].line == 8  # wait_forever's while, nothing else

    def test_real_tests_are_clean(self):
        assert sleep_deadline.check(REPO_ROOT) == []


class TestCacheMutation:
    def test_detects_out_of_entry_mutations(self):
        violations = cache_mutation.check(
            REPO_ROOT,
            targets={
                ("tests/graftlint_fixtures/bad_cache.py", "_plans"): (
                    "__init__",
                    "configure",
                    "_plan_for",
                )
            },
        )
        kinds = {v.message.split(";")[0] for v in violations}
        assert len(violations) == 3
        assert any("sneaky_drop" in v.message for v in violations)
        assert any("sneaky_insert" in v.message for v in violations)
        assert any("sneaky_rebind" in v.message for v in violations)
        assert kinds  # each message names its mutation kind

    def test_real_plan_cache_is_clean(self):
        assert cache_mutation.check(REPO_ROOT) == []


class TestRunner:
    def test_run_all_clean_on_repo(self):
        assert graftlint.run(REPO_ROOT) == []

    def test_unknown_rule_rejected(self):
        with pytest.raises(KeyError):
            graftlint.run(REPO_ROOT, ["no_such_rule"])

    def test_cli_exit_codes(self):
        import subprocess

        proc = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts/graftlint.py")],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "clean" in proc.stdout
