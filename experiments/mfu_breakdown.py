"""TPU experiment: where does the big-config step time go?

Component attribution for the flagship 111M-param LM (d_model 1024, H16,
L8, d_ff 4096, S 2048, B 16, flash attention) — the round-3 verdict's #1
ask is MFU 37% -> >=50%, so before pulling levers we measure:

  matmul_roofline   what a plain big bf16 matmul sustains on THIS chip
                    through THIS tunnel (the real ceiling; v5e paper peak
                    is 197 TFLOP/s bf16)
  step_full         the fused train step (the bench's measured number)
  grad_only         value_and_grad without the optimizer apply
  fwd_only          forward loss only
  step_no_attn      train step with attention replaced by an identity
                    projection (attention cost by subtraction)
  step_mean_loss    train step with cross-entropy replaced by mean(logits)
                    (xent + log_softmax cost by subtraction)
  attn_standalone   the flash kernel fwd+bwd at the in-model shape
                    (B*layers calls folded into one timing)

Run ALONE on the chip (one tunneled v5e; concurrent TPU work wrecks both
timings). Queue-and-drain discipline per the repo's benchmarking notes.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from torchft_tpu.models import TransformerConfig, init_params, loss_fn, make_train_step
from torchft_tpu.models import transformer as T

B, S = 16, 2048
CFG = dict(vocab_size=8192, d_model=1024, n_heads=16, n_layers=8,
           d_ff=4096, max_seq_len=2048)


def drain(x):
    jax.block_until_ready(x)
    np.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0:1])


def bench(fn, make_args, warm=2, iters=8, label="", chain=False):
    """chain=True: fn(state) -> state, threaded through iterations (train
    steps with donation); else fn(*args) re-called on the same args."""
    args = make_args()
    if chain:
        state = args
        for _ in range(warm):
            state = fn(state)
        drain(state)
        t0 = time.perf_counter()
        for _ in range(iters):
            state = fn(state)
        drain(state)
    else:
        for _ in range(warm):
            out = fn(*args)
        drain(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        drain(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:20s} {dt * 1000:9.2f} ms", flush=True)
    return dt


def main():
    assert jax.devices()[0].platform == "tpu", "needs the real chip"
    from torchft_tpu.platform import apply_compilation_cache_env

    apply_compilation_cache_env(
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".bench_jax_cache")
    )
    rng = np.random.default_rng(0)
    batch = jnp.asarray(rng.integers(0, 8192, size=(B, S), dtype=np.int32))
    tx = optax.adamw(1e-3)

    # -- roofline probe: plain big bf16 matmul, MXU-shaped --
    M = 8192
    a = jax.random.normal(jax.random.PRNGKey(1), (M, M), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(2), (M, M), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    dt = bench(mm, lambda: (a, b), iters=32, label="matmul 8192^3")
    print(f"  -> {2 * M**3 / dt / 1e12:.1f} TFLOP/s sustained", flush=True)
    del a, b

    flash_cfg = TransformerConfig(use_flash=True, **CFG)
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(
                       init_params(flash_cfg, jax.random.PRNGKey(0))))
    ptf = 6 * n_params * B * S / 1e12
    print(f"params {n_params / 1e6:.1f}M  param-TFLOP/step {ptf:.2f}",
          flush=True)

    def fresh_state():
        p = init_params(flash_cfg, jax.random.PRNGKey(0))
        return (p, tx.init(p))

    # -- full fused step --
    step = make_train_step(flash_cfg, tx)
    dt = bench(lambda st: step(st[0], st[1], batch)[:2], fresh_state,
               label="step_full", chain=True)
    print(f"  -> {ptf / dt:.1f} param-TFLOP/s", flush=True)

    # -- grad only (no apply; non-donating) --
    gf = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(flash_cfg, p, b)))
    p0 = init_params(flash_cfg, jax.random.PRNGKey(0))
    bench(gf, lambda: (p0, batch), label="grad_only")

    # -- forward only --
    ff = jax.jit(lambda p, b: loss_fn(flash_cfg, p, b))
    bench(ff, lambda: (p0, batch), label="fwd_only")

    # -- attention cost by subtraction: identity-attention model --
    real_attn = T._attention_impl
    try:
        T._attention_impl = lambda cfg, p, x: x @ p["wo"].astype(cfg.dtype)
        step_na = make_train_step(flash_cfg, tx)
        bench(lambda st: step_na(st[0], st[1], batch)[:2], fresh_state,
              label="step_no_attn", chain=True)
    finally:
        T._attention_impl = real_attn

    # -- xent cost by subtraction: mean-logit loss --
    real_loss = T.next_token_loss
    try:
        T.next_token_loss = lambda logits, targets: jnp.mean(logits)
        step_ml = make_train_step(flash_cfg, tx)
        bench(lambda st: step_ml(st[0], st[1], batch)[:2], fresh_state,
              label="step_mean_loss", chain=True)
    finally:
        T.next_token_loss = real_loss

    # -- standalone flash fwd+bwd at the in-model shape (S-1 = 2047) --
    from torchft_tpu.ops import flash_attention

    Sm = S - 1
    q = jax.random.normal(jax.random.PRNGKey(3), (B, Sm, 16, 64), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(4), (B, Sm, 16, 64), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(5), (B, Sm, 16, 64), jnp.bfloat16)

    def aloss(q, k, v):
        return jnp.sum(flash_attention(q, k, v).astype(jnp.float32))

    af = jax.jit(jax.grad(aloss, argnums=(0, 1, 2)))
    dt = bench(af, lambda: (q, k, v), label="attn_standalone")
    attn_flop = 4 * B * 16 * Sm * Sm * 64 / 2 * 3.5  # causal, fwd+2.5x bwd
    print(f"  -> x8 layers = {dt * 8 * 1000:.1f} ms/step; "
          f"{attn_flop / dt / 1e12:.1f} TFLOP/s eff (causal-counted)",
          flush=True)


if __name__ == "__main__":
    main()
