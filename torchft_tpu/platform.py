"""Backend-selection helper for entry scripts.

On hosts where a sitecustomize registers and pins an accelerator backend
via ``jax.config`` at interpreter start, the ``JAX_PLATFORMS`` env var
alone loses that race — subprocesses that must run on CPU (tests, local
replica-group simulation, bench peers) silently land on the accelerator
and pay a device round-trip per collective. Entry points call
:func:`apply_jax_platform_env` right after ``import jax`` to make the env
var authoritative again.
"""

from __future__ import annotations

import os


def apply_jax_platform_env() -> None:
    """Re-applies ``JAX_PLATFORMS`` through ``jax.config`` (no-op when the
    env var is unset or jax is already initialized on the right backend)."""
    platforms = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if not platforms:
        return
    import jax

    jax.config.update("jax_platforms", platforms)


def standby_gate() -> None:
    """Hot-spare start line. When ``TORCHFT_STANDBY_FILE`` is set, the
    process is a pre-warmed STANDBY: call this after imports and jit
    warm-up but BEFORE creating the Manager (a standby must not join
    quorums or heartbeat), and it blocks until the supervisor activates
    the process by creating the file. No-op for normal processes.

    This is the process-level analog of ``WorldSizeMode.FIXED_WITH_SPARES``:
    a cold restart pays interpreter + library import + compile before it
    can heal (~14 s measured under 4-way CPU contention, CHURN_BENCH.json
    heal breakdown); a promoted standby pays none of it. The launcher's
    ``--hot-spare`` mode manages the standby lifecycle
    (torchft_tpu.launcher).

    Deployment constraint: the standby warms up on ITS OWN resources.
    On a host whose accelerator is exclusively owned by the primary
    (single-chip TPU hosts), a standby cannot warm the same chip — run
    standbys on separate hosts (the per-host-per-group topology this
    framework targets) or accept cold restarts there.

    If the supervisor dies without activating us (hard kill: its cleanup
    never runs), exit instead of leaking a fully-warmed parked process.

    Reaching the gate means warm-up is COMPLETE, so a ``<path>.warm``
    marker is touched on entry: the supervisor reads it to tell a
    fully-warmed spare from one still importing/compiling — the
    warm-deadline re-arm policy (a half-warmed spare on a saturated host
    gets its idle priority lifted so the NEXT kill finds it parked here,
    not mid-import) and promotion logging both key off it."""
    path = os.environ.get("TORCHFT_STANDBY_FILE")
    if not path:
        return
    import sys
    import time

    try:
        open(path + ".warm", "w").close()
    except OSError:
        pass  # marker is advisory; the gate still works without it
    supervisor = os.getppid()
    while not os.path.exists(path):
        if os.getppid() != supervisor:
            sys.exit(0)  # orphaned: supervisor is gone, nobody can promote us
        time.sleep(0.05)


def standby_should_warm() -> bool:
    """Whether a standby should run the full AOT warm-up before parking
    (``FTTrainState.warm`` + ``HostCollectives.prewarm``): default yes —
    promotion is then quorum join + weight fetch only. Set
    ``TORCHFT_STANDBY_WARM=0`` to park right after imports instead (e.g.
    when the warm-up itself would fight the primary for a single
    accelerator)."""
    return os.environ.get("TORCHFT_STANDBY_WARM", "1") != "0"


def standby_warm_deadline_s() -> float:
    """How long a supervisor lets a niced standby warm before lifting it
    to normal priority (``TORCHFT_STANDBY_WARM_DEADLINE_S``, default 20).
    On a saturated host an idle-priority warm-up can starve forever —
    the round-3/round-5 hot-spare regression: every promotion found a
    HALF-warmed spare and paid the full import+compile on the heal
    critical path. Lifting after a bounded grace costs a few seconds of
    measured contention once per re-arm; an unwarmed spare costs ~15 s on
    EVERY subsequent kill of that group."""
    try:
        return float(os.environ.get("TORCHFT_STANDBY_WARM_DEADLINE_S", "20"))
    except ValueError:
        return 20.0


def heal_boost_nice() -> int:
    """Nice-level boost (``TORCHFT_HEAL_BOOST``, default 5; ``0``
    disables) a PRIVILEGED supervisor gives a cold-restarting worker
    while it heals, de-boosting at its first committed step (or a 60 s
    hard cap). Rationale: on a shared host the restarting member is the
    cohort's degraded one — survivors keep committing without it — so a
    bounded slice of their CPU during the heal shortens the window the
    cohort runs without redundancy; measured on a 2-CPU 4-group box it
    roughly halves the cold import+compile path. Supervisors must gate
    it on the same capability probe as standby renicing (boosting needs
    CAP_SYS_NICE / root / RLIMIT_NICE)."""
    try:
        return max(0, int(os.environ.get("TORCHFT_HEAL_BOOST", "5")))
    except ValueError:
        return 5


def apply_compilation_cache_env(default_dir: str = "") -> None:
    """Enables JAX's persistent compilation cache from the
    ``TORCHFT_COMPILE_CACHE`` env var (falling back to ``default_dir``).

    Heal latency on a restarted replica is dominated by process restart +
    re-jit, not weight transfer; with the cache on, the restarted process
    loads the executables its predecessor compiled (measured on this
    harness: 1.5 s -> 0.3 s for the churn-bench model) and rejoins within
    a few seconds. Set ``TORCHFT_COMPILE_CACHE=0`` to disable. The
    launcher exports a per-job default so every replica group shares one
    cache (torchft_tpu.launcher)."""
    path = os.environ.get("TORCHFT_COMPILE_CACHE", default_dir)
    if not path or path == "0":
        return
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache every executable: the default thresholds skip sub-second
    # compiles, but at heal time even those are re-paid under restart
    # contention.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
