"""CNN model family: shapes, training signal, DP sharding, FT composition."""

import jax
import jax.numpy as jnp
import numpy as np

from torchft_tpu.models import cnn
from torchft_tpu.models.cnn import tiny_cnn_config


def _batch(cfg, n=8, seed=0):
    rng = np.random.default_rng(seed)
    images = jnp.asarray(
        rng.standard_normal((n, cfg.image_size, cfg.image_size, cfg.channels)),
        jnp.float32,
    )
    labels = jnp.asarray(rng.integers(0, cfg.classes, n), jnp.int32)
    return images, labels


def test_forward_shapes_and_finite():
    cfg = tiny_cnn_config()
    params = cnn.init_params(cfg, jax.random.PRNGKey(0))
    images, _ = _batch(cfg)
    logits = cnn.forward(cfg, params, images)
    assert logits.shape == (8, cfg.classes)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_loss_decreases_with_sgd():
    import optax

    cfg = tiny_cnn_config()
    params = cnn.init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg, n=16)
    tx = optax.sgd(0.05)
    opt_state = tx.init(params)
    step = jax.jit(
        lambda p, o, b: (lambda l, g: (l, *(
            lambda u, no: (optax.apply_updates(p, u), no)
        )(*tx.update(g, o, p))))(
            *jax.value_and_grad(lambda pp: cnn.loss_fn(cfg, pp, b))(p)
        )
    )
    first = None
    for _ in range(15):
        loss, params, opt_state = step(params, opt_state, batch)
        first = first if first is not None else float(loss)
    assert float(loss) < first, (first, float(loss))


def test_dp_sharded_batch_matches_unsharded():
    from torchft_tpu.parallel import make_mesh

    cfg = tiny_cnn_config()
    params = cnn.init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    base = float(cnn.loss_fn(cfg, params, batch))

    mesh = make_mesh({"data": 8})
    from jax.sharding import NamedSharding, PartitionSpec as P

    images = jax.device_put(
        batch[0], NamedSharding(mesh, P("data", None, None, None))
    )
    labels = jax.device_put(batch[1], NamedSharding(mesh, P("data")))
    sharded = float(
        jax.jit(lambda p, b: cnn.loss_fn(cfg, p, b))(params, (images, labels))
    )
    # bf16 activations: sharded batch stats reduce in a different order
    np.testing.assert_allclose(sharded, base, rtol=1e-3, atol=1e-3)


def test_cnn_trains_with_ft_stack():
    from datetime import timedelta

    import optax

    from torchft_tpu import Lighthouse, Store
    from torchft_tpu.collectives import DummyCollectives
    from torchft_tpu.manager import Manager

    cfg = tiny_cnn_config()
    params = cnn.init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    batch = _batch(cfg)

    lighthouse = Lighthouse(
        bind="[::]:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=50, heartbeat_timeout_ms=1000,
    )
    store = Store()
    manager = Manager(
        collectives=DummyCollectives(world_size=1),
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=1,
        rank=0,
        world_size=1,
        use_async_quorum=False,
        timeout=timedelta(seconds=10),
        store_addr=store.address(),
        lighthouse_addr=lighthouse.address(),
        replica_id="cnn_test",
    )
    try:
        manager.start_quorum()
        loss, grads = jax.value_and_grad(
            lambda p: cnn.loss_fn(cfg, p, batch)
        )(params)
        grads = manager.allreduce(grads).wait()
        assert manager.should_commit()
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax as _optax

        params = _optax.apply_updates(params, updates)
        assert np.isfinite(float(loss))
    finally:
        manager.shutdown()
        store.shutdown()
        lighthouse.shutdown()
