"""Chaos-plane invariants: the seeded fault engine, the CRC-guarded wire,
the stall verdict, heal-range integrity, and the replayable step-
transaction harness (a fixed small seed set — the CI gate; the broad
seeded sweep lives in scripts/chaos_run.py).

The load-bearing claims proven here:

- DETERMINISM: a FaultPlan is a pure function of its seed; the native
  engine's firing decisions replay from (seed, plan).
- DETECTION: a wire bit flip (or stream-desyncing duplicate) on any ring
  path with TORCHFT_WIRE_CRC on raises the typed WireCorruption — and
  with CRC off the same flip commits silently (the gap the CRC closes,
  pinned as a test so the motivation stays true).
- ZERO ADDED COST OFF: with CRC off the wire carries EXACTLY the
  pre-CRC byte count (measured per-tier tx, not a model), and on it
  carries exactly +4 bytes per frame — the single-branch contract.
- STALL VERDICT: a SIGSTOPped child surfaces as ChildStalledError
  within the stall grace, never the op timeout masquerade.
- TRANSACTION INVARIANTS: seeded schedules over a real multi-member TCP
  fleet commit no step under mixed quorum epochs, end bit-identical,
  never commit a corrupted step, and recover to a clean commit.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from datetime import timedelta

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import torchft_tpu._native as _native  # noqa: E402
from torchft_tpu._native import Store, WireCorruption  # noqa: E402
from torchft_tpu.chaos import (  # noqa: E402
    ChaosInjector,
    FaultEvent,
    FaultPlan,
    HealFaultProxy,
    splitmix64,
)
from torchft_tpu.collectives import HostCollectives  # noqa: E402
from torchft_tpu.isolated_xla import (  # noqa: E402
    ChildDiedError,
    ChildStalledError,
    _MonitoredChannel,
)

import chaos_run  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    _native.fault_disarm()


@pytest.fixture
def store():
    s = Store()
    yield s
    s.shutdown()


def _make_ring(store, n, prefix, crc, stripes=1, timeout_s=10):
    cols = [
        HostCollectives(
            timeout=timedelta(seconds=timeout_s),
            stripes=stripes,
            wire_crc=crc,
        )
        for _ in range(n)
    ]
    threads = [
        threading.Thread(
            target=cols[r].configure,
            args=(f"{store.address()}/{prefix}", r, n),
        )
        for r in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return cols


def _run_all(cols, fn):
    out = [None] * len(cols)
    errs = [None] * len(cols)

    def run(r):
        try:
            out[r] = fn(cols[r], r)
        except Exception as e:  # noqa: BLE001 - the errors ARE the data
            errs[r] = e

    threads = [
        threading.Thread(target=run, args=(r,)) for r in range(len(cols))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out, errs


class TestFaultPlan:
    def test_random_is_deterministic_in_seed(self):
        a = FaultPlan.random(123, steps=10, members=4)
        b = FaultPlan.random(123, steps=10, members=4)
        c = FaultPlan.random(124, steps=10, members=4)
        assert a == b
        assert a != c

    def test_json_roundtrip(self):
        plan = FaultPlan.random(7, steps=6, members=3,
                                seams=("ring_send", "net_send"))
        assert FaultPlan.from_json(plan.to_json()) == plan

    def test_step_zero_stays_clean(self):
        for seed in range(20):
            plan = FaultPlan.random(seed, steps=5, members=2)
            assert all(e.step >= 1 for e in plan.events)

    def test_native_rules_cover_only_native_seams(self):
        plan = FaultPlan(
            seed=1,
            events=(
                FaultEvent(2, "ring_send", "bit_flip", 0),
                FaultEvent(2, "child", "sigstop", 1),
            ),
        )
        rules = plan.native_rules(2)
        assert len(rules) == 1 and rules[0]["seam"] == "ring_send"
        assert rules[0]["max_fires"] == 1 and rules[0]["permille"] == 1000

    def test_fingerprint_replays(self):
        plan = FaultPlan.random(55, steps=8, members=2)
        fp = plan.fingerprint()
        assert FaultPlan.from_json(fp["plan"]) == plan
        assert fp["seed"] == 55

    def test_splitmix64_matches_native_backoff_mixer(self):
        # Same constants as native mix64 (net.cc splitmix64): pin a known
        # value so the two streams can never drift silently.
        assert splitmix64(0) == 0xE220A8397B1DCDAF


class TestBenchFaultStamp:
    """The ``fault_plan`` key every bench artifact carries (bench_churn /
    bench_dcn / bench_policy): whatever produced the run must be
    replayable from the stamp."""

    def test_explicit_plan_wins(self, monkeypatch):
        from torchft_tpu.chaos import bench_fault_stamp

        monkeypatch.setenv("TORCHFT_CHAOS_SEED", "999")
        plan = FaultPlan.random(3, steps=4, members=2)
        stamp = bench_fault_stamp(plan=plan, bench="x")
        assert stamp["seed"] == 3
        assert FaultPlan.from_json(stamp["plan"]) == plan
        assert stamp["bench"] == "x"

    def test_env_seed_and_plan_contract(self, monkeypatch):
        from torchft_tpu.chaos import bench_fault_stamp

        monkeypatch.delenv("TORCHFT_CHAOS_PLAN", raising=False)
        monkeypatch.setenv("TORCHFT_CHAOS_SEED", "77")
        assert bench_fault_stamp()["seed"] == 77
        plan = FaultPlan.random(12, steps=4, members=2)
        monkeypatch.setenv("TORCHFT_CHAOS_PLAN", plan.to_json())
        stamp = bench_fault_stamp(kill_every=100)
        assert stamp["seed"] == 12 and stamp["kill_every"] == 100

    def test_unseeded_run_stamps_none(self, monkeypatch):
        from torchft_tpu.chaos import bench_fault_stamp

        monkeypatch.delenv("TORCHFT_CHAOS_PLAN", raising=False)
        monkeypatch.delenv("TORCHFT_CHAOS_SEED", raising=False)
        assert bench_fault_stamp()["seed"] is None


class TestNativeFaultEngine:
    def test_arm_disarm_states(self):
        assert not _native.fault_armed()
        _native.fault_arm({"seed": 1, "rules": [
            {"seam": "ring_send", "kind": "drop"}]})
        assert _native.fault_armed()
        _native.fault_arm({"seed": 1, "rules": []})
        assert not _native.fault_armed()  # empty rules = disarmed
        _native.fault_disarm()
        stats = _native.fault_stats()
        assert stats["fired_total"] == 0

    def test_bad_plan_raises(self):
        with pytest.raises(RuntimeError, match="unknown seam"):
            _native.fault_arm({"seed": 1, "rules": [
                {"seam": "nope", "kind": "drop"}]})
        with pytest.raises(RuntimeError, match="unknown kind"):
            _native.fault_arm({"seed": 1, "rules": [
                {"seam": "ring_send", "kind": "nope"}]})

    def test_permille_zero_never_fires(self, store):
        cols = _make_ring(store, 2, "pz", crc=True)
        _native.fault_arm({"seed": 3, "rules": [
            {"seam": "ring_send", "kind": "bit_flip", "permille": 0}]})
        out, errs = _run_all(
            cols,
            lambda c, r: c.allreduce(
                {"w": np.ones(256, dtype=np.float32)}
            ).wait(),
        )
        assert all(e is None for e in errs), errs
        assert _native.fault_stats()["fired_total"] == 0
        for c in cols:
            c.shutdown()


class TestWireCrc:
    def test_crc32c_known_vector(self):
        assert _native.crc32c(b"123456789") == 0xE3069283
        assert _native.crc32c_combine([b"1234", b"56789"]) == 0xE3069283
        assert _native.crc32c(memoryview(bytearray(b"123456789"))) == (
            0xE3069283
        )

    @pytest.mark.parametrize("path,wire", [
        ("legacy", None),
        ("legacy", "q8"),
        ("plan", None),
        ("plan", "bf16"),
        ("plan", "q8"),
    ])
    def test_clean_ops_bit_identical_crc_on(self, store, path, wire):
        """CRC is pure framing: results with the guarded wire match the
        raw wire bit for bit on every encoding and both schedule paths."""
        tree = {"w": (np.arange(4096) % 17).astype(np.float32)}
        results = {}
        for crc in (False, True):
            cols = _make_ring(store, 2, f"id{int(crc)}{path}{wire}", crc=crc)
            if path == "legacy":
                fn = lambda c, r: c.allreduce(dict(tree), wire=wire).wait()
            else:
                fn = lambda c, r: c.plan_allreduce(
                    dict(tree), wire=wire
                ).wait()
            out, errs = _run_all(cols, fn)
            assert all(e is None for e in errs), errs
            assert out[0]["w"].tobytes() == out[1]["w"].tobytes()
            results[crc] = out[0]["w"].tobytes()
            for c in cols:
                c.shutdown()
        assert results[False] == results[True]

    def test_bit_flip_detected_with_crc(self, store):
        cols = _make_ring(store, 2, "bf", crc=True)
        _native.fault_arm({"seed": 42, "rules": [
            {"seam": "ring_send", "kind": "bit_flip", "member": 0,
             "max_fires": 1}]})
        out, errs = _run_all(
            cols,
            lambda c, r: c.allreduce(
                {"w": np.ones(2048, dtype=np.float32)}
            ).wait(),
        )
        stats = _native.fault_stats()
        assert stats["fired"].get("ring_send:bit_flip") == 1
        assert any(isinstance(e, WireCorruption) for e in errs if e), errs
        for c in cols:
            c.shutdown()

    def test_bit_flip_silent_without_crc(self, store):
        """The motivating gap, pinned: with CRC off the same flip decodes
        cleanly and COMMITS wrong bytes — the one failure the vote cannot
        catch. If this test ever fails, the raw wire grew a payload check
        and the CRC knob's rationale needs rewriting."""
        cols = _make_ring(store, 2, "bfoff", crc=False)
        _native.fault_arm({"seed": 42, "rules": [
            {"seam": "ring_send", "kind": "bit_flip", "member": 0,
             "max_fires": 1}]})
        out, errs = _run_all(
            cols,
            lambda c, r: c.allreduce(
                {"w": np.ones(2048, dtype=np.float32)}
            ).wait(),
        )
        assert all(e is None for e in errs), errs
        corrupted = (
            out[0]["w"].tobytes() != out[1]["w"].tobytes()
            or not np.all(out[0]["w"] == 1.0)
        )
        assert corrupted
        for c in cols:
            c.shutdown()

    @pytest.mark.parametrize("path,wire", [
        ("legacy", None),
        ("legacy", "q8"),
        ("plan", None),
        ("plan", "bf16"),
        ("plan", "q8"),
        ("hier", None),
    ])
    def test_bit_flip_detected_every_wire_and_path(self, store, path, wire):
        """The acceptance matrix: a mid-ring payload bit flip is
        DETECTED on every wire encoding and schedule path — the step
        errors (latch -> vote discard), never a clean commit of
        poisoned bytes."""
        regions = ["r0", "r1"] if path == "hier" else None
        cols = [
            HostCollectives(timeout=timedelta(seconds=10), stripes=1,
                            wire_crc=True)
            for _ in range(2)
        ]
        threads = [
            threading.Thread(
                target=cols[r].configure,
                args=(f"{store.address()}/m{path}{wire}", r, 2, regions),
            )
            for r in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _native.fault_arm({"seed": 11, "rules": [
            {"seam": "ring_send", "kind": "bit_flip", "member": 0,
             "max_fires": 1}]})
        tree = {"w": np.ones(8192, dtype=np.float32)}
        if path == "legacy":
            fn = lambda c, r: c.allreduce(dict(tree), wire=wire).wait()
        elif path == "plan":
            fn = lambda c, r: c.plan_allreduce(dict(tree), wire=wire).wait()
        else:
            fn = lambda c, r: c.allreduce_hier(dict(tree)).wait()
        out, errs = _run_all(cols, fn)
        stats = _native.fault_stats()
        assert stats["fired"].get("ring_send:bit_flip") == 1, stats
        fails = [e for e in errs if e is not None]
        assert fails, f"flip committed cleanly on {path}/{wire}"
        texts = " | ".join(str(e) for e in fails)
        assert (
            "wire corruption" in texts or "protocol desync" in texts
        ), texts
        for c in cols:
            c.shutdown()

    def test_bit_flip_typed_detection_survives_striping(self, store):
        """With stripes > 1 the corrupted stripe's shutdown makes its
        SIBLINGS die with generic socket errors; the TYPED
        WireCorruption must still be the error the victim member
        surfaces (run_striped prefers it over stripe order) — the
        detection ledger cannot depend on which stripe lost the race."""
        cols = _make_ring(store, 2, "bfstr", crc=True, stripes=4)
        _native.fault_arm({"seed": 21, "rules": [
            {"seam": "ring_send", "kind": "bit_flip", "member": 0,
             "max_fires": 1}]})
        # large enough that all 4 stripes are active (>= 64 KiB each)
        out, errs = _run_all(
            cols,
            lambda c, r: c.allreduce(
                {"w": np.ones(1 << 17, dtype=np.float32)}
            ).wait(),
        )
        assert _native.fault_stats()["fired"].get("ring_send:bit_flip") == 1
        assert any(isinstance(e, WireCorruption) for e in errs if e), [
            f"{type(e).__name__}: {e}" for e in errs if e
        ]
        for c in cols:
            c.shutdown()

    def test_duplicate_detected_with_crc(self, store):
        cols = _make_ring(store, 2, "dup", crc=True)
        _native.fault_arm({"seed": 8, "rules": [
            {"seam": "ring_send", "kind": "duplicate", "member": 1,
             "max_fires": 1}]})
        out, errs = _run_all(
            cols,
            lambda c, r: c.allreduce(
                {"w": np.ones(4096, dtype=np.float32)}
            ).wait(),
        )
        # the shifted stream must surface as a typed integrity/desync
        # error somewhere in the ring — never a clean commit
        assert any(e is not None for e in errs)
        texts = " | ".join(str(e) for e in errs if e)
        assert "wire corruption" in texts or "protocol desync" in texts
        for c in cols:
            c.shutdown()

    def test_crc_mismatch_fails_fast_at_negotiation(self, store):
        cols = [
            HostCollectives(timeout=timedelta(seconds=5), stripes=1,
                            wire_crc=(r == 0))
            for r in range(2)
        ]
        out, errs = _run_all(
            cols,
            lambda c, r: c.configure(f"{store.address()}/mix", r, 2),
        )
        assert any(
            e is not None and "mismatch" in str(e) for e in errs
        ), errs
        for c in cols:
            c.shutdown()

    def test_header_desync_error_names_the_edge(self, store):
        """The enriched protocol-desync error: tier, peer address, op
        kind and op index — a W=8 fleet log must name the guilty edge."""
        cols = _make_ring(store, 2, "hdr", crc=False)
        _native.fault_arm({"seed": 4, "rules": [
            {"seam": "ring_hdr", "kind": "bit_flip", "member": 0,
             "max_fires": 1}]})
        out, errs = _run_all(
            cols,
            lambda c, r: c.allreduce(
                {"w": np.ones(128, dtype=np.float32)}
            ).wait(),
        )
        texts = [str(e) for e in errs if e is not None]
        assert texts, "header corruption surfaced nowhere"
        desync = [t for t in texts if "protocol desync" in t]
        assert desync, texts
        for key in ("tier=", "prev_peer=", "op_kind=", "op_index="):
            assert key in desync[0], desync[0]
        for c in cols:
            c.shutdown()


class TestCrcAccounting:
    def test_crc_off_adds_zero_wire_bytes_and_on_adds_4_per_frame(
        self, store
    ):
        """The single-branch contract, proven on MEASURED bytes: with
        CRC off the inter tier ships exactly the analytic pre-CRC byte
        count (header 24B + one chunk per rs/ag hop), and with CRC on
        exactly 4 more per frame (3 frames here: header, rs hop, ag
        hop). Any hidden cost in the off path would break the equality,
        not a tolerance."""
        count = 1024  # f32 elems; W=2 chunks of 512
        analytic_off = 24 + (count // 2) * 4 + (count // 2) * 4
        measured = {}
        for crc in (False, True):
            cols = [
                HostCollectives(timeout=timedelta(seconds=10), stripes=1,
                                wire_crc=crc)
                for _ in range(2)
            ]
            threads = [
                threading.Thread(
                    target=cols[r].configure,
                    args=(f"{store.address()}/acct{int(crc)}", r, 2,
                          ["r0", "r1"]),
                )
                for r in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert cols[0].hier_capable()
            out, errs = _run_all(
                cols,
                lambda c, r: c.allreduce_hier(
                    {"w": np.ones(count, dtype=np.float32)}
                ).wait(),
            )
            assert all(e is None for e in errs), errs
            measured[crc] = cols[0]._last_hier_dict()["inter_tx_bytes"]
            for c in cols:
                c.shutdown()
        assert measured[False] == analytic_off
        assert measured[True] == analytic_off + 4 * 3


class _Sleeper:
    """A real child process for the monitored-channel verdict tests."""

    def __enter__(self):
        self.proc = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(120)"]
        )
        a, b = socket.socketpair()
        self.sock_a = a
        self.sock_b = b
        self.channel = _MonitoredChannel(
            a, self.proc.poll, pid=self.proc.pid
        )
        return self

    def __exit__(self, *exc):
        try:
            self.proc.kill()
            self.proc.wait(timeout=5)
        except Exception:
            pass
        self.sock_a.close()
        self.sock_b.close()


class TestStallVerdict:
    def test_sigstop_surfaces_as_stall_within_grace(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_ISO_STALL_MS", "300")
        with _Sleeper() as s:
            os.kill(s.proc.pid, signal.SIGSTOP)
            t0 = time.monotonic()
            with pytest.raises(ChildStalledError, match="STALLED"):
                s.channel.recv(timeout_s=10.0)
            took = time.monotonic() - t0
            os.kill(s.proc.pid, signal.SIGCONT)
        # verdict at the grace, not the 10 s deadline
        assert took < 5.0, took

    def test_running_child_times_out_not_stalls(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_ISO_STALL_MS", "300")
        with _Sleeper() as s:
            with pytest.raises(TimeoutError):
                s.channel.recv(timeout_s=0.8)

    def test_dead_child_is_died_not_stalled(self):
        with _Sleeper() as s:
            s.proc.kill()
            s.proc.wait(timeout=5)
            with pytest.raises(ChildDiedError) as ei:
                s.channel.recv(timeout_s=5.0)
            assert not isinstance(ei.value, ChildStalledError)

    def test_brief_stop_within_grace_is_not_a_verdict(self, monkeypatch):
        """A SIGSTOP/SIGCONT pulse shorter than the grace (a debugger
        attach, a cgroup freeze blip) must NOT kill the child's op."""
        monkeypatch.setenv("TORCHFT_ISO_STALL_MS", "2000")
        with _Sleeper() as s:
            os.kill(s.proc.pid, signal.SIGSTOP)

            def cont():
                time.sleep(0.3)
                os.kill(s.proc.pid, signal.SIGCONT)
                time.sleep(0.2)
                s.sock_b.sendall(b'{"ok": 1}\n')

            t = threading.Thread(target=cont)
            t.start()
            msg = s.channel.recv(timeout_s=10.0)
            t.join()
            assert msg == {"ok": 1}


class TestHealRangeCrc:
    def _publish(self, nbytes=1 << 16):
        from torchft_tpu.checkpointing import CheckpointServer

        srv = CheckpointServer(timeout=timedelta(seconds=10))
        state = {
            "params": {
                "w": np.arange(nbytes // 4, dtype=np.float32)
            }
        }
        srv.send_checkpoint(
            [1], step=1, state_dict=state, timeout=timedelta(seconds=10)
        )
        return srv, state

    def test_range_header_matches_body(self):
        import urllib.parse
        import urllib.request

        srv, _state = self._publish()
        try:
            base = srv.address()
            with urllib.request.urlopen(
                f"{base}1/stream/0/2/none/1", timeout=10
            ) as resp:
                want = resp.headers["X-TFT-Crc32c"]
                body = resp.read()
            assert want is not None
            assert int(want, 16) == _native.crc32c(body)
        finally:
            srv.shutdown()

    def test_corrupted_range_detected_and_fallback_correct(self):
        import urllib.parse

        from torchft_tpu.checkpointing import CheckpointServer

        srv, state = self._publish()
        parts = urllib.parse.urlparse(srv.address())
        proxy = HealFaultProxy(
            f"{parts.scheme}://{parts.netloc}",
            mode="bit_flip",
            only_paths=("/stream/",),
            max_faults=1,
        )
        try:
            out, stats = CheckpointServer._fetch(
                proxy.address() + parts.path + "1",
                timeout=timedelta(seconds=15),
            )
            assert proxy.faults_fired == 1
            # detected -> NOT the stream path; bytes still exact
            assert stats["path"] != "stream"
            np.testing.assert_array_equal(
                out["params"]["w"], state["params"]["w"]
            )
        finally:
            proxy.shutdown()
            srv.shutdown()


class TestTransactionInvariants:
    """The CI chaos-invariant gate: fixed small seeds through the REAL
    fleet harness (scripts/chaos_run.py), one schedule per data-plane
    configuration. The broad random sweep (more seeds, every seam, the
    policy fleet, the iso probes) is scripts/chaos_run.py's full run."""

    def _flip_plan(self, member=0, step=2):
        return FaultPlan(
            seed=7,
            events=(
                FaultEvent(step, "ring_send", "bit_flip", member),
            ),
        )

    def test_ddp_bit_flip_discarded_then_recovers(self):
        rec = chaos_run.run_schedule(
            7, "ddp", groups=2, steps=4, plan=self._flip_plan(),
            deadline_s=120,
        )
        assert rec["crc_detections"] >= 1
        assert rec["silent_commits"] == 0
        assert rec["liveness_ok"] and rec["bit_identity_ok"]

    def test_plan_path_seeded_schedule(self):
        rec = chaos_run.run_schedule(
            1031, "plan", groups=2, steps=4,
            plan=FaultPlan(
                seed=1031,
                events=(
                    FaultEvent(1, "ring_send", "bit_flip", 1),
                    FaultEvent(2, "ring_send", "drop", 0),
                ),
            ),
            deadline_s=120,
        )
        assert rec["crc_detections"] >= 1
        assert rec["epoch_purity_ok"] and rec["bit_identity_ok"]

    @pytest.mark.slow
    def test_hier_seeded_schedule(self):
        rec = chaos_run.run_schedule(
            9000, "hier", groups=4, steps=6,
            plan=FaultPlan(
                seed=9000,
                events=(
                    FaultEvent(2, "ring_send", "bit_flip", 0),
                    FaultEvent(3, "ring_send", "partition", 2),
                ),
            ),
            deadline_s=240,
        )
        assert rec["crc_detections"] >= 1
        assert rec["liveness_ok"]

    @pytest.mark.slow
    def test_random_seeds_ddp(self):
        for seed in (101, 202):
            rec = chaos_run.run_schedule(
                seed, "ddp", groups=3, steps=6, deadline_s=240
            )
            assert rec["silent_commits"] == 0
