// Minimal HTTP/1.1 response + escaping helpers shared by the dashboard
// endpoints of the flat/root lighthouse and the region tier. The servers
// sniff HTTP apart from protocol frames on one port (see lighthouse.cc
// handle_conn); everything here is response-side only.
#pragma once

#include <cstring>
#include <sstream>
#include <string>

#include "net.h"

namespace tft {

// Sniffs whether the connection opens with an HTTP request (ASCII method)
// instead of a protocol frame (whose first byte is the high byte of a u32
// length — 0 for any sane payload). If HTTP, consumes the request head
// through the blank line (64 KiB cap) into `head` and returns true; the
// caller serves HTTP. Otherwise leaves the stream untouched (peek only).
inline bool sniff_http(Socket& sock, std::string& head) {
  char probe[4] = {0};
  size_t n = sock.peek(probe, sizeof(probe));
  if (n < 3 ||
      (memcmp(probe, "GET", 3) != 0 && memcmp(probe, "POS", 3) != 0)) {
    return false;
  }
  char buf[1024];
  while (head.find("\r\n\r\n") == std::string::npos) {
    size_t got = sock.peek(buf, sizeof(buf));
    sock.recv_all(buf, got);
    head.append(buf, got);
    if (head.size() > 64 * 1024) break;
  }
  return true;
}

inline void http_respond(Socket& sock, int code, const std::string& content_type,
                         const std::string& body) {
  std::ostringstream os;
  const char* reason = code == 200 ? "OK" : (code == 404 ? "Not Found" : "Error");
  os << "HTTP/1.1 " << code << " " << reason << "\r\n"
     << "Content-Type: " << content_type << "\r\n"
     << "Content-Length: " << body.size() << "\r\n"
     << "Connection: close\r\n\r\n"
     << body;
  std::string out = os.str();
  sock.send_all(out.data(), out.size());
}

inline std::string html_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '<': out += "&lt;"; break;
      case '>': out += "&gt;"; break;
      case '&': out += "&amp;"; break;
      case '"': out += "&quot;"; break;
      case '\'': out += "&#39;"; break;
      default: out += c;
    }
  }
  return out;
}

} // namespace tft
