"""Prototype fault-tolerant parameter server on reconfigurable collectives.

Reference: torchft/parameter_server.py:31-195. No lighthouse needed
(reference README.md:142-145): the server owns a rendezvous Store and an
HTTP endpoint; each ``GET /new_session`` mints a uuid-prefixed store
namespace, replies with JSON, then hijacks the handler thread to run
``forward(session_id, collectives)`` over a world-size-2 ring (server
rank 0, client rank 1). A failed session frees the collectives; the client
just opens a new session.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import urllib.request
import uuid
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import _native
from .collectives import Collectives

logger: logging.Logger = logging.getLogger(__name__)


class ParameterServer(ABC):
    """Threaded parameter server over the reconfigurable collectives."""

    def __init__(self, port: int = 0) -> None:
        self.store = _native.Store()

        ps = self

        class RequestHandler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:
                if self.path != "/new_session":
                    self.send_error(400, f"invalid path, got {self.path}")
                    return
                try:
                    session_id = str(uuid.uuid4())
                    store_addr = f"{ps.store.address()}/session/{session_id}"
                    logger.info(f"creating new session {session_id}")

                    data = (
                        json.dumps(
                            {"session_id": session_id, "store_addr": store_addr}
                        )
                        + "\n"
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    # Close eagerly so the client knows the JSON is complete,
                    # then hijack this handler thread for the session
                    # (reference parameter_server.py:91-97).
                    self.finish()
                    self.connection.close()

                    ps._handle_session(session_id, store_addr)
                except Exception:
                    logger.exception(
                        f"got exception in request handler for {self.path}"
                    )
                    raise

            def log_message(self, format: str, *args: object) -> None:
                logger.debug(f"parameter server: {format % args}")

        class _Server(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            daemon_threads = True

        self._server = _Server(("::", port), RequestHandler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="parameter_server",
        )
        self._thread.start()
        logger.info(f"Started ParameterServer on {self.address()}...")

    def address(self) -> str:
        """HTTP address for creating sessions: http://host:port/new_session"""
        port = self._server.socket.getsockname()[1]
        return f"http://{socket.gethostname()}:{port}/new_session"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._thread.join()
        self._server.server_close()
        self.store.shutdown()

    @classmethod
    @abstractmethod
    def new_collectives(cls) -> Collectives:
        """A fresh, unconfigured Collectives for one session (both sides)."""

    @classmethod
    def new_session(cls, address: str) -> Collectives:
        """Client side: opens a session, returns collectives configured with
        the server (server rank 0, client rank 1)."""
        with urllib.request.urlopen(address) as f:
            data = json.load(f)
        session_id = data["session_id"]
        store_addr = data["store_addr"]
        logger.info(f"connecting to session {session_id} at {store_addr}")

        collectives = cls.new_collectives()
        collectives.configure(store_addr, rank=1, world_size=2)
        return collectives

    def _handle_session(self, session_id: str, store_addr: str) -> None:
        collectives = self.new_collectives()
        try:
            collectives.configure(store_addr, rank=0, world_size=2)
            self.forward(session_id, collectives)
        finally:
            # A finished or failed session frees its collectives (ring
            # sockets + op thread) immediately, not at GC time.
            collectives.shutdown()

    @abstractmethod
    def forward(self, session_id: str, collectives: Collectives) -> None:
        """Runs once per session on a dedicated thread; loop inside for
        multiple operations. Errors free the collectives — the client then
        opens a new session (reference parameter_server.py:177-195)."""
