"""Cross-replica-group collectives compiled by XLA over a multi-process mesh.

The second of the two cross-group (DCN) data-plane options SURVEY.md §5
maps out for the TPU build (the role of the reference's NCCL backend choice,
reference torchft/process_group.py:299-315):

- :class:`~torchft_tpu.collectives.HostCollectives` (the default): host TCP
  ring, outside XLA. Elastic — reconfigure is a millisecond-scale socket
  rendezvous, device state is untouched, and a dead peer surfaces as an
  abortable socket error.
- :class:`XLACollectives` (this module): the reduction is a jitted psum over
  a GLOBAL device mesh spanning every replica group's processes — gloo
  between CPU hosts, DCN between TPU slices. XLA owns the wire, so large
  payloads ride the fastest path available with zero host involvement
  (pass ``keep_global=True``), but the membership is baked into the
  distributed runtime:

  * ``configure()`` onto a NEW membership must tear down and re-create the
    XLA distributed runtime (``jax.distributed.shutdown`` + backend clear +
    re-initialize), **orphaning every live jax array in the process**:
    measured on CPU, their buffers keep their data (the retired client
    lives while referenced) and implicit transfers let new jits consume
    them, but they pin old-backend memory and none of this is contractual
    on accelerator backends — snapshot training state to host around a
    reconfigure. Measured at ~1.0-1.2 s per reconfigure on CPU vs ~1 ms
    for the host ring (bench_dcn.py, DCN.md).
  * a peer that dies mid-collective wedges the compiled op until the
    distributed-runtime heartbeat gives up (minutes by default) — exactly
    the hazard the reference isolates NCCL in a subprocess for (reference
    process_group.py:303-307,551-1064) and that keeps the host ring the
    default here.

  Use it for static-membership deployments (fixed cohort, spares handled by
  ``WorldSizeMode.FIXED_WITH_SPARES`` restarts) where cross-group bandwidth
  dominates; use the host ring whenever membership is elastic.

Deployment model: ONE process per replica group (slice), same as the
manager. ``configure()`` performs coordinator rendezvous through the same
store/prefix discipline as the host ring, so healthy-membership quorum
changes drop into ``Manager``'s reconfiguration; after a WEDGED collective,
however, ``configure()`` can only fail fast with ``TimeoutError`` (a
compiled op cannot be interrupted — see ``abort()``) and the process must
be restarted, unlike the ring's in-place abort.
"""

from __future__ import annotations

import socket
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from datetime import timedelta
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ._native import StoreClient
from .collectives import (
    Collectives,
    OpStatsMixin,
    ReduceOp,
    Work,
    _flatten,
    _unflatten,
)

_COORD_KEY = "xla_coordinator"

# Bounded retries of the coordinator-port race (see _reserve_port): each
# lost race re-reserves and republishes under the next attempt key, so a
# loss is recovered in-place instead of burning a whole quorum round.
_COORD_ATTEMPTS = 3


def _reserve_port() -> tuple:
    """Reserves an ephemeral port for the distributed-runtime coordinator:
    binds port 0 and returns ``(port, bound_socket)`` with the socket
    STILL HELD — the caller publishes the actual bound port through the
    store while holding it, and closes it only immediately before
    ``jax.distributed.initialize`` binds the same port. The old
    probe-then-close helper released the port before publication, leaving
    a publication-to-initialize window (a full cross-rank rendezvous) in
    which any process could take it; holding the bind shrinks the race to
    the close→re-bind instant, and the attempt-keyed retry in
    ``configure()`` recovers the residual loss in-place."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("", 0))
    return s.getsockname()[1], s


def _is_bind_failure(exc: BaseException) -> bool:
    """Whether an initialize() failure is the coordinator losing the
    reserved port (the lost race the attempt-keyed retry recovers), as
    opposed to a backend-predates-runtime error or a peer outage."""
    msg = str(exc).lower()
    return "address already in use" in msg or (
        "bind" in msg and "fail" in msg
    )


def _is_backend_predates(exc: BaseException) -> bool:
    """Whether an initialize() failure is "the XLA backend pre-dates the
    distributed runtime" ("initialize() must be called before any JAX
    computations") — the ONE failure the teardown-and-retry-once branch
    exists for. Anything else must propagate to the attempt loop: the
    old catch-all retried ARBITRARY RuntimeErrors against the same
    (possibly doomed) coordinator address, paying a spurious
    array-orphaning teardown and, on runtimes whose registration
    timeout is a fatal process abort, dying before the retry protocol
    could ever run."""
    msg = str(exc).lower()
    return "must be called before" in msg or "already initialized" in msg


def _split_store_addr(store_addr: str) -> tuple:
    """``host:port/prefix`` -> (``host:port``, ``prefix``)."""
    if "/" in store_addr:
        hostport, prefix = store_addr.split("/", 1)
    else:
        hostport, prefix = store_addr, ""
    return hostport, prefix


def _leaf_bytes(leaves) -> int:
    """Payload bytes of a leaf list from shapes/dtypes alone (no device
    fetch — ``np.asarray`` on a jax leaf would pull it to host just to
    count)."""
    total = 0
    for l in leaves:
        shape = getattr(l, "shape", ())
        n = 1
        for d in shape:
            n *= int(d)
        total += n * np.dtype(getattr(l, "dtype", np.float64)).itemsize
    return total


def _coord_key(prefix: str, attempt: int) -> str:
    base = f"{prefix}/{_COORD_KEY}" if prefix else _COORD_KEY
    return base if attempt == 0 else f"{base}/r{attempt}"


def _rendezvous_coordinator(
    store: StoreClient,
    prefix: str,
    rank: int,
    attempt: int,
    connect_timeout: timedelta,
    probe_listen: bool = False,
) -> tuple:
    """One coordinator rendezvous attempt, shared by ``XLACollectives``
    and the isolated backend's child. Rank 0 reserves a port (held bind),
    publishes the ACTUAL bound ``host:port`` under the attempt key and
    returns ``(coord, held_socket)`` — the caller must close the socket
    immediately before ``jax.distributed.initialize``. Other ranks fetch
    the key and return ``(coord, None)``.

    ``probe_listen`` (non-zero ranks): poll a TCP connect against the
    coordinator until it accepts before returning. The distributed
    runtime's client retries a failed first connect on a ~1 s backoff, so
    a cohort whose processes (re)start simultaneously pays a full second
    per member without the probe — the dominant term in the measured
    ~1.0 s in-process reconfigure. The isolated child probes; the
    in-process path keeps its historical behavior."""
    key = _coord_key(prefix, attempt)
    if rank == 0:
        port, held = _reserve_port()
        coord = f"{socket.gethostname()}:{port}"
        store.set(key, coord.encode())
        return coord, held
    coord = store.get(key, timeout=connect_timeout).decode()
    if probe_listen:
        host, _, port = coord.rpartition(":")
        deadline = time.perf_counter() + connect_timeout.total_seconds()
        while True:
            try:
                socket.create_connection((host, int(port)), timeout=0.25).close()
                break
            except OSError:
                if time.perf_counter() >= deadline:
                    # NEVER hand a dead coordinator to initialize(): on
                    # runtimes whose registration timeout is a fatal
                    # process abort (observed on jax 0.4's coordination
                    # client) the caller's retry protocol would die with
                    # it. Raising here routes to the attempt loop, which
                    # checks whether rank 0 republished after a lost
                    # port race.
                    raise TimeoutError(
                        f"coordinator {coord} never started listening "
                        f"(attempt {attempt})"
                    )
                time.sleep(0.005)
    return coord, None


class XLACollectives(OpStatsMixin, Collectives):
    """Reconfigurable cross-group collectives as jitted global-mesh psums.

    Results are returned as host-backed local arrays by default (drop-in
    parity with ``HostCollectives``: downstream per-group jitted steps can
    consume them); construct with ``keep_global=True`` to keep results on
    the global mesh (no host hop — the pure-DCN path) when the consumer is
    itself jitted over the global mesh.
    """

    def __init__(
        self,
        timeout: timedelta = timedelta(seconds=60),
        connect_timeout: timedelta = timedelta(seconds=60),
        keep_global: bool = False,
        probe_listen: bool = False,
    ) -> None:
        """``probe_listen``: non-zero ranks poll a TCP connect against
        the published coordinator until it accepts before calling
        ``initialize()`` — the distributed client retries a failed first
        connect on a ~1 s backoff, so cohorts whose processes (re)start
        simultaneously pay ~1 s per configure without it. Default off
        (historical behavior); the isolated backend's child turns it on
        (its whole point is cheap respawn)."""
        self._timeout = timeout
        self._connect_timeout = connect_timeout
        self._keep_global = keep_global
        self._probe_listen = probe_listen
        self._rank = -1
        self._world_size = 0
        self._mesh: Optional[Any] = None
        self._initialized = False
        # One thread: collectives must issue in submission order.
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="xla_collectives"
        )
        self._shutdown_flag = False
        self._aborted = False
        self._jit_cache: dict = {}
        self._protected: List[Any] = []
        # Host snapshots of _protected taken at teardown, restored by the
        # next SUCCESSFUL configure (survives an initialize() failure
        # in between — see teardown_backends in configure()).
        self._pending_snapshots: Optional[List[Any]] = None

    def register_state(self, state: Any) -> None:
        """Registers a state holder (anything with ``snapshot()`` /
        ``restore(snap)``, e.g. :class:`~torchft_tpu.train_state.FTTrainState`)
        to be round-tripped through the host across every reconfigure:
        ``configure()`` onto a new membership tears down the XLA
        distributed runtime and orphans live jax arrays (module
        docstring), so protected holders are snapshotted to host before
        the teardown and restored onto the new backend after it. This is
        the automated form of the manual snapshot discipline the hazard
        note prescribes."""
        self._protected.append(state)

    # -- lifecycle --

    def abort(self) -> None:
        """Fails queued-but-unstarted ops fast. An IN-FLIGHT compiled
        collective cannot be interrupted — XLA owns it until the
        distributed runtime gives up (the wedge hazard DCN.md documents;
        after that the process must reconfigure or restart)."""
        self._aborted = True

    def configure(
        self,
        store_addr: str,
        rank: int,
        world_size: int,
        regions: Optional[Sequence[str]] = None,
        hosts: Optional[Sequence[str]] = None,
    ) -> None:
        # `regions` accepted and ignored (the reconfigure contract): the
        # compiled XLA data plane has no host-side topology to compile —
        # the runtime owns placement.
        # Unblock the queue the way HostCollectives does pre-configure;
        # do_configure clears the flag once the new membership is live.
        self._aborted = True

        def do_configure() -> None:
            import jax

            hostport, prefix = _split_store_addr(store_addr)
            store = StoreClient(hostport, connect_timeout=self._connect_timeout)

            from jax.extend import backend as jax_backend

            def teardown_backends() -> None:
                # Orphans live jax arrays (see module docstring), so
                # registered state holders are snapshotted to host first
                # — lazily, right before the clear, so a no-teardown
                # configure never pays the d2h state copy. Snapshots live
                # on SELF, not a local: if initialize() fails after a
                # teardown, the next configure attempt must still restore
                # the holders (whose arrays are already orphaned) — a
                # local list would leak them and silently hand training
                # stale-backend arrays. Never overwrite pending snapshots:
                # after a failed attempt the holders' current arrays are
                # orphans, and re-snapshotting them would capture garbage.
                if self._pending_snapshots is None:
                    self._pending_snapshots = [
                        s.snapshot() for s in self._protected
                    ]
                jax.clear_caches()
                jax_backend.clear_backends()
                self._jit_cache.clear()

            if self._initialized:
                # Membership change: the distributed runtime is torn down
                # and rebuilt.
                jax.distributed.shutdown()
                teardown_backends()
                self._initialized = False

            attempt = 0
            while True:
                try:
                    coord, held = _rendezvous_coordinator(
                        store, prefix, rank, attempt, self._connect_timeout,
                        probe_listen=self._probe_listen,
                    )
                    init_kwargs = dict(
                        coordinator_address=coord,
                        num_processes=world_size,
                        process_id=rank,
                        initialization_timeout=max(
                            int(self._connect_timeout.total_seconds()), 1
                        ),
                    )
                    if held is not None:
                        # The reserved port was held through publication;
                        # the close→bind instant below is the only
                        # residual race window, and losing it is
                        # recovered by the attempt loop instead of
                        # failing the quorum round.
                        held.close()
                    try:
                        jax.distributed.initialize(**init_kwargs)
                    except RuntimeError as e:
                        if not _is_backend_predates(e):
                            raise
                        # The process already ran jax computations, so the
                        # XLA backend pre-dates the distributed runtime
                        # ("initialize() must be called before any JAX
                        # calls"). Clear it and retry once — pre-existing
                        # arrays are orphaned, same contract as a
                        # reconfigure.
                        teardown_backends()
                        jax.distributed.initialize(**init_kwargs)
                    break
                except Exception as e:  # noqa: BLE001 - attempt routing
                    if attempt + 1 >= _COORD_ATTEMPTS:
                        raise
                    if rank == 0:
                        if not _is_bind_failure(e):
                            raise
                        # Lost the close→bind instant: reserve a fresh
                        # port and republish under the next attempt key.
                        attempt += 1
                        continue
                    # Non-zero rank: a failed initialize may mean rank 0
                    # lost the race and republished. The next attempt
                    # key's presence tells a recoverable loss from a real
                    # outage (absent -> re-raise the original failure).
                    # Short bounded poll: rank 0 republishes within
                    # milliseconds of ITS bind failure (which precedes
                    # this rank's timeout), so waiting a full
                    # connect_timeout here would only stall quorum-level
                    # recovery on every genuine outage.
                    try:
                        store.get(
                            _coord_key(prefix, attempt + 1),
                            timeout=min(
                                self._connect_timeout, timedelta(seconds=2)
                            ),
                        )
                    except Exception:
                        raise e
                    attempt += 1
            self._initialized = True
            from jax.sharding import Mesh

            # One mesh row per process, its local devices as columns, so
            # multi-chip processes (a TPU slice per replica group) shard
            # correctly: the replica axis has size world_size and local
            # devices hold replicated copies of their process's row.
            devs = sorted(
                jax.devices(), key=lambda d: (d.process_index, d.id)
            )
            local_counts = {d.process_index: 0 for d in devs}
            for d in devs:
                local_counts[d.process_index] += 1
            if len(set(local_counts.values())) != 1:
                raise RuntimeError(
                    f"uneven devices per process: {local_counts}"
                )
            per_proc = len(devs) // world_size
            self._mesh = Mesh(
                np.array(devs).reshape(world_size, per_proc),
                ("replica", "local"),
            )
            self._rank = rank
            self._world_size = world_size
            if self._pending_snapshots is not None:
                # Only a teardown orphans device arrays; a no-teardown
                # configure must not pay the host round-trip (or drop the
                # holders' cached executables). Pending snapshots may also
                # be carried over from a PREVIOUS configure whose
                # initialize() failed post-teardown — restored here on the
                # first attempt that succeeds.
                for holder, snap in zip(
                    self._protected, self._pending_snapshots
                ):
                    holder.restore(snap)
                self._pending_snapshots = None
            self._aborted = False

        # Bounded wait: if a wedged in-flight collective is holding the op
        # thread (see abort()), surface a TimeoutError for the manager's
        # error latching instead of blocking the train loop forever.
        budget = (
            self._connect_timeout.total_seconds()
            + self._timeout.total_seconds()
        )
        self._executor.submit(do_configure).result(timeout=budget)

    def global_mesh(self) -> Any:
        """The global mesh spanning every group's devices — jit whole train
        steps over it for the zero-host-copy multi-slice mode."""
        assert self._mesh is not None, "configure() first"
        return self._mesh

    def shutdown(self) -> None:
        if self._shutdown_flag:
            return
        self._shutdown_flag = True

        def do_shutdown() -> None:
            if self._initialized:
                import jax

                jax.distributed.shutdown()
                self._initialized = False

        # Same bounded-wait rationale as configure(): a wedged in-flight
        # collective must not hang process teardown forever. On timeout the
        # op thread stays wedged (only process exit reclaims it — the
        # documented hazard); skip joining it.
        try:
            self._executor.submit(do_shutdown).result(
                timeout=self._timeout.total_seconds()
            )
            self._executor.shutdown(wait=True)
        except FuturesTimeoutError:
            self._executor.shutdown(wait=False)

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    # -- ops --

    def _submit(self, fn: Callable[[], Any]) -> Work:
        if self._shutdown_flag:
            raise RuntimeError("collectives already shut down")

        def guarded() -> Any:
            if self._aborted:
                raise RuntimeError("collectives aborted")
            return fn()

        return Work(self._executor.submit(guarded))

    def _stack_global(self, leaves: List[Any]) -> List[Any]:
        """Each process's leaf becomes row ``rank`` of a (world, *shape)
        global array sharded over the replica axis. jax-array leaves stay
        on device (the process's row IS its local shard); host leaves are
        uploaded."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._mesh
        out = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                local = jnp.expand_dims(leaf, 0)  # no host hop
                sharding = NamedSharding(
                    mesh, P("replica", *([None] * leaf.ndim))
                )
                # The replica axis shards dim 0 (size world == mesh rows);
                # the local axis is unused, so EVERY local device holds a
                # replicated copy of this process's row.
                shards = [
                    jax.device_put(local, d)
                    for d in sorted(
                        sharding.addressable_devices, key=lambda d: d.id
                    )
                ]
                out.append(
                    jax.make_array_from_single_device_arrays(
                        (self._world_size,) + tuple(leaf.shape),
                        sharding,
                        shards,
                    )
                )
            else:
                local = np.asarray(leaf)[None]
                sharding = NamedSharding(
                    mesh, P("replica", *([None] * (local.ndim - 1)))
                )
                out.append(
                    jax.make_array_from_process_local_data(sharding, local)
                )
        return out

    def _localize(self, leaves: List[Any]) -> List[Any]:
        if self._keep_global:
            return list(leaves)
        import jax.numpy as jnp

        return [jnp.asarray(np.asarray(l)) for l in leaves]

    def _reduce_jit(self, n_leaves: int, op: ReduceOp, with_divisor: bool) -> Any:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = ("reduce", n_leaves, int(op), with_divisor)
        fn = self._jit_cache.get(key)
        if fn is None:
            world = self._world_size
            replicated = NamedSharding(self._mesh, P())

            def _div(s, leaf_dtype, d):
                # Same-dtype contract (Collectives.allreduce): integers
                # floor-divide like the host ring does.
                if jnp.issubdtype(leaf_dtype, jnp.integer):
                    return s // jnp.asarray(d, s.dtype)
                return (s / d).astype(leaf_dtype)

            def reduce(leaves, divisor=None):
                outs = []
                for l in leaves:
                    if op == ReduceOp.SUM:
                        r = jnp.sum(l, axis=0)
                        if divisor is not None:
                            r = _div(r, l.dtype, divisor)
                    elif op == ReduceOp.AVG:
                        r = _div(jnp.sum(l, axis=0), l.dtype, world)
                    elif op == ReduceOp.MAX:
                        r = jnp.max(l, axis=0)
                    elif op == ReduceOp.MIN:
                        r = jnp.min(l, axis=0)
                    elif op == ReduceOp.PRODUCT:
                        r = jnp.prod(l, axis=0)
                    else:
                        raise ValueError(f"unsupported op {op}")
                    outs.append(r)
                return outs

            fn = self._jit_cache[key] = jax.jit(
                reduce, out_shardings=[replicated] * n_leaves
            )
        return fn

    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        # wire="q8" is accepted and served LOSSLESSLY: XLA collectives ride
        # ICI/DCN where the f32 psum is the native (and cheaper) path; the
        # quantized wire exists for the host ring's TCP links.
        return self._submit(lambda: self._allreduce_sync(tree, op, divisor))

    def _allreduce_sync(
        self, tree: Any, op: ReduceOp, divisor: Optional[float] = None
    ) -> Any:
        if divisor is not None and op != ReduceOp.SUM:
            raise ValueError("divisor only composes with ReduceOp.SUM")
        if self._world_size == 1:
            if divisor is not None and divisor != 1:
                import jax

                from .collectives import _divide_leaf

                return jax.tree_util.tree_map(
                    lambda l: _divide_leaf(l, divisor), tree
                )
            return tree
        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        t0 = time.perf_counter()
        stacked = self._stack_global(leaves)
        fn = self._reduce_jit(len(leaves), op, divisor is not None)
        t1 = time.perf_counter()
        if divisor is not None:
            import jax.numpy as jnp

            reduced = fn(stacked, jnp.float32(divisor))
        else:
            reduced = fn(stacked)
        t2 = time.perf_counter()
        out = self._localize(reduced)
        # pop_op_stats parity with the host ring: payload bytes, the
        # bytes that crossed the device link (the localize fetch when
        # results come back host-backed; keep_global leaves everything on
        # the mesh), and the stack/dispatch/localize phase split. The
        # compiled reduce is async — ``ring`` is its DISPATCH, and the
        # wire wall is absorbed by the blocking localize (``h2d``) or the
        # caller's next use under keep_global.
        nbytes = _leaf_bytes(leaves)
        self._record_op_stats({
            "op": "allreduce",
            "backend": "xla",
            "bytes": nbytes,
            "d2h_bytes": 0 if self._keep_global else nbytes,
            "pack": t1 - t0,
            "ring": t2 - t1,
            "h2d": time.perf_counter() - t2,
        })
        return _unflatten(treedef, out)

    def allgather(self, tree: Any) -> Work:
        return self._submit(lambda: self._allgather_sync(tree))

    def _allgather_sync(self, tree: Any) -> List[Any]:
        if self._world_size == 1:
            return [tree]
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        leaves, treedef = _flatten(tree)
        if not leaves:
            return [tree] * self._world_size
        t0 = time.perf_counter()
        stacked = self._stack_global(leaves)
        key = ("gather", len(leaves))
        fn = self._jit_cache.get(key)
        if fn is None:
            replicated = NamedSharding(self._mesh, P())
            fn = self._jit_cache[key] = jax.jit(
                lambda ls: [l + 0 for l in ls],
                out_shardings=[replicated] * len(leaves),
            )
        gathered = fn(stacked)  # (world, *shape), replicated everywhere
        if self._keep_global:
            # Slice on the global mesh so rows keep the no-host-hop
            # contract (same as allreduce/broadcast in this mode).
            skey = ("gather_rows", len(leaves))
            row_fn = self._jit_cache.get(skey)
            if row_fn is None:
                replicated = NamedSharding(self._mesh, P())
                world = self._world_size
                row_fn = self._jit_cache[skey] = jax.jit(
                    lambda ls: [[l[r] for l in ls] for r in range(world)],
                    out_shardings=[[replicated] * len(leaves)]
                    * self._world_size,
                )
            out = [
                _unflatten(treedef, rows) for rows in row_fn(gathered)
            ]
            # parity contract: every op drains through pop_op_stats,
            # keep_global included (nothing crossed the device link)
            self._record_op_stats({
                "op": "allgather",
                "backend": "xla",
                "bytes": _leaf_bytes(leaves),
                "d2h_bytes": 0,
                "pack": time.perf_counter() - t0,
            })
            return out
        t1 = time.perf_counter()
        host = [np.asarray(g) for g in gathered]
        out = [
            _unflatten(treedef, self._localize([h[r] for h in host]))
            for r in range(self._world_size)
        ]
        nbytes = _leaf_bytes(leaves)
        self._record_op_stats({
            "op": "allgather",
            "backend": "xla",
            "bytes": nbytes,
            # every member's row comes back through the host fetch
            "d2h_bytes": nbytes * self._world_size,
            "pack": t1 - t0,
            "h2d": time.perf_counter() - t1,
        })
        return out

    def broadcast(self, tree: Any, root: int = 0) -> Work:
        return self._submit(lambda: self._broadcast_sync(tree, root))

    def _broadcast_sync(self, tree: Any, root: int) -> Any:
        if self._world_size == 1:
            if root != 0:
                raise RuntimeError(f"bad broadcast root {root} for world size 1")
            return tree
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        stacked = self._stack_global(leaves)
        key = ("bcast", len(leaves), root)
        fn = self._jit_cache.get(key)
        if fn is None:
            replicated = NamedSharding(self._mesh, P())
            fn = self._jit_cache[key] = jax.jit(
                lambda ls: [l[root] for l in ls],
                out_shardings=[replicated] * len(leaves),
            )
        t0 = time.perf_counter()
        out = _unflatten(treedef, self._localize(fn(stacked)))
        nbytes = _leaf_bytes(leaves)
        self._record_op_stats({
            "op": "broadcast",
            "backend": "xla",
            "bytes": nbytes,
            "d2h_bytes": 0 if self._keep_global else nbytes,
            "h2d": time.perf_counter() - t0,
        })
        return out

    def barrier(self) -> Work:
        import jax.numpy as jnp

        return self._submit(
            lambda: self._allreduce_sync(
                jnp.zeros((1,), jnp.float32), ReduceOp.SUM
            )
        )
