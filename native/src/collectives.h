// Host-side collective communication over TCP: the role Gloo plays in the
// reference (reference torchft/process_group.py:282-296 ProcessGroupGloo and
// the reconfigure discipline of process_group.py:238-254).
//
// Design for the TPU build: cross-replica-group traffic stays OUTSIDE XLA
// (host-side sockets), so a dead peer surfaces as a socket error on an
// abortable fd instead of a wedged ICI collective — the property the
// reference gets from subprocess-isolated NCCL ("Baby" PGs,
// process_group.py:551-1064). Intra-group collectives are XLA's job (pjit
// over the slice mesh); this class only ever spans replica groups.
//
// Topology: a ring. configure() rendezvouses through the Store (the caller
// passes "host:port/prefix" where prefix is unique per quorum, mirroring
// manager.py:470-477), each rank listens on an ephemeral port, connects to
// rank+1 and accepts from rank-1. Ring allreduce = reduce-scatter +
// allgather; each chunk is reduced in the same rank order on every
// participant, so results are bit-identical across ranks and across runs —
// the determinism oracle the reference tests demand
// (manager_integ_test.py:279-282).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>

#include "net.h"

namespace tft {

enum class ReduceOp : int {
  kSum = 0,
  kProduct = 1,
  kMin = 2,
  kMax = 3,
};

enum class Dtype : int {
  kF32 = 0,
  kF64 = 1,
  kI32 = 2,
  kI64 = 3,
  // bfloat16 ships natively (2 bytes on the wire — half the DCN traffic of
  // an f32 upcast); reduction arithmetic is f32 per hop with
  // round-to-nearest-even back to bf16.
  kBF16 = 4,
};

size_t dtype_size(Dtype d);

class HostCollectives {
 public:
  HostCollectives() = default;
  ~HostCollectives();

  // Rebuilds the ring for a (possibly new) membership. store_addr is
  // "host:port/prefix"; the prefix must be unique per quorum — stale members
  // of an old quorum never see the new keys, so they cannot cross-talk
  // (reference manager.py:470-477 store-prefix discipline). Aborts any
  // in-flight op first.
  void configure(const std::string& store_addr, int64_t rank, int64_t world_size,
                 int64_t timeout_ms);

  // In-place ring allreduce over `count` elements of `data`.
  void allreduce(void* data, size_t count, Dtype dtype, ReduceOp op,
                 int64_t timeout_ms);

  // In-place QUANTIZED ring SUM over `count` f32 elements: every hop
  // ships each chunk as [f32 absmax/127 scale][int8 payload] and the
  // receiver dequantize-accumulates into its f32 buffer (the same
  // f32-accumulator discipline the bf16 path uses). Phase 2 circulates
  // the owner-quantized reduced chunks verbatim, so wire bytes per
  // member are ~2x the int8 payload REGARDLESS of world size — unlike a
  // quantized allgather, whose traffic grows O(world). Per-hop
  // requantization of partial sums keeps relative error at the int8
  // quantization class (~1/127 of each chunk's absmax).
  void allreduce_q8(float* data, size_t count, int64_t timeout_ms);
  // Gathers `nbytes` from every rank into `out` (world_size * nbytes), in
  // rank order.
  void allgather(const void* in, void* out, size_t nbytes, int64_t timeout_ms);
  // Broadcasts `nbytes` of `data` from `root` to all ranks, in place.
  void broadcast(void* data, size_t nbytes, int64_t root, int64_t timeout_ms);
  void barrier(int64_t timeout_ms);

  int64_t rank() const { return rank_; }
  int64_t world_size() const { return world_size_; }

  // Wakes any thread blocked inside an op with a SocketError; the instance
  // stays usable via a subsequent configure(). Safe to call from any thread.
  void abort();

 private:
  // Sends send_len bytes to next_ while concurrently receiving recv_len
  // bytes from prev_ (full-duplex pump; one-directional blocking would
  // deadlock once kernel buffers fill on a large ring step).
  void duplex(const char* send_buf, size_t send_len, char* recv_buf,
              size_t recv_len, int64_t deadline_ms);

  // Exchanges a tiny (kind, count, dtype, op) header with both neighbors
  // before a collective and throws on mismatch — a size/dtype-mismatched
  // op would otherwise deadlock silently once kernel buffers fill.
  void check_op_header(uint32_t kind, uint64_t count, uint32_t dtype,
                       uint32_t op, int64_t deadline_ms);

  // Runs an op body; on ANY failure shuts down both ring sockets before
  // rethrowing. The FIN propagates the failure around the ring: every
  // member's in-flight op fails within milliseconds instead of blocking on
  // its timeout while a majority of survivors can't reach the next quorum —
  // the distributed analog of NCCL's abort-on-error. The dead ring stays
  // dead (ops throw immediately) until the next configure().
  template <typename Fn>
  void run_op(Fn&& fn) {
    try {
      fn();
    } catch (...) {
      std::lock_guard<std::mutex> lock(cfg_mu_);
      next_.shutdown_rdwr();
      prev_.shutdown_rdwr();
      aborted_ = true;
      throw;
    }
  }

  // Guards socket object identity (swap/close) against concurrent abort.
  // Never held across blocking IO, so abort() always runs promptly.
  std::mutex cfg_mu_;
  // Serializes collective ops (they share the ring sockets and must issue in
  // the same order on every rank anyway).
  std::mutex op_mu_;

  int64_t rank_ = -1;
  int64_t world_size_ = 0;
  std::unique_ptr<Listener> listener_;
  Socket next_;
  Socket prev_;
  std::atomic<bool> aborted_{true}; // not configured yet
  // Bumped by every abort(); configure() uses it to detect an abort that
  // raced with its (lock-free) rendezvous phase.
  std::atomic<int64_t> abort_epoch_{0};
};

} // namespace tft
