"""Managed-collective latch discipline in torchft_tpu/manager.py.

The per-step fault-tolerance contract (PAPER.md): data-plane errors must
NEVER raise into the train loop — they latch, the op resolves to its
documented default, and ``should_commit`` discards the step. The rule
checks the two halves statically:

- every ``Manager`` method that touches a managed collective op
  (``self._collectives.allreduce`` etc. — the isolated data plane
  ``self._iso_collectives`` included) must route through
  ``_managed_dispatch`` and may only ``raise ValueError`` (the eager
  static-usage errors the docstrings carve out) — no bare ``raise``, no
  other exception types on the managed path. Raises inside nested
  functions are exempt: the dispatch closure executes under
  ``_managed_dispatch``'s try, so raising there IS latching (the
  ``iso_allreduce`` unusable-plane RuntimeError rides this);
- ``_managed_dispatch`` itself must keep the latch: a ``try`` whose
  handler calls ``self.report_error`` and contains no ``raise``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from . import Violation, relpath

RULE = "latch_discipline"

MANAGER_PY = Path("torchft_tpu/manager.py")

# The managed data-plane surface. Anything new that dispatches to one of
# these from Manager must adopt the same discipline (or extend this rule).
MANAGED_OPS = {
    "allreduce",
    "plan_allreduce",
    "allreduce_hier",
    "reduce_scatter",
    "allgather_into",
    "allgather",
    "plan_reduce_scatter",
    "plan_allgather_into",
}
# Both data planes carry the discipline: the primary backend and the
# disposable-child isolated one.
RECEIVERS = ("_collectives", "_iso_collectives")
DISPATCH = "_managed_dispatch"
LATCH = "report_error"


def _touches_managed_op(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Attribute)
            and node.attr in MANAGED_OPS
            and isinstance(node.value, ast.Attribute)
            and node.value.attr in RECEIVERS
            and isinstance(node.value.value, ast.Name)
            and node.value.value.id == "self"
        ):
            return True
    return False


def _walk_outside_closures(node: ast.AST):
    """ast.walk that does not descend into nested function bodies — code
    there runs under the dispatch latch, not on the caller's path."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _walk_outside_closures(child)


def _calls_self_method(fn: ast.FunctionDef, method: str) -> bool:
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == method
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        ):
            return True
    return False


def _raise_is_value_error(node: ast.Raise) -> bool:
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    return isinstance(exc, ast.Name) and exc.id == "ValueError"


def _check_dispatch(fn: ast.FunctionDef, rel: str) -> List[Violation]:
    out: List[Violation] = []
    latching_handler = False
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            calls_latch = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == LATCH
                for n in ast.walk(handler)
            )
            reraises = any(
                isinstance(n, ast.Raise) for n in ast.walk(handler)
            )
            if calls_latch and not reraises:
                latching_handler = True
            elif reraises:
                out.append(
                    Violation(
                        RULE,
                        rel,
                        handler.lineno,
                        f"{DISPATCH} exception handler re-raises: managed "
                        "failures must latch via report_error, not "
                        "propagate",
                    )
                )
    if not latching_handler:
        out.append(
            Violation(
                RULE,
                rel,
                fn.lineno,
                f"{DISPATCH} has no exception handler that latches via "
                f"self.{LATCH}",
            )
        )
    return out


def check(root: Path, manager_path: Optional[Path] = None) -> List[Violation]:
    path = manager_path or root / MANAGER_PY
    rel = relpath(root, path)
    tree = ast.parse(path.read_text())
    out: List[Violation] = []

    manager = next(
        (
            n
            for n in tree.body
            if isinstance(n, ast.ClassDef) and n.name == "Manager"
        ),
        None,
    )
    if manager is None:
        return [Violation(RULE, rel, 1, "no Manager class found")]

    saw_dispatch = False
    for fn in manager.body:
        if not isinstance(fn, ast.FunctionDef):
            continue
        if fn.name == DISPATCH:
            saw_dispatch = True
            out.extend(_check_dispatch(fn, rel))
            continue
        if not _touches_managed_op(fn):
            continue
        if not _calls_self_method(fn, DISPATCH):
            out.append(
                Violation(
                    RULE,
                    rel,
                    fn.lineno,
                    f"Manager.{fn.name} touches a managed collective but "
                    f"does not route through {DISPATCH} (failure -> None/"
                    "default + latch -> vote-discard)",
                )
            )
        for node in _walk_outside_closures(fn):
            if isinstance(node, ast.Raise):
                if node.exc is None:
                    out.append(
                        Violation(
                            RULE,
                            rel,
                            node.lineno,
                            f"Manager.{fn.name} bare re-raise on the "
                            "managed path (errors must latch, not "
                            "propagate)",
                        )
                    )
                elif not _raise_is_value_error(node):
                    out.append(
                        Violation(
                            RULE,
                            rel,
                            node.lineno,
                            f"Manager.{fn.name} raises a non-ValueError "
                            "on the managed path (only eager static-usage "
                            "ValueErrors may raise; data-plane failures "
                            "latch)",
                        )
                    )
    if not saw_dispatch:
        out.append(
            Violation(
                RULE, rel, manager.lineno, f"Manager has no {DISPATCH}"
            )
        )
    return out
