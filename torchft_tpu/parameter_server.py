"""Fault-tolerant parameter server, rebuilt on the serving plane.

Reference: torchft/parameter_server.py:31-195 — the world-size-2
prototype where ``GET /new_session`` mints a uuid-prefixed store
namespace and hijacks the handler thread to run
``forward(session_id, collectives)`` over a 2-member ring. That session
API is kept VERBATIM as a thin compat shim, but the HTTP listener is now
a :class:`torchft_tpu.serving.ServingServer`: the same port also serves
the ``/ps/*`` pub/sub weight-distribution surface (zero-copy versioned
ranges, leases, staleness-bounded reads) through an owned
:class:`~torchft_tpu.serving.WeightPublisher` — ``publish()`` hands a
weight tree to thousands of subscribers while legacy clients keep
opening 2-world sessions against ``/new_session``.

Addressing: peers may not resolve this machine's bare hostname, so all
advertised URLs honor env ``TORCHFT_PS_HOST`` (falling back to the
hostname) via :func:`torchft_tpu.serving.advertise_host`.
"""

from __future__ import annotations

import json
import logging
import urllib.request
import uuid
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Optional

from . import _native
from .collectives import Collectives
from .serving import WeightPublisher, advertise_host, _url_host

logger: logging.Logger = logging.getLogger(__name__)


class ParameterServer(ABC):
    """Threaded parameter server over the reconfigurable collectives,
    fronted by the serving plane's HTTP listener."""

    def __init__(
        self,
        port: int = 0,
        wire: Optional[str] = None,
        snapshot_every: Optional[int] = None,
        keep: Optional[int] = None,
    ) -> None:
        self.store = _native.Store()
        # The serving tier owns the listener; /new_session rides it as
        # the compat shim. The publisher starts empty — /ps/* answers
        # (latest = -1) even before the first publish.
        self.publisher = WeightPublisher(
            port=port,
            wire=wire,
            snapshot_every=snapshot_every,
            keep=keep,
            extra_get=self._handle_legacy_get,
        )
        self._server = self.publisher.server
        logger.info(f"Started ParameterServer on {self.address()}...")

    def _handle_legacy_get(
        self, handler: BaseHTTPRequestHandler, path: str
    ) -> bool:
        """The pre-serving session API: consumes ``/new_session`` and
        leaves every other path (the /ps/* surface) to the serving
        router. Runs ON the handler thread — the session hijacks it
        exactly as before (reference parameter_server.py:91-97)."""
        if path.split("?")[0] != "/new_session":
            return False
        try:
            session_id = str(uuid.uuid4())
            store_addr = f"{self.store.address()}/session/{session_id}"
            logger.info(f"creating new session {session_id}")

            data = (
                json.dumps(
                    {"session_id": session_id, "store_addr": store_addr}
                )
                + "\n"
            ).encode()
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
            # Close eagerly so the client knows the JSON is complete,
            # then hijack this handler thread for the session
            # (reference parameter_server.py:91-97).
            handler.finish()
            handler.connection.close()

            self._handle_session(session_id, store_addr)
        except Exception:
            logger.exception(
                f"got exception in request handler for {path}"
            )
            raise
        return True

    def address(self) -> str:
        """HTTP address for creating sessions:
        ``http://host:port/new_session``. The host honors env
        ``TORCHFT_PS_HOST`` (peers may not resolve the bare hostname);
        IPv6 literals are bracketed."""
        port = self._server.port
        return f"http://{_url_host(advertise_host())}:{port}/new_session"

    def serving_address(self) -> str:
        """Base URL of the pub/sub serving surface (``/ps/*``) — what
        relays and subscribers dial."""
        return self._server.address()

    def publish(self, params: Any, step: Optional[int] = None) -> Dict[str, Any]:
        """Publish one weight version into the serving plane (see
        :meth:`torchft_tpu.serving.WeightPublisher.publish`)."""
        return self.publisher.publish(params, step=step)

    def shutdown(self) -> None:
        self.publisher.shutdown()
        self.store.shutdown()

    @classmethod
    @abstractmethod
    def new_collectives(cls) -> Collectives:
        """A fresh, unconfigured Collectives for one session (both sides)."""

    @classmethod
    def new_session(cls, address: str) -> Collectives:
        """Client side: opens a session, returns collectives configured with
        the server (server rank 0, client rank 1)."""
        with urllib.request.urlopen(address) as f:
            data = json.load(f)
        session_id = data["session_id"]
        store_addr = data["store_addr"]
        logger.info(f"connecting to session {session_id} at {store_addr}")

        collectives = cls.new_collectives()
        collectives.configure(store_addr, rank=1, world_size=2)
        return collectives

    def _handle_session(self, session_id: str, store_addr: str) -> None:
        collectives = self.new_collectives()
        try:
            collectives.configure(store_addr, rank=0, world_size=2)
            self.forward(session_id, collectives)
        finally:
            # A finished or failed session frees its collectives (ring
            # sockets + op thread) immediately, not at GC time.
            collectives.shutdown()

    @abstractmethod
    def forward(self, session_id: str, collectives: Collectives) -> None:
        """Runs once per session on a dedicated thread; loop inside for
        multiple operations. Errors free the collectives — the client then
        opens a new session (reference parameter_server.py:177-195)."""
