"""Ring attention (context parallelism) tests on the virtual 8-device CPU
mesh: numerical equivalence with dense causal attention, differentiability,
and composition with data- and tensor-parallel axes in one mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import HAS_SHARD_MAP, SHARD_MAP_SKIP

if not HAS_SHARD_MAP:
    # context_parallel imports jax.shard_map at module load, so the guard
    # must run before the import or collection itself errors.
    pytest.skip(SHARD_MAP_SKIP, allow_module_level=True)

from torchft_tpu.context_parallel import ring_attention
from torchft_tpu.parallel import make_mesh


def _dense_causal(q, k, v):
    """Reference: full-materialization causal attention, f32."""
    Dh = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (Dh ** -0.5)
    S = q.shape[1]
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _qkv(key, B=2, S=32, H=4, Dh=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (B, S, H, Dh)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


class TestRingAttention:
    def test_matches_dense_seq_only(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(0))
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="seq",
                             batch_axis=None)
        ref = _dense_causal(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_matches_dense_dp_x_seq_x_tp(self):
        # The composition claim: batch over "data", sequence ring over
        # "seq", heads over "model" — one mesh, one op.
        mesh = make_mesh({"data": 2, "seq": 2, "model": 2},
                         devices=jax.devices()[:8])
        q, k, v = _qkv(jax.random.PRNGKey(1), B=4, S=16, H=4)
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="seq",
                             batch_axis="data", head_axis="model")
        ref = _dense_causal(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_non_causal(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(2))
        out = ring_attention(q, k, v, mesh=mesh, batch_axis=None,
                             causal=False)
        Dh = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (Dh ** -0.5)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_flow_through_ring(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(3))

        def loss_ring(qkv):
            out = ring_attention(*qkv, mesh=mesh, batch_axis=None)
            return jnp.sum(out ** 2)

        def loss_dense(qkv):
            return jnp.sum(_dense_causal(*qkv) ** 2)

        g_ring = jax.grad(loss_ring)((q, k, v))
        g_dense = jax.grad(loss_dense)((q, k, v))
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       rtol=2e-4, atol=2e-4)

    def test_inside_jit(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(4))
        f = jax.jit(lambda a, b, c: ring_attention(
            a, b, c, mesh=mesh, batch_axis=None))
        np.testing.assert_allclose(np.asarray(f(q, k, v)),
                                   np.asarray(_dense_causal(q, k, v)),
                                   rtol=2e-5, atol=2e-5)

    def test_uneven_sequence_rejected(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(5), S=30)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh=mesh, batch_axis=None)

    def test_bf16_inputs(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(6), dtype=jnp.bfloat16)
        out = ring_attention(q, k, v, mesh=mesh, batch_axis=None)
        assert out.dtype == jnp.bfloat16
        ref = _dense_causal(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=0.05, atol=0.05,
        )


class TestUlyssesAttention:
    """Ulysses: all-to-all seq<->heads around full-sequence attention
    (flash or dense per device)."""

    def test_matches_dense_seq_only(self):
        from torchft_tpu.context_parallel import ulysses_attention

        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(0))
        for use_flash in (False, True):
            out = ulysses_attention(
                q, k, v, mesh=mesh, seq_axis="seq", batch_axis=None,
                use_flash=use_flash,
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(_dense_causal(q, k, v)),
                rtol=2e-5, atol=2e-5, err_msg=f"use_flash={use_flash}",
            )

    def test_matches_dense_dp_x_seq_x_tp(self):
        from torchft_tpu.context_parallel import ulysses_attention

        # H=4 over model:2 -> 2 local heads; seq:2 needs 2 | 2 ok
        mesh = make_mesh({"data": 2, "seq": 2, "model": 2},
                         devices=jax.devices()[:8])
        q, k, v = _qkv(jax.random.PRNGKey(1), B=4, S=16, H=4)
        out = ulysses_attention(q, k, v, mesh=mesh, seq_axis="seq",
                                batch_axis="data", head_axis="model")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense_causal(q, k, v)),
            rtol=2e-5, atol=2e-5,
        )

    def test_grads_match_dense(self):
        from torchft_tpu.context_parallel import ulysses_attention

        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(jax.random.PRNGKey(3))

        def loss_u(qkv):
            out = ulysses_attention(*qkv, mesh=mesh, batch_axis=None)
            return jnp.sum(out ** 2)

        def loss_dense(qkv):
            return jnp.sum(_dense_causal(*qkv) ** 2)

        g_u = jax.grad(loss_u)((q, k, v))
        g_d = jax.grad(loss_dense)((q, k, v))
        for a, b in zip(g_u, g_d):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_too_few_heads_rejected(self):
        from torchft_tpu.context_parallel import ulysses_attention

        mesh = make_mesh({"seq": 8})
        q, k, v = _qkv(jax.random.PRNGKey(5), H=4)  # 4 heads < seq:8
        with pytest.raises(ValueError, match="head count"):
            ulysses_attention(q, k, v, mesh=mesh, batch_axis=None)

    def test_transformer_strategy_switch(self):
        import dataclasses

        from torchft_tpu.models import init_params, loss_fn, tiny_config

        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        cfg_ring = dataclasses.replace(
            tiny_config(), cp_seq_axis="seq", cp_mesh=mesh,
            cp_batch_axis=None,
        )
        cfg_uly = dataclasses.replace(cfg_ring, cp_strategy="ulysses")
        params = init_params(cfg_ring, jax.random.PRNGKey(0))
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg_ring.vocab_size, (2, 33)),
            jnp.int32,
        )
        l_ring = loss_fn(cfg_ring, params, tokens)
        l_uly = loss_fn(cfg_uly, params, tokens)
        np.testing.assert_allclose(float(l_uly), float(l_ring),
                                   rtol=1e-4, atol=1e-4)
